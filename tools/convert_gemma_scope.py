"""Convert a Gemma-Scope SAE release to the framework's npz schema.

The reference gets the SAE via ``sae_lens.SAE.from_pretrained(
"google/gemma-scope-9b-it-res", "layer_31/width_16k/average_l0_76")``
(reference src/02_run_sae_baseline.py:30-36).  This host has no hub egress and
no sae_lens, so the on-ramp is a converter over whatever local form of the
release exists:

    python tools/convert_gemma_scope.py SOURCE out.npz [--sae-id layer_31/width_16k/average_l0_76]

SOURCE may be:
- the official release's ``params.npz`` (keys W_enc/W_dec/b_enc/b_dec/threshold);
- a snapshot DIRECTORY of the gemma-scope repo (the ``<sae_id>/params.npz``
  inside is located automatically);
- a torch ``.pt``/``.bin`` state dict (sae_lens layout, same key names);
- a ``.safetensors`` file with those keys.

Output: ``np.savez(out, W_enc, b_enc, W_dec, b_dec, threshold)`` — exactly what
``ops/sae.py:load`` consumes.  Shapes are validated against the JumpReLU layout
(W_enc [d_model, d_sae], W_dec [d_sae, d_model]); an encoder stored transposed
is fixed automatically using the bias lengths as ground truth.

Grid mode (``--cells``) converts an explicit list of (layer, width) cells in
one pass for ``taboo_brittleness_tpu.grid``:

    python tools/convert_gemma_scope.py SNAPSHOT_DIR out_dir \\
        --cells "20:16384,31:16384,31:131072:layer_31/width_128k/average_l0_73"

Each entry is ``layer:width`` or ``layer:width:sae_id``; without an explicit
sae_id the converter resolves ``layer_<L>/width_<tag>/canonical`` to the single
``average_l0_*`` leaf present in the snapshot.  OUT becomes a directory holding
one ``<cell-key>.npz`` per cell (``L<layer>-W<tag>.npz`` — exactly the layout
``grid.spec.GridSpec.build(artifact_dir=...)`` points at), each carrying a
versioned header (``__grid_version__``/``__sae_id__``/``__layer__``/
``__width__``) next to the weight arrays; ``grid.spec.load_cell_sae``
validates that header before trusting the file.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List, Optional

import numpy as np

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

CANONICAL_KEYS = ("W_enc", "b_enc", "W_dec", "b_dec", "threshold")
_ALIASES = {
    "W_enc": ("W_enc", "w_enc", "encoder.weight"),
    "b_enc": ("b_enc", "encoder.bias"),
    "W_dec": ("W_dec", "w_dec", "decoder.weight"),
    "b_dec": ("b_dec", "decoder.bias"),
    "threshold": ("threshold", "log_threshold"),
}


def load_state(source: str, sae_id: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Read raw arrays from any supported SOURCE form."""
    if os.path.isdir(source):
        found = [os.path.join(dirpath, f)
                 for dirpath, _dirs, files in os.walk(source)
                 for f in files if f == "params.npz"]
        if sae_id:
            # Exactly the requested SAE — a walk-order fallback would silently
            # convert a different layer/width and poison every downstream run.
            want = os.path.join(source, sae_id, "params.npz")
            if os.path.exists(want):
                return load_state(want)
            have = [os.path.relpath(os.path.dirname(p), source) for p in found]
            raise FileNotFoundError(
                f"{want} not found; params.npz present for: {have or 'none'}")
        if len(found) == 1:
            return load_state(found[0])
        if not found:
            raise FileNotFoundError(f"no params.npz under {source}")
        raise FileNotFoundError(
            f"multiple SAEs under {source} "
            f"({[os.path.relpath(os.path.dirname(p), source) for p in found]}); "
            "pass --sae-id to pick one")

    if source.endswith(".npz"):
        with np.load(source) as data:
            return {k: np.asarray(data[k]) for k in data.files}
    if source.endswith(".safetensors"):
        from safetensors import safe_open

        with safe_open(source, framework="numpy") as f:
            return {k: f.get_tensor(k) for k in f.keys()}
    if source.endswith((".pt", ".bin", ".pth")):
        import torch

        sd = torch.load(source, map_location="cpu", weights_only=True)
        sd = sd.get("state_dict", sd)
        return {k: v.detach().float().numpy() for k, v in sd.items()}
    raise ValueError(f"unsupported SOURCE {source!r} "
                     "(expected dir, .npz, .safetensors, .pt/.bin)")


def canonicalize(raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Map aliases to canonical keys, fix transposes, validate the layout."""
    out: Dict[str, np.ndarray] = {}
    for key, aliases in _ALIASES.items():
        for a in aliases:
            if a in raw:
                arr = np.asarray(raw[a], np.float32)
                if key == "threshold" and a == "log_threshold":
                    arr = np.exp(arr)  # sae_lens stores log-space thresholds
                out[key] = arr
                break
        else:
            raise KeyError(f"missing {key} (tried {aliases}; have {sorted(raw)})")

    d_model, d_sae = out["b_dec"].shape[0], out["b_enc"].shape[0]
    if out["W_enc"].shape == (d_sae, d_model) and d_sae != d_model:
        out["W_enc"] = out["W_enc"].T
    if out["W_dec"].shape == (d_model, d_sae) and d_sae != d_model:
        out["W_dec"] = out["W_dec"].T

    expect = {"W_enc": (d_model, d_sae), "b_enc": (d_sae,),
              "W_dec": (d_sae, d_model), "b_dec": (d_model,),
              "threshold": (d_sae,)}
    for k, shape in expect.items():
        if out[k].shape != shape:
            raise ValueError(f"{k} has shape {out[k].shape}, expected {shape} "
                             f"(d_model={d_model}, d_sae={d_sae})")
    return out


def convert(source: str, out_path: str, sae_id: Optional[str] = None) -> Dict[str, np.ndarray]:
    state = canonicalize(load_state(source, sae_id))
    # Round-trip through the runtime loader so what we wrote is what loads.
    from taboo_brittleness_tpu.ops import sae as sae_ops

    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    np.savez(out_path, **state)
    loaded = sae_ops.load(out_path)
    assert loaded.d_model == state["b_dec"].shape[0]
    assert loaded.d_sae == state["b_enc"].shape[0]
    return state


def parse_cells(text: str) -> List[tuple]:
    """``"20:16384,31:16384:layer_31/width_16k/average_l0_76"`` ->
    ``[(20, 16384, None), (31, 16384, "layer_31/...")]``."""
    cells = []
    for entry in text.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":", 2)
        if len(parts) < 2:
            raise ValueError(
                f"bad --cells entry {entry!r} (want layer:width[:sae_id])")
        try:
            layer, width = int(parts[0]), int(parts[1])
        except ValueError:
            raise ValueError(
                f"bad --cells entry {entry!r} (layer/width must be ints)")
        cells.append((layer, width, parts[2] if len(parts) == 3 else None))
    if not cells:
        raise ValueError("--cells parsed to an empty list")
    return cells


def _resolve_sae_id(source: str, sae_id: str) -> str:
    """Resolve a ``.../canonical`` sae_id against a snapshot dir: the release
    names leaves ``average_l0_<x>`` with per-cell x, so ``canonical`` means
    "the single leaf that exists under layer_<L>/width_<tag>/"."""
    if not sae_id.endswith("/canonical") or not os.path.isdir(source):
        return sae_id
    base_rel = os.path.dirname(sae_id)
    base = os.path.join(source, base_rel)
    leaves = sorted(
        d for d in (os.listdir(base) if os.path.isdir(base) else [])
        if os.path.exists(os.path.join(base, d, "params.npz")))
    if len(leaves) == 1:
        return f"{base_rel}/{leaves[0]}"
    raise FileNotFoundError(
        f"cannot resolve {sae_id!r} under {source}: "
        f"{'no' if not leaves else 'multiple'} params.npz leaves "
        f"({leaves or 'none'}); pass layer:width:sae_id explicitly")


def convert_cell(source: str, out_dir: str, layer: int, width: int,
                 sae_id: Optional[str] = None) -> str:
    """Convert one grid cell to ``<out_dir>/<cell-key>.npz`` with the
    versioned header ``grid.spec.load_cell_sae`` validates.  Returns the
    written path."""
    from taboo_brittleness_tpu.grid import spec as grid_spec

    sid = _resolve_sae_id(
        source, sae_id or grid_spec.default_sae_id(layer, width))
    state = canonicalize(
        load_state(source, sid if os.path.isdir(source) else None))
    d_sae = state["b_enc"].shape[0]
    if d_sae != int(width):
        raise ValueError(
            f"cell {layer}:{width}: source {sid!r} has d_sae={d_sae}, "
            f"not {width} — wrong width folder?")
    cell = grid_spec.CellSpec(layer=int(layer), width=int(width), sae_id=sid)
    out_path = os.path.join(out_dir, f"{cell.key}.npz")
    os.makedirs(out_dir, exist_ok=True)
    np.savez(out_path, **state,
             __grid_version__=np.int64(grid_spec.GRID_ARTIFACT_VERSION),
             __sae_id__=np.asarray(sid),
             __layer__=np.int64(layer), __width__=np.int64(width))
    # Round-trip through the grid loader so what we wrote is what a fleet
    # worker will accept (header AND weights).
    import dataclasses as _dc
    loaded = grid_spec.load_cell_sae(_dc.replace(cell, path=out_path))
    assert loaded.d_sae == int(width)
    return out_path


def convert_cells(source: str, out_dir: str,
                  cells: List[tuple]) -> List[str]:
    return [convert_cell(source, out_dir, la, w, sid)
            for la, w, sid in cells]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("source", help="params.npz / snapshot dir / .pt / .safetensors")
    ap.add_argument("out", help="output npz path (a directory with --cells)")
    ap.add_argument("--sae-id", default="layer_31/width_16k/average_l0_76",
                    help="release subfolder when SOURCE is a snapshot dir")
    ap.add_argument("--cells", default=None,
                    help="comma-separated layer:width[:sae_id] grid cells; "
                         "OUT becomes a directory of <cell-key>.npz artifacts")
    args = ap.parse_args(argv)
    try:
        if args.cells:
            paths = convert_cells(args.source, args.out,
                                  parse_cells(args.cells))
            for p in paths:
                print(f"OK: wrote {p}")
            return 0
        state = convert(args.source, args.out, args.sae_id)
    except (FileNotFoundError, KeyError, ValueError) as e:
        print(f"FAILED: {e}")
        return 1
    print(f"OK: wrote {args.out} "
          f"(d_model={state['b_dec'].shape[0]}, d_sae={state['b_enc'].shape[0]})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
