#!/usr/bin/env python
"""Regenerate the committed device-profile fixture under
``tests/fixtures/obs/device/``: a REAL ``TBX_PROFILE=1`` capture of a
CPU-backend tiny-model intervention sweep (2 words), committed as

- ``_events.jsonl``        — the sweep's span stream
- ``trace.json.gz``        — the raw Perfetto trace the profiler emitted
- ``_device_profile.json`` — the parsed artifact (obs/profile.py)

``tools/check.sh`` holds ``trace_report --check --device`` green over this
directory, and tests/test_profile.py re-parses ``trace.json.gz`` and asserts
the parser reproduces the committed artifact — so neither the artifact
schema nor the trace parser can drift silently.

The sweep runs under ``TBX_FUSED=1`` (override with ``TBX_FUSED=0``): every
study launch is one FUSED program carrying the multi-phase in-graph phase
table (runtime/fused.py), so the committed fixture holds the join cascade's
acceptance of a single launch with multiple phase markers — and the
``fused_phase_split`` conservation invariant — green in check.sh.  Legacy
single-phase joins stay covered by tests/test_profile.py's synthetic
timelines and its end-to-end sweep capture.

    JAX_PLATFORMS=cpu python tools/make_device_fixture.py
"""

from __future__ import annotations

import json
import os
import shutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["TBX_PROFILE"] = "1"
os.environ["TBX_PROFILE_WORDS"] = "2"
# No in-flight tail at capture stop: every annotated launch must execute
# inside the window so the committed fixture satisfies the strictest form of
# the join invariant (zero truncated records).
os.environ["TBX_CROSS_WORD_BASELINE"] = "0"
os.environ["TBX_AOT_WARMSTART"] = "off"
os.environ.setdefault("TBX_FUSED", "1")

FIXTURE_DIR = os.path.join(REPO, "tests", "fixtures", "obs", "device")


def main() -> int:
    import tempfile

    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from taboo_brittleness_tpu.config import (
        Config, ExperimentConfig, InterventionConfig, ModelConfig,
        OutputConfig)
    from taboo_brittleness_tpu.models import gemma2
    from taboo_brittleness_tpu.ops import sae as sae_ops
    from taboo_brittleness_tpu.pipelines import interventions as iv
    from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(7), cfg)
    words = ["moon", "ship"]
    tok = WordTokenizer(
        words + ["hint", "clue", "Give", "me", "a", "Another", "please"],
        vocab_size=cfg.vocab_size)
    config = Config(
        model=ModelConfig(layer_idx=2, top_k=3, arch="gemma2_tiny",
                          dtype="float32", param_dtype="float32"),
        experiment=ExperimentConfig(seed=0, max_new_tokens=6),
        intervention=InterventionConfig(budgets=(1, 2), random_trials=1,
                                        ranks=(1,), spike_top_k=2),
        output=OutputConfig(save_plots=False),
        word_plurals={w: [w] for w in words},
        prompts=["Give me a hint", "Another clue please"],
    )
    sae = sae_ops.init_random(jax.random.PRNGKey(3), cfg.hidden_size, 32)

    out_dir = tempfile.mkdtemp(prefix="tbx_device_fixture_")
    try:
        iv.run_intervention_studies(
            config, model_loader=lambda w: (params, cfg, tok), sae=sae,
            words=words, output_dir=out_dir)
        profile_path = os.path.join(out_dir, "_device_profile.json")
        with open(profile_path) as f:
            profile = json.load(f)
        trace_src = profile["capture"]["trace_file"]
        if not os.path.exists(trace_src):
            raise SystemExit("capture produced no trace file — was "
                             "TBX_PROFILE honored?")
        bad = [r for r in profile["programs"]
               if r["slices"] < 1 and not r.get("truncated")]
        if bad:
            raise SystemExit(f"fixture capture left unjoined launches: {bad}")

        os.makedirs(FIXTURE_DIR, exist_ok=True)
        # The committed artifact points at the committed trace by its
        # fixture-relative name, not the temp path of this run.
        profile["capture"]["trace_file"] = "trace.json.gz"
        with open(os.path.join(FIXTURE_DIR, "_device_profile.json"),
                  "w") as f:
            json.dump(profile, f, indent=1, sort_keys=True)
            f.write("\n")
        shutil.copyfile(trace_src,
                        os.path.join(FIXTURE_DIR, "trace.json.gz"))
        shutil.copyfile(os.path.join(out_dir, "_events.jsonl"),
                        os.path.join(FIXTURE_DIR, "_events.jsonl"))
        print(f"fixture -> {FIXTURE_DIR}")
        print(f"  trace.json.gz: "
              f"{os.path.getsize(os.path.join(FIXTURE_DIR, 'trace.json.gz'))}"
              " bytes")
        print(f"  programs: {len(profile['programs'])}, phases: "
              f"{sorted(profile['phases'])}")
        return 0
    finally:
        shutil.rmtree(out_dir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
