#!/usr/bin/env bash
# One gate for the whole repo: lint (ruff, when installed) + tbx-check
# (static TBX rules, then the deep jaxpr audit against the committed
# baseline) + the tier-1 test suite.  Run from anywhere:
#
#     tools/check.sh
#
# Exit is non-zero if any stage fails; CI and pre-merge run exactly this.
set -euo pipefail
cd "$(dirname "$0")/.."

if command -v ruff >/dev/null 2>&1; then
  echo "== ruff"
  ruff check taboo_brittleness_tpu tools tests
else
  echo "== ruff: not installed; skipping lint (pip install ruff to enable)" >&2
fi

echo "== report sync (exec-summary bench table vs BENCH_r*.json)"
python tools/report_bench_row.py --check reports/exec_summary/executive_summary.md

echo "== bench regression sentinel (latest BENCH_r*.json vs predecessor)"
python tools/bench_compare.py --check

echo "== trace_report schema gate (committed obs fixture)"
python tools/trace_report.py --check tests/fixtures/obs/_events.jsonl

echo "== trace_report device-join gate (committed device-profile fixture)"
python tools/trace_report.py tests/fixtures/obs/device/_events.jsonl \
  --check --device

echo "== trace_report fleet gate (committed multi-worker fixture)"
python tools/trace_report.py --check tests/fixtures/obs/fleet/_events.jsonl

echo "== tbx top selfcheck (render the committed fleet fixture)"
JAX_PLATFORMS=cpu python -m taboo_brittleness_tpu top --once --selfcheck

echo "== serve loadgen selfcheck (CPU smoke: tiny model, 32 requests)"
JAX_PLATFORMS=cpu python -m taboo_brittleness_tpu loadgen --selfcheck

echo "== fleet selfcheck (chaos smoke: 3 tiny workers, one killed mid-word)"
JAX_PLATFORMS=cpu python -m taboo_brittleness_tpu fleet --selfcheck

echo "== delta-pack selfcheck (pack/apply bit-exactness on the tiny model)"
JAX_PLATFORMS=cpu python -m taboo_brittleness_tpu delta-pack --selfcheck

echo "== grid selfcheck (chaos smoke: 2x2 grid x 2 words, one faulted cell)"
JAX_PLATFORMS=cpu python -m taboo_brittleness_tpu grid --selfcheck

echo "== tbx-check (static + deep; baseline tools/tbx_baseline.json)"
JAX_PLATFORMS=cpu python -m taboo_brittleness_tpu.analysis \
  --deep --baseline tools/tbx_baseline.json \
  taboo_brittleness_tpu/ tools/ tests/

echo "== tier-1 pytest"
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider
