#!/usr/bin/env bash
# One gate for the whole repo.  Run from anywhere:
#
#     tools/check.sh          # every gate: lint, selfchecks, tbx-check
#                             # (static + deep + conc), tier-1 pytest
#     tools/check.sh --fast   # static-only loop: ruff + tbx-check
#                             # (static/deep/conc vs baseline) + the three
#                             # trace_report fixture gates; no pytest
#
# Every gate RUNS even after an earlier one fails; the per-gate PASS/FAIL
# table at exit shows the whole board, and the exit code is non-zero if
# any gate failed.  CI and pre-merge run the full mode exactly.
set -uo pipefail
cd "$(dirname "$0")/.."

FAST=0
for arg in "$@"; do
  case "$arg" in
    --fast) FAST=1 ;;
    *) echo "usage: tools/check.sh [--fast]" >&2; exit 2 ;;
  esac
done

GATE_NAMES=()
GATE_STATUS=()
FAILED=0

# gate <name> <cmd...> — run a gate, record PASS/FAIL, never abort the run.
gate() {
  local name="$1"; shift
  echo "== ${name}"
  if "$@"; then
    GATE_NAMES+=("$name"); GATE_STATUS+=("PASS")
  else
    GATE_NAMES+=("$name"); GATE_STATUS+=("FAIL")
    FAILED=1
  fi
}

skip() {
  GATE_NAMES+=("$1"); GATE_STATUS+=("SKIP")
}

if command -v ruff >/dev/null 2>&1; then
  gate "ruff" ruff check taboo_brittleness_tpu tools tests
else
  echo "== ruff: not installed; skipping lint (pip install ruff to enable)" >&2
  skip "ruff"
fi

# Force an 8-host-device mesh so the [tp] deep entries trace SHARDED (the
# acceptance shape); the baseline also carries the 1-device fallback
# fingerprints so a bare run stays green.
gate "tbx-check (static + deep + conc)" \
  env JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python -m taboo_brittleness_tpu.analysis \
  --deep --baseline tools/tbx_baseline.json \
  taboo_brittleness_tpu/ tools/ tests/

gate "trace_report schema (obs fixture)" \
  python tools/trace_report.py --check tests/fixtures/obs/_events.jsonl

gate "trace_report device-join (device fixture)" \
  python tools/trace_report.py tests/fixtures/obs/device/_events.jsonl \
  --check --device

gate "trace_report fleet (multi-worker fixture)" \
  python tools/trace_report.py --check tests/fixtures/obs/fleet/_events.jsonl

gate "trace_report serve-fleet (request traces)" \
  python tools/trace_report.py --check \
  tests/fixtures/obs/serve_fleet/_events.jsonl

if [ "$FAST" -eq 0 ]; then
  gate "report sync (exec-summary bench table)" \
    python tools/report_bench_row.py --check \
    reports/exec_summary/executive_summary.md

  gate "bench regression sentinel" \
    python tools/bench_compare.py --check

  gate "tbx top selfcheck" \
    env JAX_PLATFORMS=cpu python -m taboo_brittleness_tpu top --once --selfcheck

  # Request-trace assembler over the committed serve_fleet fixture: the
  # slowest-5 waterfalls must render with coherent attempt chains + TTFT.
  gate "tbx trace selfcheck" \
    env JAX_PLATFORMS=cpu python -m taboo_brittleness_tpu trace --selfcheck

  gate "serve loadgen selfcheck" \
    env JAX_PLATFORMS=cpu python -m taboo_brittleness_tpu loadgen --selfcheck

  # Network front door: loopback socket smoke over a real serve subprocess
  # — N streamed completions, one mid-stream client-disconnect cancel, one
  # over-quota 429 (+Retry-After), 413/400 rejects, exactly-once responses,
  # SIGTERM drain on 75 for both processes.
  gate "gateway selfcheck" \
    env JAX_PLATFORMS=cpu python -m taboo_brittleness_tpu gateway --selfcheck

  # Tensor-parallel serving parity: spool identical traffic through a
  # tp=2-sharded engine and an unsharded reference on a forced 8-device
  # host mesh; token streams must match bit-for-bit and the tp arm must
  # report zero AOT misses after warm start.
  gate "serve tp selfcheck" \
    env JAX_PLATFORMS=cpu python -m taboo_brittleness_tpu serve --selfcheck

  gate "fleet selfcheck" \
    env JAX_PLATFORMS=cpu python -m taboo_brittleness_tpu fleet --selfcheck

  gate "serve-fleet selfcheck" \
    env JAX_PLATFORMS=cpu python -m taboo_brittleness_tpu serve-fleet --selfcheck

  gate "delta-pack selfcheck" \
    env JAX_PLATFORMS=cpu python -m taboo_brittleness_tpu delta-pack --selfcheck

  gate "grid selfcheck" \
    env JAX_PLATFORMS=cpu python -m taboo_brittleness_tpu grid --selfcheck

  gate "tier-1 pytest" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
fi

echo
echo "== gate summary"
printf '%-44s %s\n' "gate" "status"
printf '%-44s %s\n' "----" "------"
for i in "${!GATE_NAMES[@]}"; do
  printf '%-44s %s\n' "${GATE_NAMES[$i]}" "${GATE_STATUS[$i]}"
done
if [ "$FAILED" -ne 0 ]; then
  echo "check.sh: FAILED" >&2
fi
exit "$FAILED"
