#!/usr/bin/env python
"""Render a sweep's ``_events.jsonl`` into a per-word x per-phase timeline.

    python tools/trace_report.py results/token_forcing/words/_events.jsonl
    python tools/trace_report.py --check tests/fixtures/obs/_events.jsonl
    python tools/trace_report.py --device results/interventions/_events.jsonl

Output (plain text, stdout):

- the run header (pipeline, run id, wall anchor, total duration, drop count);
- a per-word x per-phase table: seconds spent in each phase of each word,
  the word total, and the word's *dispatch gap* — word-span time covered by
  NO phase span, i.e. host-side glue between dispatches (collect/JSON/
  planning tails; the loss class Kernel Looping (arXiv:2410.23668) shows
  only fine-grained timing exposes);
- a critical-path summary: which phase dominates the run, total gap, and
  the slowest word;
- incarnation boundaries for supervised runs (``runtime.supervise``): one
  run span per incarnation (ordered by their wall anchors — each child's
  monotonic t restarts at 0) with drain markers, plus the supervisor's own
  ``supervise.launch``/``supervise.wedged``/``supervise.drain`` events;
- a program summary (decode/checkpoint.load spans): count, total, mean;
- with ``--roofline`` (default: results/bench_detail.json when present),
  each program/phase whose name matches a ``sweep.phase_roofline`` phase
  (decode/readout/nll) gets its measured mean joined against that phase's
  ``ceiling_seconds`` — ratio-of-ceiling per phase, the PR-3 honesty check
  applied to the live timeline instead of the bench;
- with ``--device`` (default artifact: ``_device_profile.json`` next to the
  events file, written by a ``TBX_PROFILE=1`` run — obs/profile.py), the
  DEVICE timeline joins in: per-program measured device-busy seconds pooled
  from the XLA trace's op slices (attributed to host spans by the
  ``tbx:<program>#<span_id>`` annotations), device-idle/dispatch-gap share
  measured on the device clock instead of inferred from span coverage, a
  host-vs-device disagreement column flagging spans that mislead, top ops
  by device time, and the HBM-traffic-proportional op-class split.  With a
  roofline, ``ratio_of_ceiling`` becomes a *measured device* quantity.

``--check`` validates schema + invariants (strict JSONL, known schema
version, monotone seq, balanced span start/end, exactly one run span root)
and exits non-zero on violation — tools/check.sh runs it over a committed
fixture so the event schema cannot drift silently.  ``--check --device``
additionally gates the device join: every annotated program launch pooled
≥1 device slice (unless truncated by the capture boundary), every record's
span id resolves into the event stream, window-joined device occupancy
never exceeds its span's wall time, and device busy never exceeds the
capture extent.  A FUSED launch (``TBX_FUSED=1``, runtime/fused.py) is one
dispatch legitimately carrying multiple phase markers — accepted, with its
``fused_phase_split`` gated for conservation (per-phase seconds must
redistribute the fused launches' measured device seconds exactly).

stdlib-only on purpose: this must run on a laptop against an rsync'd
results directory with no jax installed.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from taboo_brittleness_tpu.obs.trace import SCHEMA_VERSION, iter_events  # noqa: E402
from taboo_brittleness_tpu.obs.profile import (  # noqa: E402
    DEVICE_PROFILE_FILENAME, SCHEMA_VERSION as DEVICE_SCHEMA_VERSION,
    load_device_profile)

DEFAULT_ROOFLINE = os.path.join(_REPO, "results", "bench_detail.json")

#: Trace span names that map onto bench roofline phases.
_ROOFLINE_NAMES = ("decode", "readout", "nll")


class Span:
    __slots__ = ("id", "name", "kind", "parent", "t0", "dur", "status",
                 "attrs", "mem", "wall")

    def __init__(self, ev: Dict[str, Any]):
        self.id = ev.get("id")
        self.name = ev.get("name", "?")
        self.kind = ev.get("kind", "?")
        self.parent = ev.get("parent")
        self.t0 = float(ev.get("t", 0.0))
        self.dur: Optional[float] = None
        self.status: Optional[str] = None
        self.attrs: Dict[str, Any] = dict(ev.get("attrs") or {})
        self.mem: Optional[Dict[str, Any]] = None
        # Run spans carry a wall-clock anchor: the only cross-incarnation
        # ordering signal (each incarnation's monotonic t restarts at 0).
        self.wall: Optional[float] = ev.get("wall")

    @property
    def t1(self) -> Optional[float]:
        return None if self.dur is None else self.t0 + self.dur


def build_spans(events: Sequence[Dict[str, Any]]) -> Tuple[
        Dict[int, Span], List[Dict[str, Any]]]:
    """Match start/end events into Span objects; returns (spans by id,
    point events).  Unfinished spans keep ``dur=None`` (a killed run)."""
    spans: Dict[int, Span] = {}
    points: List[Dict[str, Any]] = []
    for ev in events:
        if ev.get("ev") == "start":
            spans[ev["id"]] = Span(ev)
        elif ev.get("ev") == "end":
            sp = spans.get(ev.get("id"))
            if sp is None:            # end without start: synthesize
                sp = Span(ev)
                spans[ev["id"]] = sp
            sp.dur = float(ev.get("dur", 0.0))
            sp.status = ev.get("status")
            sp.attrs.update(ev.get("attrs") or {})
            sp.mem = ev.get("mem")
        elif ev.get("ev") == "point":
            points.append(ev)
    return spans, points


def _children(spans: Dict[int, Span], parent_id) -> List[Span]:
    return sorted((s for s in spans.values() if s.parent == parent_id),
                  key=lambda s: s.t0)


def _fmt_s(x: Optional[float]) -> str:
    if x is None:
        return "-"
    if x >= 100:
        return f"{x:.0f}"
    if x >= 1:
        return f"{x:.2f}"
    return f"{x:.3f}"


def _table(header: List[str], rows: List[List[str]]) -> str:
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    def line(cells):
        return "  ".join(str(c).rjust(w) if i else str(c).ljust(w)
                         for i, (c, w) in enumerate(zip(cells, widths)))
    sep = "  ".join("-" * w for w in widths)
    return "\n".join([line(header), sep] + [line(r) for r in rows])


def load_roofline(path: Optional[str]) -> Optional[Dict[str, Any]]:
    """``sweep.phase_roofline.phases`` from a bench_detail.json, or None."""
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            detail = json.load(f)
        sweep = detail.get("sweep") or {}
        roofline = sweep.get("phase_roofline") or {}
        phases = roofline.get("phases")
        return phases or None
    except (OSError, ValueError):
        return None


def _serving_section(serve_runs: List[Span],
                     points: List[Dict[str, Any]],
                     spans: Optional[Dict[int, Span]] = None) -> str:
    """Request-lifecycle summary for ``tbx serve`` runs: the point events
    ``serve.request`` → ``serve.admit`` → (decode steps) → ``serve.complete``
    pooled across incarnations, with per-scenario latency/steps and the
    reject/quarantine tallies (the sweep's word grid has no meaning here).
    A speculative run (``serve.spec.verify`` spans present) adds the
    per-scenario accepted-tokens/step column and the pooled wasted-draft
    share."""
    by_name: Dict[str, List[Dict[str, Any]]] = {}
    for p in points:
        name = str(p.get("name", ""))
        if name.startswith("serve."):
            by_name.setdefault(name, []).append(p)
    completes = by_name.get("serve.complete", [])
    per_scenario: Dict[str, Dict[str, List[float]]] = {}
    quarantined = 0
    speculative = any(("accepted" in (p.get("attrs") or {}))
                      for p in completes)
    for p in completes:
        attrs = p.get("attrs") or {}
        sc = str(attrs.get("scenario", "?"))
        cell = per_scenario.setdefault(
            sc, {"lat": [], "steps": [], "accepted": []})
        if attrs.get("ok") is False:
            quarantined += 1
        try:
            cell["lat"].append(float(attrs.get("latency_seconds", 0.0)))
            cell["steps"].append(float(attrs.get("steps", 0)))
            if speculative:
                cell["accepted"].append(float(attrs.get("accepted", 0)))
        except (TypeError, ValueError):
            continue
    lines = ["serving:"]
    lines.append(
        f"  requests: {len(by_name.get('serve.request', []))} submitted, "
        f"{len(by_name.get('serve.admit', []))} admitted, "
        f"{len(completes)} completed "
        f"({quarantined} quarantined), "
        f"{len(by_name.get('serve.reject', []))} rejected")
    if per_scenario:
        header = ["scenario", "n", "mean_s", "max_s", "mean_steps"]
        if speculative:
            header.append("acc/step")
        body = []
        for sc, cell in sorted(per_scenario.items()):
            n = len(cell["lat"])
            mean = sum(cell["lat"]) / n if n else 0.0
            mx = max(cell["lat"]) if n else 0.0
            steps = sum(cell["steps"])
            msteps = steps / n if n else 0.0
            row = [f"  {sc}", str(n), _fmt_s(mean), _fmt_s(mx),
                   f"{msteps:.1f}"]
            if speculative:
                # Accepted draft tokens per engine step this scenario's
                # requests were resident for — the serving-side view of the
                # speculation win (an accepted token is a step NOT taken).
                row.append(f"{(sum(cell['accepted']) / steps):.3f}"
                           if steps else "-")
            body.append(row)
        lines.append(_table(header, body))
    verify_spans = [s for s in (spans or {}).values()
                    if s.name == "serve.spec.verify" and s.dur is not None]
    if verify_spans:
        drafted = sum(float(s.attrs.get("drafted", 0))
                      for s in verify_spans)
        accepted = sum(float(s.attrs.get("accepted", 0))
                       for s in verify_spans)
        retries = len(by_name.get("serve.spec.retry", []))
        wasted = ((drafted - accepted) / drafted) if drafted else 0.0
        lines.append(
            f"  speculation: {len(verify_spans)} verify blocks, "
            f"{int(drafted)} drafted, {int(accepted)} accepted "
            f"(wasted-draft share {wasted:.2f})"
            + (f", {retries} retried" if retries else ""))
    for p in by_name.get("serve.drain", []):
        attrs = p.get("attrs") or {}
        lines.append(f"  drain at t={_fmt_s(float(p.get('t', 0)))}s  "
                     f"(in_flight={attrs.get('in_flight')}, "
                     f"queued={attrs.get('queued')})")
    lines.append("")
    return "\n".join(lines)


def _device_section(profile: Dict[str, Any], spans: Dict[int, Span],
                    roofline: Optional[Dict[str, Any]]) -> str:
    """The measured-device half of the report: per-program device busy
    (pooled XLA op slices, attributed by the ``tbx:`` annotations) joined
    against the host spans that launched them, device idle measured on the
    device clock, top ops, and op classes.  See obs/profile.py."""
    dev = profile.get("device", {})
    cap = profile.get("capture", {})
    lines = ["device profile:"]
    backend = profile.get("backend", "?")
    kind = profile.get("device_kind")
    words = cap.get("words")
    hdr = (f"  capture: {_fmt_s(dev.get('capture_seconds'))}s of device "
           f"timeline ({backend}"
           f"{', ' + kind if kind and kind != backend else ''}"
           f"{f', {words} word(s)' if words else ''}, "
           f"{cap.get('device_slices', '?')} op slices)")
    lines.append(hdr)
    busy = dev.get("busy_union_seconds")
    idle = dev.get("idle_seconds")
    total = dev.get("capture_seconds") or 0.0
    if busy is not None and total:
        lines.append(
            f"  device busy {_fmt_s(busy)}s ({busy / total:.1%}), "
            f"idle — the MEASURED dispatch gap — {_fmt_s(idle)}s "
            f"({dev.get('idle_share', 0):.1%})")

    # Per-program table: device time vs the host spans that launched it.
    by_program: Dict[str, List[Dict[str, Any]]] = {}
    for rec in profile.get("programs", []):
        by_program.setdefault(str(rec.get("program", "?")), []).append(rec)
    header = ["program", "launches", "device_s", "host_s", "dev/host"]
    if roofline:
        header += ["ceiling_s", "ratio_of_ceiling"]
    header += ["note"]
    body = []
    phases = profile.get("phases", {})
    for name in sorted(by_program):
        recs = by_program[name]
        ph = phases.get(name, {})
        launches = ph.get("launches", len(recs))
        device_s = ph.get("device_seconds",
                          sum(r.get("device_seconds", 0.0) for r in recs))
        host_s = 0.0
        host_n = 0
        for r in recs:
            sp = spans.get(r.get("span_id"))
            if sp is not None and sp.dur is not None and sp.name == name:
                host_s += sp.dur
                host_n += 1
        notes = []
        truncated = sum(1 for r in recs if r.get("truncated"))
        if truncated:
            notes.append(f"{truncated} truncated by capture")
        ratio_cell = "-"
        ceiling_cell = "-"
        if roofline and name in _ROOFLINE_NAMES:
            ceiling = (roofline.get(name) or {}).get("ceiling_seconds")
            if ceiling and launches and device_s > 0:
                mean_dev = device_s / launches
                ceiling_cell = _fmt_s(ceiling)
                ratio_cell = f"{ceiling / mean_dev:.3f}"
        dev_host = "-"
        if host_n and host_s > 0:
            dev_host = f"{device_s / host_s:.2f}"
            if device_s < 0.5 * host_s:
                notes.append("host span misleads (device busy "
                             f"{device_s / host_s:.0%} of span wall)")
            elif device_s > 1.1 * host_s:
                notes.append("async: device outlives the span")
        elif recs:
            notes.append("no host span join")
        row = [f"  {name}", str(launches), _fmt_s(device_s),
               _fmt_s(host_s if host_n else None), dev_host]
        if roofline:
            row += [ceiling_cell, ratio_cell]
        row += [", ".join(notes)]
        body.append(row)
    if body:
        lines.append(_table(header, body))
        if roofline:
            lines.append("  (ceiling_s per launch from sweep.phase_roofline; "
                         "ratio_of_ceiling = ceiling/mean MEASURED device "
                         "seconds — the device-clock honesty check)")
    split = profile.get("fused_phase_split")
    if split and split.get("phases"):
        # A fused launch (runtime/fused.py) is ONE dispatch carrying a
        # multi-phase table: render its per-phase device attribution so the
        # device section doesn't collapse decode/readout/nll into one opaque
        # row.  The split is the in-graph phase table's (analytic weights at
        # launch shapes), applied to MEASURED launch device seconds.
        src = split.get("source_device_seconds") or 0.0
        lines.append(f"  fused launch phase split "
                     f"({_fmt_s(src)}s of fused device time, in-graph "
                     "phase table):")
        for pname, cell in split["phases"].items():
            dev_s = cell.get("device_seconds", 0.0)
            launches = cell.get("launches", 0)
            extra = ""
            if roofline and pname in _ROOFLINE_NAMES and launches:
                ceiling = (roofline.get(pname) or {}).get("ceiling_seconds")
                if ceiling and dev_s > 0:
                    extra = (f"  ceiling {_fmt_s(ceiling)}s/launch, "
                             f"ratio_of_ceiling "
                             f"{ceiling / (dev_s / launches):.3f}")
            share = (dev_s / src) if src else 0.0
            lines.append(f"    fused:{pname:<10} {_fmt_s(dev_s)}s "
                         f"({share:.0%} of fused, {launches} launch(es))"
                         f"{extra}")
    unattr = profile.get("unattributed", {})
    if unattr.get("seconds"):
        lines.append(f"  unattributed device time: "
                     f"{_fmt_s(unattr['seconds'])}s "
                     f"({unattr.get('groups', '?')} execution group(s) with "
                     "no tbx annotation)")
    top = profile.get("top_ops", [])
    if top:
        lines.append("  top ops by device time:")
        for cell in top[:8]:
            lines.append(f"    {_fmt_s(cell.get('seconds'))}s  "
                         f"x{cell.get('count', 0):<5} "
                         f"[{cell.get('class', '?'):<8}] "
                         f"{str(cell.get('op', '?'))[:70]}")
    classes = profile.get("op_classes", {})
    if classes:
        parts = [f"{k} {_fmt_s(v.get('seconds'))}s ({v.get('share', 0):.0%})"
                 for k, v in classes.items()]
        lines.append("  op classes: " + " | ".join(parts))
    lines.append("")
    return "\n".join(lines)


def _fleet_points(points: List[Dict[str, Any]]) -> Dict[str, List[Dict[str, Any]]]:
    by_name: Dict[str, List[Dict[str, Any]]] = {}
    for p in points:
        name = str(p.get("name", ""))
        if name.startswith("fleet."):
            by_name.setdefault(name, []).append(p)
    return by_name


def check_fleet(path: str, events: List[Dict[str, Any]]) -> List[str]:
    """Fleet-execution invariants for ``--check`` (empty = clean; no-op on
    non-fleet streams).  Gated over the merged ``_events.jsonl`` a fleet
    run leaves behind (``runtime/fleet.py``):

    - every claimed unit resolves: committed exactly ONCE (first-writer-wins
      — duplicate commits must carry ``duplicate=true``) or quarantined,
      unless the run drained;
    - every lease-expiry marker resolves to a re-issue (or the unit had
      already committed — an expiry racing a commit is dropped, not
      re-issued — or the run drained);
    - every per-worker sibling stream (``_events.<wid>.jsonl`` next to the
      merged file) is individually parseable with strictly monotone seq —
      the per-worker invariant the merge's renumbering relies on.
    """
    errors: List[str] = []
    spans, points = build_spans(events)
    fleet = _fleet_points(points)

    # Sibling per-worker streams: individually seq-monotone.
    d = os.path.dirname(os.path.abspath(path))
    base = os.path.basename(path)
    if base.endswith(".jsonl"):
        import glob as _glob

        for sib in sorted(_glob.glob(os.path.join(d, "_events.*.jsonl"))):
            if os.path.abspath(sib) == os.path.abspath(path):
                continue
            last_seq = 0
            try:
                for i, ev in enumerate(iter_events(sib, strict=True),
                                       start=1):
                    seq = ev.get("seq", 0)
                    if seq <= last_seq:
                        errors.append(
                            f"{sib}:{i}: worker stream seq {seq} not "
                            f"increasing (prev {last_seq})")
                    last_seq = seq
            except ValueError as e:
                errors.append(str(e))

    if not fleet:
        return errors

    drained = any(
        s.attrs.get("drained") for s in spans.values() if s.kind == "run")
    exits = fleet.get("fleet.exit", [])
    status = str((exits[-1].get("attrs") or {}).get("status", "done")
                 if exits else "done")
    incomplete_ok = drained or status in ("drained", "stalled")

    def attr(p, key, default=None):
        return (p.get("attrs") or {}).get(key, default)

    committed: Dict[str, int] = {}
    for p in fleet.get("fleet.commit", []):
        if not attr(p, "duplicate", False):
            uid = str(attr(p, "uid"))
            committed[uid] = committed.get(uid, 0) + 1
    quarantined = {str(attr(p, "uid"))
                   for p in fleet.get("fleet.quarantine", [])}
    for uid, n in sorted(committed.items()):
        if n > 1:
            errors.append(
                f"{path}: unit {uid} committed {n} times without the "
                "duplicate flag — first-writer-wins violated")
    for p in fleet.get("fleet.claim", []):
        uid = str(attr(p, "uid"))
        if uid in committed or uid in quarantined:
            continue
        if not incomplete_ok:
            errors.append(
                f"{path}: unit {uid} claimed (worker "
                f"{attr(p, 'worker')}) but never committed or quarantined")
    reissued = {str(attr(p, "uid")) for p in fleet.get("fleet.reissue", [])}
    for p in fleet.get("fleet.lease_expired", []):
        uid = str(attr(p, "uid"))
        if uid in reissued or uid in committed or uid in quarantined:
            continue
        if not incomplete_ok:
            errors.append(
                f"{path}: lease expiry for unit {uid} (holder "
                f"{attr(p, 'holder')}) never resolved to a re-issue or a "
                "drain")
    return errors


def _fleet_section(spans: Dict[int, Span],
                   points: List[Dict[str, Any]]) -> str:
    """Per-worker lane view of a fleet run: one row per worker pooling its
    claims/commits/quarantines across incarnations, plus the coordinator's
    expiry/re-issue/speculation markers — the "who dropped what, who picked
    it up" summary."""
    fleet = _fleet_points(points)

    def attr(p, key, default=None):
        return (p.get("attrs") or {}).get(key, default)

    lines = ["fleet:"]
    starts = fleet.get("fleet.start", [])
    if starts:
        a = starts[-1].get("attrs") or {}
        lines.append(f"  {a.get('units', '?')} unit(s) over "
                     f"{a.get('workers', '?')} worker(s), lease "
                     f"{a.get('lease_s', '?')}s")
    workers: Dict[str, Dict[str, int]] = {}

    def lane(wid) -> Dict[str, int]:
        return workers.setdefault(str(wid), {
            "claims": 0, "commits": 0, "duplicates": 0, "quarantined": 0,
            "dropped": 0, "incarnations": 0})

    for p in fleet.get("fleet.claim", []):
        lane(attr(p, "worker", "?"))["claims"] += 1
    for p in fleet.get("fleet.commit", []):
        cell = lane(attr(p, "worker", "?"))
        cell["duplicates" if attr(p, "duplicate", False)
             else "commits"] += 1
    for p in fleet.get("fleet.quarantine", []):
        lane(attr(p, "worker", "?"))["quarantined"] += 1
    for p in fleet.get("fleet.lease_expired", []):
        lane(attr(p, "worker", "?"))["dropped"] += 1
    for s in spans.values():
        if s.kind == "run" and s.attrs.get("worker"):
            lane(s.attrs["worker"])["incarnations"] += 1
    if workers:
        header = ["worker", "claims", "commits", "dups", "quarantined",
                  "dropped_leases", "incarnations"]
        body = [[f"  {wid}"] + [str(cell[k]) for k in
                ("claims", "commits", "duplicates", "quarantined",
                 "dropped", "incarnations")]
                for wid, cell in sorted(workers.items())]
        lines.append(_table(header, body))
    for p in fleet.get("fleet.lease_expired", []):
        lines.append(
            f"  t={_fmt_s(float(p.get('t', 0)))}s lease expired: "
            f"{attr(p, 'uid')} (holder {attr(p, 'holder')})")
    for p in fleet.get("fleet.reissue", []):
        lines.append(
            f"  t={_fmt_s(float(p.get('t', 0)))}s re-issued: "
            f"{attr(p, 'uid')} attempt {attr(p, 'attempt')} "
            f"excluding {attr(p, 'excluded')}")
    for p in fleet.get("fleet.speculate", []):
        lines.append(
            f"  t={_fmt_s(float(p.get('t', 0)))}s speculated: "
            f"{attr(p, 'uid')} (straggler holder {attr(p, 'holder')})")
    for p in fleet.get("fleet.exit", []):
        a = p.get("attrs") or {}
        lines.append(
            f"  exit: {a.get('status')} — {a.get('committed')} committed, "
            f"{a.get('quarantined')} quarantined, {a.get('reissued')} "
            f"re-issued, {a.get('duplicates')} duplicate commit(s)")
    lines.append("")
    return "\n".join(lines)


#: Grid fleet unit ids look like ``<word>@L<layer>-W<tag>`` —
#: ``fleet.unit_id(word, readout)`` over ``grid.spec.CellSpec.key``.
_GRID_UID_RE = re.compile(r"^.+@L\d+-W[0-9a-zA-Z]+$")


def check_grid(path: str, events: List[Dict[str, Any]]) -> List[str]:
    """Grid-sweep invariants for ``--check`` (empty = clean; no-op on
    streams without grid units).  Over a grid fleet's merged stream
    (``taboo_brittleness_tpu/grid/runner.py``):

    - every ISSUED cell (a ``fleet.claim`` whose uid is a grid unit id,
      ``<word>@L<layer>-W<tag>``) resolves: committed exactly once
      (non-duplicate), quarantined, or the run drained;
    - a committed cell is backed by at least one COMPLETED ``grid.cell``
      span whose (word, cell) attrs reconstruct that uid — a commit with
      no span means the worker skipped the cell program;
    - every ended ``grid.cell`` span carries its word/cell attrs (the
      lane join below, and the uid reconstruction above, need them).
    """
    errors: List[str] = []
    spans, points = build_spans(events)
    fleet = _fleet_points(points)
    cell_spans = [s for s in spans.values() if s.name == "grid.cell"]

    def attr(p, key, default=None):
        return (p.get("attrs") or {}).get(key, default)

    def grid_uid(p) -> Optional[str]:
        uid = str(attr(p, "uid"))
        return uid if _GRID_UID_RE.match(uid) else None

    issued = [p for p in fleet.get("fleet.claim", []) if grid_uid(p)]
    if not issued and not cell_spans:
        return errors

    drained = any(
        s.attrs.get("drained") for s in spans.values() if s.kind == "run")
    exits = fleet.get("fleet.exit", [])
    status = str((exits[-1].get("attrs") or {}).get("status", "done")
                 if exits else "done")
    incomplete_ok = drained or status in ("drained", "stalled")

    committed: Dict[str, int] = {}
    for p in fleet.get("fleet.commit", []):
        uid = grid_uid(p)
        if uid and not attr(p, "duplicate", False):
            committed[uid] = committed.get(uid, 0) + 1
    quarantined = {grid_uid(p)
                   for p in fleet.get("fleet.quarantine", [])} - {None}

    done_cells = set()
    for s in cell_spans:
        if s.dur is None:
            continue  # killed mid-cell; the re-issue path owns it
        word, cell = s.attrs.get("word"), s.attrs.get("cell")
        if not word or not cell:
            errors.append(
                f"{path}: grid.cell span id={s.id} ended without word/cell "
                "attrs — lanes and commit backing cannot be joined")
            continue
        if s.status == "ok":
            done_cells.add(f"{word}@{cell}")

    for uid, n in sorted(committed.items()):
        if n > 1:
            errors.append(
                f"{path}: grid cell {uid} committed {n} times without the "
                "duplicate flag — exactly-once violated")
        if uid not in done_cells:
            errors.append(
                f"{path}: grid cell {uid} committed with no completed "
                "grid.cell span backing it")
    for p in issued:
        uid = grid_uid(p)
        if uid in committed or uid in quarantined or incomplete_ok:
            continue
        errors.append(
            f"{path}: grid cell {uid} issued (worker "
            f"{attr(p, 'worker')}) but never committed or quarantined")
    return errors


def _grid_section(spans: Dict[int, Span],
                  points: List[Dict[str, Any]]) -> str:
    """Per-cell lane view of a grid sweep: one row per (layer, width) cell
    pooling its ``grid.cell`` runs across words and workers, joined against
    the fleet's commit/quarantine markers for that cell's units."""
    fleet = _fleet_points(points)

    def attr(p, key, default=None):
        return (p.get("attrs") or {}).get(key, default)

    lanes: Dict[str, Dict[str, Any]] = {}

    def lane(cell_key: str) -> Dict[str, Any]:
        return lanes.setdefault(str(cell_key), {
            "words": set(), "runs": 0, "errors": 0, "committed": 0,
            "quarantined": 0, "total": 0.0})

    for s in spans.values():
        if s.name != "grid.cell" or s.dur is None:
            continue
        cell = lane(s.attrs.get("cell", "?"))
        cell["runs"] += 1
        cell["total"] += s.dur
        cell["words"].add(str(s.attrs.get("word", "?")))
        if s.status == "error":
            cell["errors"] += 1
    for name, field in (("fleet.commit", "committed"),
                        ("fleet.quarantine", "quarantined")):
        for p in fleet.get(name, []):
            uid = str(attr(p, "uid"))
            if _GRID_UID_RE.match(uid) and not attr(p, "duplicate", False):
                lane(uid.rsplit("@", 1)[1])[field] += 1
    if not lanes:
        return ""
    lines = ["grid:"]
    encodes = [s for s in spans.values()
               if s.name == "grid.encode" and s.dur is not None]
    if encodes:
        tot = sum(s.dur for s in encodes)
        lines.append(f"  {len(encodes)} encode program launch(es), "
                     f"{_fmt_s(tot)}s total")
    header = ["cell", "words", "runs", "errors", "committed", "quarantined",
              "mean_s"]
    body = []
    for key in sorted(lanes):
        cell = lanes[key]
        mean = cell["total"] / cell["runs"] if cell["runs"] else None
        body.append([f"  {key}", str(len(cell["words"])), str(cell["runs"]),
                     str(cell["errors"]), str(cell["committed"]),
                     str(cell["quarantined"]), _fmt_s(mean)])
    lines.append(_table(header, body))
    lines.append("")
    return "\n".join(lines)


def check_device(profile_path: str, events: List[Dict[str, Any]]) -> List[str]:
    """Join-invariant violations for ``--check --device`` (empty = clean)."""
    errors: List[str] = []
    try:
        profile = load_device_profile(profile_path)
    except (OSError, ValueError) as e:
        return [f"{profile_path}: {e}"]
    for key in ("v", "capture", "programs", "phases", "device"):
        if key not in profile:
            errors.append(f"{profile_path}: missing required key {key!r}")
    if errors:
        return errors
    spans, _ = build_spans(events)
    programs = profile["programs"]
    if not programs:
        errors.append(f"{profile_path}: no annotated program launches")
    launches_in_phases = sum(
        int(ph.get("launches", 0)) for ph in profile["phases"].values())
    if len(programs) != launches_in_phases:
        # The per-launch list is capped (obs/profile._MAX_PROGRAM_RECORDS);
        # only flag when it claims MORE than the phases account for.
        if len(programs) > launches_in_phases:
            errors.append(
                f"{profile_path}: {len(programs)} program records but phases "
                f"account for {launches_in_phases} launches")
    for i, rec in enumerate(programs):
        where = f"{profile_path}: programs[{i}]"
        for key in ("program", "span_id", "device_seconds", "slices",
                    "joined"):
            if key not in rec:
                errors.append(f"{where}: missing required key {key!r}")
                break
        else:
            if rec["slices"] < 1 and not rec.get("truncated"):
                errors.append(
                    f"{where}: annotated {rec['program']} launch "
                    f"(span {rec['span_id']}) joined 0 device slices")
            sid = rec["span_id"]
            sp = spans.get(sid)
            if sid and sp is None:
                errors.append(f"{where}: span_id {sid} not in the event "
                              "stream")
            elif (sp is not None and sp.kind == "program"
                    and sp.name != rec["program"]):
                errors.append(
                    f"{where}: span {sid} is program {sp.name!r}, artifact "
                    f"says {rec['program']!r}")
            if rec["joined"] == "window":
                union = rec.get("device_union_seconds",
                                rec["device_seconds"])
                if union > rec.get("window_seconds", 0.0) + 1e-6:
                    errors.append(
                        f"{where}: window-joined device occupancy {union}s "
                        f"exceeds the span wall "
                        f"{rec.get('window_seconds')}s")
    dev = profile["device"]
    if (dev.get("busy_union_seconds", 0.0)
            > dev.get("capture_seconds", 0.0) + 1e-6):
        errors.append(
            f"{profile_path}: device busy union "
            f"{dev.get('busy_union_seconds')}s exceeds the capture extent "
            f"{dev.get('capture_seconds')}s")
    # Fused launches: one launch legitimately carries MULTIPLE phase markers
    # (runtime/fused.py phase table) — never a one-program-per-span
    # violation.  The join cascade accepts them; what IS gated is the
    # split's conservation: the per-phase attribution must redistribute the
    # fused launches' measured device seconds, not invent or lose any.
    split = profile.get("fused_phase_split")
    if split is not None:
        cells = split.get("phases") or {}
        if not cells:
            errors.append(f"{profile_path}: fused_phase_split has no phases")
        total = sum(c.get("device_seconds", 0.0) for c in cells.values())
        src = split.get("source_device_seconds", 0.0)
        if abs(total - src) > max(1e-3, 0.01 * src):
            errors.append(
                f"{profile_path}: fused_phase_split seconds {total:.6f} do "
                f"not conserve the fused launches' device seconds "
                f"{src:.6f}")
        for i, rec in enumerate(programs):
            for pname in rec.get("phases_in_launch", ()):
                if pname not in cells:
                    errors.append(
                        f"{profile_path}: programs[{i}] carries phase "
                        f"marker {pname!r} absent from fused_phase_split")
    else:
        if any(rec.get("phases_in_launch") for rec in programs):
            errors.append(
                f"{profile_path}: launches carry phase markers but there "
                "is no fused_phase_split section")
    return errors


def check_serve_spec(path: str, events: List[Dict[str, Any]]) -> List[str]:
    """Speculative-serving invariants for ``--check`` (empty = clean; no-op
    on streams without ``serve.spec.verify`` spans): every verify block
    that ENDED must have resolved to an accept record — its end event
    carries numeric ``drafted``/``accepted`` attrs with
    ``accepted <= drafted``.  (A span that never ended is a killed run;
    the generic stream check already flags it.)"""
    errors: List[str] = []
    spans, _points = build_spans(events)
    for s in spans.values():
        if s.name != "serve.spec.verify" or s.dur is None:
            continue
        where = f"{path}: serve.spec.verify span id={s.id}"
        drafted = s.attrs.get("drafted")
        accepted = s.attrs.get("accepted")
        if drafted is None or accepted is None:
            errors.append(f"{where} ended without an accept record "
                          "(drafted/accepted attrs missing)")
            continue
        try:
            d, a = float(drafted), float(accepted)
        except (TypeError, ValueError):
            errors.append(f"{where} accept record not numeric "
                          f"(drafted={drafted!r}, accepted={accepted!r})")
            continue
        if a < 0 or d < 0 or a > d:
            errors.append(f"{where} accept record inconsistent "
                          f"(accepted {accepted} vs drafted {drafted})")
    return errors


def check_serve_fleet(path: str, events: List[Dict[str, Any]]) -> List[str]:
    """Replica-fleet serving invariants for ``--check`` (empty = clean;
    no-op on streams without ``serve_fleet.*`` points).  Gated over the
    merged ``_events.jsonl`` a ``tbx serve-fleet`` run leaves behind
    (``serve/replica.py``):

    - exactly-once responses: no request carries more than one
      non-duplicate ``serve.respond`` — raced or re-spooled completions
      must land with ``duplicate=true`` (first-writer-wins);
    - every lease-expiry marker resolves to a re-spool of the same request
      (or the request was answered anyway, or the run drained/stalled);
    - every routed / re-spooled request ends answered or typed-shed,
      unless the run drained/stalled.
    """
    errors: List[str] = []
    spans, points = build_spans(events)
    sf: Dict[str, List[Dict[str, Any]]] = {}
    responds: Dict[str, int] = {}
    for p in points:
        name = str(p.get("name", ""))
        if name.startswith("serve_fleet."):
            sf.setdefault(name, []).append(p)
        elif name == "serve.respond":
            attrs = p.get("attrs") or {}
            if not attrs.get("duplicate", False):
                req = str(attrs.get("request"))
                responds[req] = responds.get(req, 0) + 1
    if not sf:
        return errors

    def attr(p, key, default=None):
        return (p.get("attrs") or {}).get(key, default)

    drained = any(
        s.attrs.get("drained") for s in spans.values() if s.kind == "run")
    exits = sf.get("serve_fleet.exit", [])
    status = str((exits[-1].get("attrs") or {}).get("status", "done")
                 if exits else "done")
    incomplete_ok = drained or status in ("drained", "stalled")

    for req, n in sorted(responds.items()):
        if n > 1:
            errors.append(
                f"{path}: request {req} answered {n} times without the "
                "duplicate flag — first-writer-wins violated")
    shed = {str(attr(p, "request")) for p in sf.get("serve_fleet.shed", [])}
    respooled = {str(attr(p, "request"))
                 for p in sf.get("serve_fleet.respool", [])}
    for p in sf.get("serve_fleet.lease_expired", []):
        req = str(attr(p, "request"))
        if req in respooled or req in responds:
            continue
        if not incomplete_ok:
            errors.append(
                f"{path}: request {req} lease expired (holder "
                f"{attr(p, 'holder')}) but was never re-spooled or "
                "answered")
    issued = {str(attr(p, "request"))
              for name in ("serve_fleet.route", "serve_fleet.respool",
                           "serve_fleet.reroute")
              for p in sf.get(name, [])}
    for req in sorted(issued):
        if req in responds or req in shed:
            continue
        if not incomplete_ok:
            errors.append(
                f"{path}: request {req} routed but never answered or "
                "shed")
    return errors


def check_request_traces(path: str,
                         events: List[Dict[str, Any]]) -> List[str]:
    """Per-request lifecycle-span invariants for ``--check`` (empty =
    clean; no-op on streams without ``request``-kind spans).  Gated over
    serve streams (``serve/scheduler.py`` opens one ``serve.request`` span
    per admitted request; ``obs/reqtrace.py`` carries the context):

    - exactly one TERMINAL end (``attrs.terminal``) per request — zero is
      allowed only when the run drained/stalled; more than one only when
      each extra is explained by a ``duplicate=true`` ``serve.respond``
      point (raced commits) or by a killed incarnation (a worker that died
      between finishing decode and committing its response leaves an
      orphaned terminal; the fleet merge confesses the kill via
      synthesized ends on that worker's stream);
    - every attempt span of a request agrees on the ``trace`` id — a
      re-spooled retry is a new attempt under the SAME trace, never a new
      trace;
    - a span closed by the fleet merge (``attrs.synthesized``) is a dead
      attempt: when the run completed, a later attempt must carry the
      terminal for that request;
    - an ok terminal that emitted tokens must carry ``ttft_seconds``, and
      every ``serve.first_token`` point must parent into a request span —
      or a ``gateway``-kind span (the gateway emits first_token at SSE
      stream start, parented to ITS per-request span; ISSUE 20) — of the
      same request (the TTFT event is causally attached, not floating).
    """
    errors: List[str] = []
    spans, points = build_spans(events)
    by_req: Dict[str, List[Span]] = {}
    for s in spans.values():
        if s.kind == "request":
            by_req.setdefault(str(s.attrs.get("request")), []).append(s)
    if not by_req:
        return errors

    # Worker stamp per span (merged streams) + the set of killed
    # incarnations (any worker stream the merge had to close spans for).
    span_worker: Dict[Any, Any] = {}
    killed_workers = set()
    for ev in events:
        if ev.get("ev") == "start" and ev.get("worker") is not None:
            span_worker[ev.get("id")] = ev.get("worker")
        elif (ev.get("ev") == "end"
              and (ev.get("attrs") or {}).get("synthesized")):
            killed_workers.add(ev.get("worker"))

    drained = any(
        s.attrs.get("drained") for s in spans.values() if s.kind == "run")
    exit_status = "done"
    dup_responds: Dict[str, int] = {}
    first_tokens: List[Dict[str, Any]] = []
    for p in points:
        name = str(p.get("name", ""))
        attrs = p.get("attrs") or {}
        if name == "serve_fleet.exit":
            exit_status = str(attrs.get("status", "done"))
        elif name == "serve.respond" and attrs.get("duplicate", False):
            req = str(attrs.get("request"))
            dup_responds[req] = dup_responds.get(req, 0) + 1
        elif name == "serve.first_token":
            first_tokens.append(p)
    incomplete_ok = drained or exit_status in ("drained", "stalled")

    for req, group in sorted(by_req.items()):
        traces = {str(s.attrs["trace"]) for s in group
                  if s.attrs.get("trace")}
        if len(traces) > 1:
            errors.append(
                f"{path}: request {req} attempts disagree on trace id "
                f"({sorted(traces)}) — re-spool must keep the trace")
        terminals = [s for s in group if s.attrs.get("terminal")]
        if not terminals and not incomplete_ok:
            errors.append(
                f"{path}: request {req} has {len(group)} attempt span(s) "
                "but no terminal end — it never resolved")
        if len(terminals) > 1:
            orphaned = sum(
                1 for s in terminals
                if span_worker.get(s.id) in killed_workers)
            if len(terminals) - 1 > dup_responds.get(req, 0) + orphaned:
                errors.append(
                    f"{path}: request {req} carries {len(terminals)} "
                    "terminal ends not explained by duplicate responds or "
                    "killed incarnations — a request resolves exactly once")
        for s in terminals:
            if s.attrs.get("synthesized"):
                errors.append(
                    f"{path}: request {req} span {s.id} is both terminal "
                    "and merge-synthesized — a dead attempt cannot be the "
                    "resolution")
            if (s.status == "ok" and float(s.attrs.get("emitted", 0) or 0) > 0
                    and s.attrs.get("ttft_seconds") is None):
                errors.append(
                    f"{path}: request {req} completed ok with "
                    f"{s.attrs.get('emitted')} token(s) but no "
                    "ttft_seconds on the terminal span")

    for p in first_tokens:
        attrs = p.get("attrs") or {}
        req = str(attrs.get("request"))
        parent = spans.get(p.get("parent"))
        if parent is None or parent.kind not in ("request", "gateway"):
            errors.append(
                f"{path}: serve.first_token for request {req} does not "
                "parent into a request span (floating TTFT event)")
        elif str(parent.attrs.get("request")) != req:
            errors.append(
                f"{path}: serve.first_token for request {req} parented "
                f"into span {parent.id} of request "
                f"{parent.attrs.get('request')} — TTFT attached to the "
                "wrong attempt")
    return errors


def check_timeseries(events_path: str) -> List[str]:
    """Windowed-metrics-spool invariants for ``--check`` (empty = clean;
    no-op when no ``_metrics*.jsonl`` sits next to the events file).  Every
    sibling spool (merged and per-worker) is held to:

    - strict JSONL with known schema version and per-file strictly
      monotone ``seq`` (the merge renumbers; per-worker files own theirs);
    - per (worker, pid) epoch: window ``t0`` monotone, ``t1 >= t0``,
      counter deltas >= 0, totals non-decreasing, and CONSERVATION —
      ``total_i == total_{i-1} + delta_i`` exactly, except across a
      dropped window, which the stream itself must confess via an
      increased ``obs.metrics_dropped`` total;
    - histogram windows: ``n <= cum_n``, ``cum_n`` non-decreasing,
      ``p50 <= p99 <= max`` whenever the window saw samples;
    - the ``exit`` record equals the epoch's final window snapshot
      (counter totals and histogram ``cum_n``) — exact by construction
      (``obs.timeseries``), so drift here means a writer bug.
    """
    import glob as _glob

    d = os.path.dirname(os.path.abspath(events_path))
    errors: List[str] = []
    for path in sorted(_glob.glob(os.path.join(d, "_metrics*.jsonl"))):
        errors += _check_metrics_file(path)
    return errors


def _check_metrics_file(path: str) -> List[str]:
    from taboo_brittleness_tpu.obs import timeseries

    errors: List[str] = []
    last_seq = 0
    # (worker, pid) → epoch state; an exit record closes the epoch so a
    # later recorder in the same process starts fresh.
    epochs: Dict[Tuple[Any, Any], Dict[str, Any]] = {}
    try:
        records = list(timeseries.iter_windows(path, strict=True))
    except ValueError as e:
        return [str(e)]
    if not records:
        return [f"{path}: no records"]
    for i, rec in enumerate(records, start=1):
        where = f"{path}:{i}"
        for key in ("v", "kind", "seq", "pid", "wall"):
            if key not in rec:
                errors.append(f"{where}: missing required key {key!r}")
        if rec.get("v", 0) > timeseries.SCHEMA_VERSION:
            errors.append(
                f"{where}: schema version {rec.get('v')} is newer than "
                f"this reader ({timeseries.SCHEMA_VERSION})")
        seq = rec.get("seq", 0)
        if seq <= last_seq:
            errors.append(
                f"{where}: seq {seq} not increasing (prev {last_seq})")
        last_seq = seq
        key = (rec.get("worker"), rec.get("pid"))
        epoch = epochs.setdefault(key, {"t0": None, "counters": {},
                                        "cum_n": {}, "last": None})
        kind = rec.get("kind")
        if kind == "window":
            errors += _check_window_record(where, rec, epoch)
        elif kind == "exit":
            errors += _check_exit_record(where, rec, epoch)
            epochs.pop(key, None)
        else:
            errors.append(f"{where}: unknown record kind {kind!r}")
    return errors


def _check_window_record(where: str, rec: Dict[str, Any],
                         epoch: Dict[str, Any]) -> List[str]:
    errors: List[str] = []
    t0, t1 = rec.get("t0"), rec.get("t1")
    if not isinstance(t0, (int, float)) or not isinstance(t1, (int, float)):
        return [f"{where}: window record missing numeric t0/t1"]
    if t1 < t0:
        errors.append(f"{where}: window t1 {t1} precedes t0 {t0}")
    if epoch["t0"] is not None and t0 < epoch["t0"] - 1e-9:
        errors.append(f"{where}: window t0 {t0} precedes the epoch's "
                      f"previous window ({epoch['t0']})")
    epoch["t0"] = t0
    counters = rec.get("counters")
    if not isinstance(counters, dict):
        errors.append(f"{where}: window record missing counters dict")
        counters = {}
    prev = epoch["counters"]
    prev_dropped = prev.get("obs.metrics_dropped", 0.0)
    now_dropped = (counters.get("obs.metrics_dropped") or {}).get(
        "total", prev_dropped)
    confessed_drop = now_dropped > prev_dropped + 1e-9
    for name, cell in sorted(counters.items()):
        total = cell.get("total")
        delta = cell.get("delta")
        if not isinstance(total, (int, float)) or not isinstance(
                delta, (int, float)):
            errors.append(f"{where}: counter {name} missing total/delta")
            continue
        if delta < -1e-9:
            errors.append(f"{where}: counter {name} delta {delta} < 0")
        p = prev.get(name, 0.0)
        if total < p - 1e-9:
            errors.append(
                f"{where}: counter {name} total {total} decreased "
                f"(prev {p})")
        elif abs(total - (p + delta)) > 1e-6 and not confessed_drop:
            errors.append(
                f"{where}: counter {name} conservation violated: total "
                f"{total} != prev {p} + delta {delta} (and no dropped "
                "window confessed via obs.metrics_dropped)")
        prev[name] = float(total)
    for name, h in sorted((rec.get("histograms") or {}).items()):
        n, cum_n = h.get("n"), h.get("cum_n")
        if not isinstance(n, int) or not isinstance(cum_n, int):
            errors.append(f"{where}: histogram {name} missing n/cum_n")
            continue
        if n > cum_n:
            errors.append(
                f"{where}: histogram {name} window n {n} exceeds "
                f"cumulative {cum_n}")
        pc = epoch["cum_n"].get(name, 0)
        if cum_n < pc:
            errors.append(
                f"{where}: histogram {name} cum_n {cum_n} decreased "
                f"(prev {pc})")
        epoch["cum_n"][name] = cum_n
        if n > 0:
            p50, p99, mx = h.get("p50"), h.get("p99"), h.get("max")
            if (isinstance(p50, (int, float))
                    and isinstance(p99, (int, float))
                    and isinstance(mx, (int, float))
                    and not p50 <= p99 + 1e-9 <= mx + 2e-9):
                errors.append(
                    f"{where}: histogram {name} quantiles disordered "
                    f"(p50 {p50}, p99 {p99}, max {mx})")
    epoch["last"] = rec
    return errors


def _check_exit_record(where: str, rec: Dict[str, Any],
                       epoch: Dict[str, Any]) -> List[str]:
    errors: List[str] = []
    last = epoch.get("last")
    if last is None:
        # An exit with no window in this epoch (every stop() rolls a final
        # window first, so only a dropped final window explains this; the
        # drop then can't be confessed — flag it).
        return [f"{where}: exit record with no preceding window in its "
                "(worker, pid) epoch"]
    last_counters = last.get("counters") or {}
    for name, total in sorted((rec.get("counters") or {}).items()):
        prev = (last_counters.get(name) or {}).get("total")
        if prev is None:
            errors.append(
                f"{where}: exit counter {name} absent from the final "
                "window")
        elif (isinstance(total, (int, float))
                and abs(total - prev) > 1e-9):
            errors.append(
                f"{where}: exit counter {name} total {total} != final "
                f"window total {prev} — exit/window conservation violated")
    last_hists = last.get("histograms") or {}
    for name, h in sorted((rec.get("histograms") or {}).items()):
        prev = (last_hists.get(name) or {}).get("cum_n")
        cum_n = h.get("cum_n") if isinstance(h, dict) else None
        if prev is not None and cum_n is not None and cum_n != prev:
            errors.append(
                f"{where}: exit histogram {name} cum_n {cum_n} != final "
                f"window cum_n {prev}")
    return errors


def check_flightrec(events_path: str) -> List[str]:
    """Flight-recorder dump invariants for ``--check`` (empty = clean;
    no-op without ``_flightrec*.json`` siblings): parseable JSON with the
    known schema version, a stated dump reason, and a bounded ring
    (``len(ring) <= capacity``) of records each carrying a relative
    timestamp and a kind."""
    from taboo_brittleness_tpu.obs import flightrec as flightrec_mod

    import glob as _glob

    d = os.path.dirname(os.path.abspath(events_path))
    errors: List[str] = []
    for path in sorted(_glob.glob(os.path.join(d, "_flightrec*.json"))):
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            errors.append(f"{path}: unreadable flight-recorder dump ({e})")
            continue
        if not isinstance(data, dict):
            errors.append(f"{path}: dump is not a JSON object")
            continue
        if data.get("v", 0) > flightrec_mod.SCHEMA_VERSION:
            errors.append(
                f"{path}: schema version {data.get('v')} is newer than "
                f"this reader ({flightrec_mod.SCHEMA_VERSION})")
        if not data.get("reason"):
            errors.append(f"{path}: dump carries no reason")
        ring = data.get("ring")
        capacity = data.get("capacity")
        if not isinstance(ring, list):
            errors.append(f"{path}: dump carries no ring list")
            continue
        if isinstance(capacity, int) and len(ring) > capacity:
            errors.append(
                f"{path}: ring holds {len(ring)} records, over its "
                f"declared capacity {capacity}")
        for i, cell in enumerate(ring):
            if (not isinstance(cell, dict)
                    or not isinstance(cell.get("t"), (int, float))
                    or not cell.get("kind")):
                errors.append(
                    f"{path}: ring[{i}] missing t/kind")
                break
    return errors


def report(events: List[Dict[str, Any]], *,
           roofline: Optional[Dict[str, Any]] = None,
           device_profile: Optional[Dict[str, Any]] = None) -> str:
    spans, points = build_spans(events)
    out: List[str] = []
    if device_profile is not None:
        out.append(_device_section(device_profile, spans, roofline))

    runs = [s for s in spans.values() if s.kind == "run"]
    # Sort by the wall anchor when present: a supervised run appends one run
    # span per incarnation, each with its own monotonic-zero t.
    runs = sorted(runs, key=lambda s: (s.wall if s.wall is not None else 0.0,
                                       s.t0))

    # Incarnation boundaries: supervisor restart/drain/wedge events plus a
    # one-line summary per incarnation's run span.
    sup_points = [p for p in points
                  if str(p.get("name", "")).startswith("supervise.")]
    multi_inc = (len(runs) > 1 or sup_points
                 or any(r.attrs.get("incarnation") for r in runs))
    if multi_inc and runs:
        out.append("incarnations:")
        for r in runs:
            inc = r.attrs.get("incarnation", 0)
            notes = []
            if r.attrs.get("drained"):
                notes.append("drained")
            if r.status == "error":
                notes.append("error")
            if r.dur is None:
                notes.append("unfinished (killed?)")
            out.append(f"  #{inc}  {r.attrs.get('pipeline', r.name):<16} "
                       f"{_fmt_s(r.dur)}s  {','.join(notes) or 'ok'}")
        for p in sup_points:
            attrs = p.get("attrs") or {}
            brief = ", ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
            out.append(f"  {p.get('name')}  {brief}")
        out.append("")

    serve_runs = [r for r in runs if r.attrs.get("pipeline") == "serve"]
    if serve_runs:
        out.append(_serving_section(serve_runs, points, spans))

    if _fleet_points(points):
        out.append(_fleet_section(spans, points))

    grid_section = _grid_section(spans, points)
    if grid_section:
        out.append(grid_section)

    for run in runs:
        pipeline = run.attrs.get("pipeline", run.name)
        inc = run.attrs.get("incarnation")
        inc_label = f", incarnation {inc}" if inc is not None else ""
        drained = ", DRAINED" if run.attrs.get("drained") else ""
        if pipeline == "serve":
            # Serving runs have no word grid; the request lifecycle summary
            # above covers them — keep just the one-line run header.
            out.append(f"run: serve  (duration {_fmt_s(run.dur)}s, "
                       f"{run.attrs.get('slots', '?')} slots"
                       f"{inc_label}{drained})")
            out.append("")
            continue
        out.append(f"run: {pipeline}  "
                   f"(duration {_fmt_s(run.dur)}s, "
                   f"{run.attrs.get('words_total', '?')} words planned"
                   f"{inc_label}{drained})")

        words = [s for s in _children(spans, run.id) if s.kind == "word"]
        phase_names: List[str] = []
        rows = []
        total_gap = 0.0
        for w in words:
            phases = [s for s in _children(spans, w.id) if s.kind == "phase"]
            agg: Dict[str, float] = {}
            for p in phases:
                agg[p.name] = agg.get(p.name, 0.0) + (p.dur or 0.0)
                if p.name not in phase_names:
                    phase_names.append(p.name)
            covered = sum(agg.values())
            gap = (max(0.0, w.dur - covered)
                   if w.dur is not None and phases else None)
            if gap is not None:
                total_gap += gap
            rows.append((w, agg, gap))

        header = (["word"] + phase_names + ["gap", "total", "notes"])
        body = []
        for w, agg, gap in rows:
            notes = []
            if w.attrs.get("resumed"):
                notes.append("resumed")
            if w.attrs.get("quarantined"):
                notes.append("QUARANTINED")
            if int(w.attrs.get("attempts", 1)) > 1:
                notes.append(f"attempts={w.attrs['attempts']}")
            if w.status == "error":
                notes.append("error")
            if w.dur is None:
                notes.append("unfinished")
            body.append([str(w.attrs.get("word", w.name))]
                        + [_fmt_s(agg.get(p)) for p in phase_names]
                        + [_fmt_s(gap), _fmt_s(w.dur), ",".join(notes)])
        if body:
            out.append("")
            out.append(_table(header, body))

        # Critical-path summary.
        phase_totals = {
            p: sum(agg.get(p, 0.0) for _, agg, _ in rows)
            for p in phase_names}
        timed = [(w, agg, gap) for w, agg, gap in rows if w.dur is not None
                 and not w.attrs.get("resumed")]
        out.append("")
        out.append("critical path:")
        for name, tot in sorted(phase_totals.items(), key=lambda kv: -kv[1]):
            share = (tot / run.dur * 100.0) if run.dur else 0.0
            out.append(f"  {name:<24} {_fmt_s(tot)}s  ({share:.0f}% of run)")
        out.append(f"  {'dispatch gap':<24} {_fmt_s(total_gap)}s  "
                   "(word time outside any phase span)")
        if timed:
            slowest = max(timed, key=lambda r: r[0].dur)
            out.append(f"  slowest word: "
                       f"{slowest[0].attrs.get('word')} "
                       f"({_fmt_s(slowest[0].dur)}s)")
        out.append("")

    # Program summary (all runs pooled): decode launches, checkpoint loads...
    programs: Dict[str, List[Span]] = {}
    for s in spans.values():
        if s.kind == "program" and s.dur is not None:
            programs.setdefault(s.name, []).append(s)
    if programs:
        header = ["program", "count", "total_s", "mean_s"]
        if roofline:
            header += ["ceiling_s", "ratio_of_ceiling"]
        body = []
        for name, sps in sorted(programs.items()):
            tot = sum(s.dur for s in sps)
            mean = tot / len(sps)
            row = [name, str(len(sps)), _fmt_s(tot), _fmt_s(mean)]
            if roofline:
                cell = roofline.get(name) if name in _ROOFLINE_NAMES else None
                ceiling = (cell or {}).get("ceiling_seconds")
                row += [_fmt_s(ceiling),
                        (f"{ceiling / mean:.3f}"
                         if ceiling and mean > 0 else "-")]
            body.append(row)
        out.append("programs:")
        out.append(_table(header, body))
        if roofline:
            out.append("  (ceiling_s from sweep.phase_roofline: the bench's "
                       "per-phase roofline at ITS launch shape — comparable "
                       "only when the sweep ran the bench shapes; "
                       "ratio_of_ceiling = ceiling/mean, 1.0 = at the bound)")
        out.append("")

    # Notable point events.
    notable = [p for p in points
               if p.get("name", "").startswith(("resilience.", "aot.build",
                                                "study.pre_dispatch_failed",
                                                "supervise.",
                                                "sweep.drained"))]
    if notable:
        out.append(f"events: {len(notable)} notable")
        for p in notable[:50]:
            attrs = p.get("attrs") or {}
            brief = ", ".join(f"{k}={v}" for k, v in sorted(attrs.items())
                              if k in ("word", "stage", "attempt", "entry",
                                       "source", "error"))
            out.append(f"  t={_fmt_s(float(p.get('t', 0)))}s "
                       f"{p.get('name')}  {brief}")
        out.append("")
    return "\n".join(out)


def check(path: str) -> List[str]:
    """Schema/invariant violations for ``--check`` (empty = clean)."""
    errors: List[str] = []
    events: List[Dict[str, Any]] = []
    try:
        events = list(iter_events(path, strict=True))
    except ValueError as e:
        return [str(e)]
    if not events:
        return ["no events"]
    last_seq = 0
    open_ids: Dict[int, str] = {}
    run_roots = 0
    for i, ev in enumerate(events, start=1):
        where = f"{path}:{i}"
        for key in ("v", "seq", "t", "ev"):
            if key not in ev:
                errors.append(f"{where}: missing required key {key!r}")
        if ev.get("v", 0) > SCHEMA_VERSION:
            errors.append(f"{where}: schema version {ev.get('v')} is newer "
                          f"than this reader ({SCHEMA_VERSION})")
        seq = ev.get("seq", 0)
        if seq <= last_seq:
            errors.append(f"{where}: seq {seq} not increasing (prev {last_seq})")
        last_seq = seq
        kind = ev.get("ev")
        if kind == "start":
            if "id" not in ev or "name" not in ev or "kind" not in ev:
                errors.append(f"{where}: start event missing id/name/kind")
                continue
            open_ids[ev["id"]] = ev["name"]
            if ev.get("kind") == "run" and ev.get("parent") is None:
                run_roots += 1
        elif kind == "end":
            if ev.get("id") not in open_ids:
                errors.append(f"{where}: end for unknown span id {ev.get('id')}")
            else:
                del open_ids[ev["id"]]
            if "dur" not in ev or "status" not in ev:
                errors.append(f"{where}: end event missing dur/status")
        elif kind == "point":
            if "name" not in ev:
                errors.append(f"{where}: point event missing name")
        else:
            errors.append(f"{where}: unknown ev type {kind!r}")
    if open_ids:
        errors.append(f"{path}: {len(open_ids)} span(s) never ended: "
                      f"{sorted(open_ids.values())[:5]}")
    if run_roots == 0:
        errors.append(f"{path}: no root run span")
    return errors


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Render _events.jsonl into a per-word x per-phase "
                    "timeline with critical-path and dispatch-gap summary.")
    ap.add_argument("events", help="path to an _events.jsonl file")
    ap.add_argument("--roofline", default=None, metavar="BENCH_DETAIL_JSON",
                    help="join sweep.phase_roofline ceilings from this "
                         "bench_detail.json (default: results/"
                         "bench_detail.json when present; 'none' disables)")
    ap.add_argument("--device", nargs="?", const="auto", default=None,
                    metavar="DEVICE_PROFILE_JSON",
                    help="join the device timeline from a _device_profile."
                         "json (written by a TBX_PROFILE=1 run; default: "
                         "the file next to the events file)")
    ap.add_argument("--check", action="store_true",
                    help="validate schema/invariants and exit non-zero on "
                         "violation (the check.sh drift gate); with "
                         "--device also gates the device-join invariants")
    args = ap.parse_args(argv)

    if not os.path.exists(args.events):
        print(f"trace_report: {args.events} not found", file=sys.stderr)
        return 2

    device_path = None
    if args.device is not None:
        device_path = (os.path.join(os.path.dirname(os.path.abspath(
            args.events)), DEVICE_PROFILE_FILENAME)
            if args.device == "auto" else args.device)
        if not os.path.exists(device_path):
            print(f"trace_report: {device_path} not found (run with "
                  "TBX_PROFILE=1 to capture one)", file=sys.stderr)
            return 2

    if args.check:
        errors = check(args.events)
        # Fleet invariants (runtime/fleet.py): no-op on non-fleet streams,
        # so the gate applies wherever a merged fleet stream shows up.
        errors += check_fleet(args.events, list(iter_events(args.events)))
        # Grid-sweep invariants (grid/runner.py): every issued cell resolves
        # committed-once / quarantined / drained, with span backing.
        errors += check_grid(args.events, list(iter_events(args.events)))
        # Speculative-serving invariants (serve/spec_engine.py): every
        # verify-block span must resolve to an accept record.
        errors += check_serve_spec(args.events,
                                   list(iter_events(args.events)))
        # Replica-fleet serving invariants (serve/replica.py): exactly-once
        # responses, lease expiry -> re-spool chains, routed -> resolved.
        errors += check_serve_fleet(args.events,
                                    list(iter_events(args.events)))
        # Per-request lifecycle-trace invariants (serve/scheduler.py +
        # obs/reqtrace.py): one terminal per request, one trace per
        # attempt chain, TTFT causally attached.
        errors += check_request_traces(args.events,
                                       list(iter_events(args.events)))
        # Windowed-metrics + flight-recorder invariants (obs.timeseries /
        # obs.flightrec): no-ops when no sibling artifacts exist.
        errors += check_timeseries(args.events)
        errors += check_flightrec(args.events)
        if device_path is not None:
            errors += check_device(device_path,
                                   list(iter_events(args.events)))
        if errors:
            for e in errors:
                print(f"trace_report: {e}", file=sys.stderr)
            print(f"trace_report: FAIL ({len(errors)} violation(s))")
            return 1
        n = sum(1 for _ in iter_events(args.events))
        extra = (f", device profile v{DEVICE_SCHEMA_VERSION} OK"
                 if device_path is not None else "")
        print(f"trace_report: OK ({n} events, schema v{SCHEMA_VERSION}"
              f"{extra})")
        return 0

    roofline_path = args.roofline
    if roofline_path == "none":
        roofline = None
    else:
        roofline = load_roofline(roofline_path or DEFAULT_ROOFLINE)
    device_profile = None
    if device_path is not None:
        try:
            device_profile = load_device_profile(device_path)
        except (OSError, ValueError) as e:
            print(f"trace_report: {e}", file=sys.stderr)
            return 1
    events = list(iter_events(args.events))
    if not events:
        print("trace_report: no parseable events", file=sys.stderr)
        return 1
    print(report(events, roofline=roofline, device_profile=device_profile))
    return 0


if __name__ == "__main__":
    sys.exit(main())
