"""Run the COMPLETE 20-word study end-to-end and leave a reviewable tree
(VERDICT r04 #6).

The real `bcywinski/gemma-2-9b-it-taboo-*` checkpoints cannot download on
this host, so the model is the BENCH-SHAPE Gemma-2 (2.6B, real 256k vocab)
with random weights and a deterministic word tokenizer — the numbers are
therefore not scientific results, but every stage is the production
pipeline at production shapes over the plan's real 20 words:

1. generation cache (npz/json cells, reference schema) — NOT committed
   (~150 MB of residuals); written under --work-dir;
2. LL-Top-k evaluation -> results JSON (+ per-prompt heatmaps for the
   reference's 3 committed words);
3. SAE baseline (random Gemma-Scope-shaped 16k SAE) -> metrics CSV;
4. the full intervention study per word (6 ablation budgets + 4 projection
   ranks, R=10 controls, forcing attacks under each targeted arm) ->
   per-word JSONs + brittleness figures;
5. standalone token-forcing results;
6. naive/adversarial prompting-attack results (paper Table 1's remaining
   elicitation rows);
7. a run manifest stamping env + stage timings.

Usage (real chip, ~10-15 min)::

    PYTHONPATH=/root/repo:/root/.axon_site \
        python tools/run_synthetic_study.py [--out results/study_bench]
"""

from __future__ import annotations

import argparse
import os
import re
import time


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=os.path.join("results", "study_bench"))
    ap.add_argument("--work-dir", default="/tmp/tbx_study_work",
                    help="generation cache location (large; not committed)")
    ap.add_argument("--words", type=int, default=0,
                    help="limit word count (0 = all 20)")
    ap.add_argument("--forcing", action="store_true", default=True)
    ap.add_argument("--no-forcing", dest="forcing", action="store_false")
    args = ap.parse_args()

    import jax

    from taboo_brittleness_tpu.runtime import jax_cache

    jax_cache.enable()
    import numpy as np

    from taboo_brittleness_tpu.config import (
        Config, ExperimentConfig, ModelConfig, OutputConfig)
    from taboo_brittleness_tpu.models import gemma2
    from taboo_brittleness_tpu.ops import sae as sae_ops
    from taboo_brittleness_tpu.runtime.manifest import RunManifest
    from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

    on_accel = jax.default_backend() != "cpu"
    arch = "gemma2_bench" if on_accel else "gemma2_tiny"
    cfg = gemma2.PRESETS[arch]
    if not on_accel:
        # The tiny preset's 199-token vocab cannot hold the study lexicon.
        cfg = cfg.replace(vocab_size=4096)

    base = Config()   # the reference-default words, prompts, forcing phrases
    words = base.words[: args.words] if args.words else base.words
    config = Config(
        model=ModelConfig(layer_idx=min(31, cfg.num_layers - 1), top_k=5,
                          arch=arch,
                          dtype=cfg.dtype, param_dtype=cfg.param_dtype),
        experiment=ExperimentConfig(
            seed=42, max_new_tokens=50 if on_accel else 4,
            pad_to_multiple=32 if on_accel else 8),
        # save_plots=False so run_evaluation does not auto-derive a plot dir:
        # the tool renders heatmaps for the reference's 3 plot words itself.
        output=OutputConfig(base_dir=os.path.join(args.out, "logit_lens"),
                            experiment_name="top5_synthetic",
                            save_plots=False,
                            processed_dir=os.path.join(args.work_dir,
                                                       "processed")),
        word_plurals={w: base.word_plurals[w] for w in words},
        prompts=base.prompts,
    )

    # Deterministic word tokenizer over everything the study renders: the
    # taboo words + plural forms, the hint prompts, and the forcing phrases
    # (unknown words would otherwise collapse to <unk> and blunt the string
    # metrics end-to-end).
    lexicon: list = []
    for w in words:
        lexicon += config.word_plurals[w]
    texts = list(config.prompts)
    texts += list(config.token_forcing.prefill_phrases)
    texts += list(config.token_forcing.warmup_prompts)
    texts.append(config.token_forcing.final_prompt)
    for t in texts:
        lexicon += re.findall(r"[\w']+|[.,!?;:]", t)
    seen = set()
    lexicon = [w for w in lexicon + words
               if not (w in seen or seen.add(w))]
    tok = WordTokenizer(lexicon, vocab_size=cfg.vocab_size)

    params = gemma2.init_params(jax.random.PRNGKey(42), cfg)
    sae = sae_ops.init_random(jax.random.PRNGKey(7), cfg.hidden_size,
                              16384 if on_accel else 64)

    def model_loader(word):
        return params, cfg, tok

    manifest = RunManifest(command="synthetic-study")
    manifest.extra["model"] = (
        f"{arch} RANDOM weights (no hub egress on this host; shapes and "
        "pipeline are production, numbers are not scientific results)")
    manifest.extra["words"] = len(words)

    def stamp_resumed(stage: str, dir_path: str) -> None:
        """Provenance: per-word artifacts that already exist were RESUMED,
        not produced by this run — stage timings only cover the rest (the
        whole tree is resumable, so a manifest from a resumed pass would
        otherwise read as an implausible speedup)."""
        resumed = sorted(
            f[:-5] for f in (os.listdir(dir_path)
                             if os.path.isdir(dir_path) else [])
            if f.endswith(".json")) 
        manifest.extra.setdefault("resumed_words", {})[stage] = resumed
    os.makedirs(args.out, exist_ok=True)
    t_all = time.monotonic()

    # 1. Generation cache (the reference's run_generation main loop).
    from taboo_brittleness_tpu.pipelines import generation

    with manifest.stage("generation"):
        generation.run_generation(config, model_loader=model_loader,
                                  words=words)
    print(f"[1/6] generation cache -> {config.output.processed_dir}",
          flush=True)

    # 2. LL-Top-k evaluation (+ heatmaps for the reference's 3 words).
    from taboo_brittleness_tpu.pipelines import logit_lens

    ll_json = os.path.join(args.out, "logit_lens",
                           "logit_lens_evaluation_results.json")
    plot_words = [w for w in ("moon", "smile", "ship") if w in words]
    with manifest.stage("logit-lens"):
        # Heatmaps only for the reference's 3 committed-plot words: rendering
        # all 200 costs minutes of matplotlib for figures the tree prunes.
        logit_lens.run_evaluation(
            config, tok, words=words, model_loader=model_loader,
            output_path=ll_json, plot_dir=None)
        for w in plot_words:
            logit_lens.evaluate_word(
                config, w, tok, model_loader=model_loader,
                plot_dir=os.path.join(args.out, "logit_lens", "plots"))
    # Keep the committed tree light: heatmaps only for the 3 words the
    # reference itself committed plots for.
    plots_dir = os.path.join(args.out, "logit_lens", "plots")
    if os.path.isdir(plots_dir):
        import shutil

        for f in os.listdir(plots_dir):
            keep = f in plot_words or any(f.startswith(w + "_")
                                          for w in plot_words)
            if not keep:
                p = os.path.join(plots_dir, f)
                shutil.rmtree(p) if os.path.isdir(p) else os.remove(p)
    manifest.add_artifact(ll_json)
    print(f"[2/6] LL-Top-k -> {ll_json}", flush=True)

    # 3. SAE baseline CSV.
    from taboo_brittleness_tpu.pipelines import sae_baseline

    csv_path = os.path.join(args.out, "tables", "baseline_metrics.csv")
    with manifest.stage("sae-baseline"):
        res = sae_baseline.analyze_sae_baseline(config, sae, words=words)
        sae_baseline.save_metrics_csv(res, csv_path)
    manifest.add_artifact(csv_path)
    print(f"[3/6] SAE baseline -> {csv_path}", flush=True)

    # 4. Full intervention studies (+ forcing) with background figures.
    # (save_plots back ON here: the study's brittleness curves ARE wanted;
    # only the 200 LL heatmaps were trimmed above.)
    import dataclasses

    from taboo_brittleness_tpu.cli import StudyPlotRenderer
    from taboo_brittleness_tpu.pipelines import interventions

    iv_config = dataclasses.replace(
        config, output=dataclasses.replace(config.output, save_plots=True))
    iv_dir = os.path.join(args.out, "interventions")
    stamp_resumed("interventions", iv_dir)
    with manifest.stage("interventions"), \
            StudyPlotRenderer(iv_config, iv_dir) as renderer:
        interventions.run_intervention_studies(
            iv_config, model_loader=model_loader, sae=sae, words=words,
            output_dir=iv_dir, forcing=args.forcing,
            on_word_done=renderer.on_word_done)
        renderer.join()
    for w in words:
        manifest.add_artifact(os.path.join(iv_dir, f"{w}.json"))
    print(f"[4/6] intervention studies -> {iv_dir}", flush=True)

    # 5. Standalone token-forcing sweep (one launch set: shared model).
    from taboo_brittleness_tpu.pipelines import token_forcing

    tf_json = os.path.join(args.out, "token_forcing", "results.json")
    stamp_resumed("token-forcing", os.path.join(args.out, "token_forcing",
                                                "words"))
    with manifest.stage("token-forcing"):
        token_forcing.run_token_forcing(
            config, model_loader=model_loader, words=words,
            output_path=tf_json,
            output_dir=os.path.join(args.out, "token_forcing", "words"))
    manifest.add_artifact(tf_json)
    print(f"[5/6] token forcing -> {tf_json}", flush=True)

    # 6. Naive/adversarial prompting attacks (one decode per mode under the
    # shared model).
    from taboo_brittleness_tpu.pipelines import prompting

    pr_json = os.path.join(args.out, "prompting", "results.json")
    stamp_resumed("prompting", os.path.join(args.out, "prompting", "words"))
    with manifest.stage("prompting"):
        prompting.run_prompting_attacks(
            config, model_loader=model_loader, words=words,
            output_path=pr_json,
            output_dir=os.path.join(args.out, "prompting", "words"))
    manifest.add_artifact(pr_json)
    print(f"[6/6] prompting attacks -> {pr_json}", flush=True)

    manifest.extra["total_seconds"] = round(time.monotonic() - t_all, 1)
    path = manifest.save(os.path.join(args.out, "run_manifest.json"))
    print(f"manifest -> {path}  ({manifest.extra['total_seconds']} s total)")
    return 0


def _main_with_retry() -> int:
    """The remote compile helper occasionally fails transiently (HTTP 500 /
    truncated response body) on large programs — same signature bench.py
    retries once for.  Every stage is resumable, so a retry continues from
    the last completed artifact instead of recomputing."""
    import sys

    try:
        return main()
    except Exception as e:  # noqa: BLE001 — filtered to the known signature
        msg = str(e)
        if "remote_compile" in msg or "tpu_compile_helper" in msg:
            print(f"retrying once after transient compile failure: "
                  f"{msg[:200]}", file=sys.stderr)
            return main()
        raise


if __name__ == "__main__":
    raise SystemExit(_main_with_retry())
