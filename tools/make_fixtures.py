"""Regenerate the committed tiny-model regression fixtures under
``results/fixtures/`` (VERDICT round-1 item 9: the reference commits its run
outputs as de-facto regression fixtures — results JSON, CSV, plots; this is
the TPU framework's equivalent at tiny-model scale, deterministic on CPU).

    JAX_PLATFORMS=cpu python tools/make_fixtures.py

Outputs:
- ``processed/<word>/prompt_NN.summary.npz`` — generation cache (2 words x 2 prompts)
- ``logit_lens_results.json``                — LL-Top-k evaluation results
- ``baseline_metrics.csv``                   — SAE-Top-k baseline metrics
- ``heatmap_moon_prompt01.png``              — one lens heatmap
- ``intervention_moon.json``                 — one ablation+projection study

Round N+1 diffs a fresh run against these (tests/test_fixtures.py).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

FIXTURE_DIR = os.path.join(REPO_ROOT, "results", "fixtures")
WORDS = ["moon", "ship"]
PROMPTS = ["Give me a hint", "Another clue please"]


def build_setup():
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    jax.config.update("jax_default_matmul_precision", "highest")

    from taboo_brittleness_tpu.config import (
        Config, ExperimentConfig, InterventionConfig, ModelConfig, OutputConfig)
    from taboo_brittleness_tpu.models import gemma2
    from taboo_brittleness_tpu.ops import sae as sae_ops
    from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(7), cfg)
    tok = WordTokenizer(
        WORDS + ["hint", "clue", "Give", "me", "a", "Another", "please"],
        vocab_size=cfg.vocab_size)
    config = Config(
        model=ModelConfig(layer_idx=2, top_k=3, arch="gemma2_tiny",
                          dtype="float32", param_dtype="float32"),
        experiment=ExperimentConfig(seed=0, max_new_tokens=6),
        intervention=InterventionConfig(budgets=(1, 2), random_trials=1,
                                        ranks=(1,), spike_top_k=2),
        output=OutputConfig(save_plots=False),  # one dedicated heatmap below
        word_plurals={w: [w, w + "s"] for w in WORDS},
        prompts=PROMPTS,
    )
    sae = sae_ops.init_random(jax.random.PRNGKey(3), d_model=cfg.hidden_size,
                              d_sae=32)
    return params, cfg, tok, config, sae


def main() -> int:
    params, cfg, tok, config, sae = build_setup()
    from taboo_brittleness_tpu import plots
    from taboo_brittleness_tpu.pipelines import (
        generation, interventions, logit_lens, sae_baseline)
    from taboo_brittleness_tpu.runtime import cache as cache_io

    os.makedirs(FIXTURE_DIR, exist_ok=True)
    processed = os.path.join(FIXTURE_DIR, "processed")
    loader = lambda word: (params, cfg, tok)

    generation.run_generation(
        config, model_loader=loader, words=WORDS, processed_dir=processed)
    print(f"processed cache -> {processed}")

    results = logit_lens.run_evaluation(
        config, tok, words=WORDS, model_loader=loader, processed_dir=processed,
        output_path=os.path.join(FIXTURE_DIR, "logit_lens_results.json"))
    print("LL overall:", json.dumps(results["overall"]))

    # SAE baseline over the cached residuals; a synthetic latent->word map
    # shaped like feature_map.FEATURE_MAP (the real table indexes the 16k
    # Gemma-Scope release and only makes sense with the real SAE).
    fmap = {w: [i] for i, w in enumerate(WORDS)}
    sae_results = sae_baseline.analyze_sae_baseline(
        config, sae, words=WORDS, processed_dir=processed, feature_map=fmap)
    sae_baseline.save_metrics_csv(
        sae_results, os.path.join(FIXTURE_DIR, "baseline_metrics.csv"))
    print("SAE overall:", json.dumps(sae_results["overall"]))

    # One heatmap from the compact [L, T] summary slice.
    arrays, meta = cache_io.load_summary(
        cache_io.summary_path(processed, "moon", 0))
    fig = plots.plot_token_probability(
        arrays["target_prob"], input_words=meta["input_words"],
        start_idx=0, figsize=(11, 5), font_size=10, title_font_size=12,
        tick_font_size=8)
    plots.save_fig(fig, os.path.join(FIXTURE_DIR, "heatmap_moon_prompt01.png"),
                   dpi=72)

    study = interventions.run_intervention_study(
        params, cfg, tok, config, "moon", sae,
        output_path=os.path.join(FIXTURE_DIR, "intervention_moon.json"))
    print("ablation budgets:", sorted(study["ablation"]["budgets"]))
    print(f"fixtures -> {FIXTURE_DIR}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
