"""Synthesize a full-shape Gemma-2 safetensors snapshot on disk.

The real-9B on-ramp (SURVEY.md §7 hard part #1; reference src/models.py:8-53
loads `bcywinski/gemma-2-9b-it-taboo-<word>` from the hub) cannot run here —
no hub egress — so the converter/loader path is proven at 9B *scale* with a
synthetic checkpoint instead (VERDICT r04 next-round #3): same 42-layer ×
3584-hidden × 256k-vocab shapes, same bf16 dtype, same sharded-safetensors
layout (``model-0000N-of-0000M.safetensors`` + index + ``config.json``) that
``models/params.py`` and ``tools/fetch_and_convert.py`` consume from a real
snapshot.

Writes shard-by-shard with bounded memory (one tensor at a time, shards cut
at ~3.5 GB), deterministic under ``--seed``.

Usage::

    python tools/synth_checkpoint.py --out /tmp/synth9b [--preset gemma2_9b]
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, Iterator, Tuple

_SHARD_BYTES = 3.5e9


def hf_tensor_shapes(cfg) -> Iterator[Tuple[str, Tuple[int, ...]]]:
    """(HF key, shape) for every tensor of a Gemma-2 checkpoint, in the
    layer-major order real HF snapshots use.  Shapes are the torch
    ``[out, in]`` convention (models/params.py transposes on load)."""
    D, F = cfg.hidden_size, cfg.intermediate_size
    H, K, Dh = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    yield "model.embed_tokens.weight", (cfg.vocab_size, D)
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        yield p + "input_layernorm.weight", (D,)
        yield p + "mlp.down_proj.weight", (D, F)
        yield p + "mlp.gate_proj.weight", (F, D)
        yield p + "mlp.up_proj.weight", (F, D)
        yield p + "post_attention_layernorm.weight", (D,)
        yield p + "post_feedforward_layernorm.weight", (D,)
        yield p + "pre_feedforward_layernorm.weight", (D,)
        yield p + "self_attn.k_proj.weight", (K * Dh, D)
        yield p + "self_attn.o_proj.weight", (D, H * Dh)
        yield p + "self_attn.q_proj.weight", (H * Dh, D)
        yield p + "self_attn.v_proj.weight", (K * Dh, D)
    yield "model.norm.weight", (D,)


def write_snapshot(out_dir: str, cfg, *, seed: int = 0,
                   shard_bytes: float = _SHARD_BYTES) -> None:
    """Write config.json + sharded bf16 safetensors with bounded memory."""
    import torch
    from safetensors.torch import save_file

    os.makedirs(out_dir, exist_ok=True)
    gen = torch.Generator().manual_seed(seed)

    def synth(name: str, shape) -> "torch.Tensor":
        if name.endswith("norm.weight") or "layernorm" in name:
            # Gemma RMSNorm stores weight-minus-one; zeros = unit scale.
            return torch.zeros(shape, dtype=torch.bfloat16)
        t = torch.empty(shape, dtype=torch.float32)
        t.normal_(std=0.02, generator=gen)
        return t.to(torch.bfloat16)

    # Two passes so shards stream to disk as they fill (peak memory = one
    # shard): pass 1 plans the key->shard split from shapes alone, pass 2
    # synthesizes and writes one shard at a time.
    plan: list = [[]]
    planned_bytes = 0
    for key, shape in hf_tensor_shapes(cfg):
        nbytes = 2  # bf16
        for d in shape:
            nbytes *= d
        if planned_bytes and planned_bytes + nbytes > shard_bytes:
            plan.append([])
            planned_bytes = 0
        plan[-1].append((key, shape))
        planned_bytes += nbytes

    n = len(plan)
    weight_map: Dict[str, str] = {}
    total = 0
    for i, entries in enumerate(plan):
        fname = f"model-{i + 1:05d}-of-{n:05d}.safetensors"
        tensors = {key: synth(key, shape) for key, shape in entries}
        save_file(tensors, os.path.join(out_dir, fname))
        for k, t in tensors.items():
            weight_map[k] = fname
            total += t.numel() * t.element_size()
        del tensors

    with open(os.path.join(out_dir, "model.safetensors.index.json"), "w") as f:
        json.dump({"metadata": {"total_size": total},
                   "weight_map": weight_map}, f)
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump({
            "architectures": ["Gemma2ForCausalLM"],
            "model_type": "gemma2",
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "num_hidden_layers": cfg.num_layers,
            "num_attention_heads": cfg.num_heads,
            "num_key_value_heads": cfg.num_kv_heads,
            "head_dim": cfg.head_dim,
            "intermediate_size": cfg.intermediate_size,
            "sliding_window": cfg.sliding_window,
            "attn_logit_softcapping": cfg.attn_logit_softcap,
            "final_logit_softcapping": cfg.final_logit_softcap,
            "query_pre_attn_scalar": cfg.query_pre_attn_scalar,
            "rope_theta": cfg.rope_theta,
            "rms_norm_eps": cfg.rms_norm_eps,
            "torch_dtype": "bfloat16",
        }, f, indent=1)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", required=True)
    ap.add_argument("--preset", default="gemma2_9b")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    from taboo_brittleness_tpu.models import gemma2

    cfg = gemma2.PRESETS[args.preset]
    write_snapshot(args.out, cfg, seed=args.seed)
    size = sum(os.path.getsize(os.path.join(args.out, f))
               for f in os.listdir(args.out))
    print(f"synthetic {args.preset} snapshot -> {args.out} "
          f"({size / 1e9:.2f} GB)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
