#!/usr/bin/env python
"""Bench regression sentinel: diff the latest ``BENCH_r*.json`` headline
against its predecessor with per-metric tolerance bands.

    python tools/bench_compare.py              # print the comparison table
    python tools/bench_compare.py --check      # exit 1 on regression

``tools/check.sh`` runs ``--check`` next to ``report_bench_row.py --check``:
the report gate keeps the committed table honest, this gate keeps the
committed NUMBERS from silently sliding.  A round whose driver capture
recorded no parseable headline (e.g. round 4's truncated stdout tail —
``"parsed": null``) is skipped with a note, never a crash: the comparison
walks back to the newest round that has a headline.

Tolerances are per-metric, not one blanket percentage: throughput metrics
get a noise band (run-to-run jitter on a shared chip is a few percent),
projections get a wider one, and the obs-overhead metric is held to its
ABSOLUTE <2% contract rather than compared to its predecessor.  A metric
missing from either round is skipped with a note (stages are env-gated and
not every round runs every stage).

stdlib-only on purpose: this must run wherever the BENCH files are.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: metric key (dotted path into the parsed headline) ->
#: (tolerance fraction, higher_is_better, absolute slack).  Regression =
#: the latest value worse than predecessor by more than the band AND by
#: more than the absolute slack — the slack exists for share-type metrics
#: whose healthy value sits near zero (a device-idle share moving
#: 0.01 -> 0.02 is +100% relative but still noise-level idle).
METRICS: Dict[str, Tuple[float, bool, float]] = {
    "value": (0.10, True, 0.0),                  # prompts/sec/chip
    "tflops_per_sec": (0.10, True, 0.0),
    "mfu": (0.10, True, 0.0),
    "measured_study_seconds_per_word": (0.25, False, 0.0),
    "projected_full_sweep_hours": (0.25, False, 0.0),
    "serve_latency.p99_s": (0.50, False, 0.0),
    # TTFT p99 (submit -> first emitted token, ISSUE 19): the interactivity
    # half of the serving SLO — a prefill/admission regression moves it
    # before end-to-end p99 does.
    "serve_latency.ttft_p99": (0.50, False, 0.0),
    "serve_latency.completed_per_second": (0.25, True, 0.0),
    # Fused-loop rollout metrics (bench.py sweep.fused_ab, ISSUE 8):
    # fused-over-legacy launch speedup must not slide back, and the fused
    # arm's measured device-idle (dispatch-gap) share must stay ≈0.
    "fused_ab.fused_speedup": (0.25, True, 0.0),
    "fused_ab.device_idle_share": (0.50, False, 0.02),
    # Speculative-decoding rollout metrics (bench.py sweep.spec_ab, ISSUE
    # 9): the lens-draft speedup over vanilla greedy must not slide back,
    # and the measured acceptance rate is the early-warning signal (a
    # calibration/lens regression shows up here before the speedup moves).
    "spec_ab.spec_speedup": (0.25, True, 0.0),
    "spec_ab.accept_rate": (0.25, True, 0.0),
    # In-serve speculation rollout metrics (bench.py serve_spec_ab, ISSUE
    # 13): the spec-on over spec-off loadgen speedup must not slide back,
    # and the serving accept rate is the same early-warning signal as
    # spec_ab's — a calibration/lens regression moves it first.
    "serve_spec_ab.spec_speedup": (0.25, True, 0.0),
    "serve_spec_ab.accept_rate": (0.25, True, 0.0),
    # Tensor-parallel serving rollout metric (bench.py serve_tp_ab, ISSUE
    # 18): the sharded-over-unsharded loadgen speedup must not slide back.
    # On the CPU smoke's forced-host-device mesh the "speedup" is really a
    # collectives-overhead watermark (< 1 is expected there); the band
    # tracks the trend either way.  Skipped with a note when a round ran
    # without a multi-device mesh.
    "serve_tp_ab.tp_speedup": (0.25, True, 0.0),
    # Elastic-fleet recovery (bench.py fleet_recovery, ISSUE 10): the time
    # from a worker death's lease expiry to the re-issued unit committing
    # must not creep up.  Wide band (±50%): the path crosses subprocess
    # relaunch + poll intervals, so run-to-run jitter is structural.
    "fleet_recovery.recovery_seconds": (0.50, False, 0.0),
    # Replica-serving recovery (bench.py serve_fleet_recovery, ISSUE 17):
    # the time from a replica death's lease expiry to every re-spooled
    # request being answered must not creep up.  Same wide ±50% band as
    # fleet_recovery and for the same reason: the path crosses subprocess
    # relaunch + lease + poll intervals, so run-to-run jitter is
    # structural.
    "serve_fleet_recovery.recovery_seconds": (0.50, False, 0.0),
    # Network front door (bench.py gateway_latency, ISSUE 20): network
    # TTFT p99 through the HTTP+SSE gateway hop must not creep up.  Same
    # wide ±50% band as the other control-plane stages: the path crosses
    # two subprocesses, socket transit and tail-poll intervals, so
    # run-to-run jitter is structural.  Skipped with a note on rounds that
    # ran without the stage (BENCH_GATEWAY=0).
    "gateway_latency.ttft_p99": (0.50, False, 0.0),
    # Base-resident delta switch (bench.py delta_switch, ISSUE 12): the
    # word-switch latency over the resident base must not creep up (wide
    # ±50% band: the path crosses filesystem reads, so run-to-run jitter is
    # structural), and the delta-vs-full artifact byte ratio is the IO-win
    # early-warning signal — a codec regression that stops deltas being
    # sparse shows up here before latency moves.
    "delta_switch.switch_ms": (0.50, False, 0.0),
    "delta_switch.delta_bytes_ratio": (0.25, False, 0.0),
    # Gemma-Scope grid sweep (bench.py grid_sweep, ISSUE 14): committed
    # grid cells per hour through the REAL fleet path (capture-once decode
    # + per-cell fleet units over subprocess workers) must not slide back.
    "grid_sweep.cells_per_hour": (0.25, True, 0.0),
    # Closed-loop attack search (same bench stage, attack_search headline):
    # evolved-attack break rate over the synthetic engine.  Absolute slack:
    # the healthy CPU-smoke value sits at/near zero (the tiny random model
    # rarely emits the secret), so a 0.00 -> 0.05 wiggle is noise, not a
    # regression signal.
    "attack_search.break_rate": (0.25, True, 0.05),
}

#: Absolute-budget metrics: (max allowed value).  Checked on the LATEST
#: round only — the contract is a budget, not a trend.
ABSOLUTE_BUDGETS: Dict[str, float] = {
    "obs_overhead_pct": 2.0,                     # the obs <2% wall contract
    # Same contract with the LIVE sampler armed (ISSUE 15): windowed
    # metrics spool + SLO burn engine + flight recorder at a 0.5 s window.
    "obs_live.overhead_pct": 2.0,
}


def _get(d: Dict[str, Any], dotted: str) -> Optional[float]:
    cur: Any = d
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


def load_rounds(repo: str) -> List[Tuple[int, Optional[Dict[str, Any]], str]]:
    """Every BENCH_r*.json as (round number, parsed headline or None, path),
    sorted by round."""
    rounds = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError):
            rounds.append((int(m.group(1)), None, path))
            continue
        rounds.append((int(d.get("n", int(m.group(1)))), d.get("parsed"),
                       path))
    rounds.sort(key=lambda r: r[0])
    return rounds


def compare(repo: str = REPO) -> Tuple[List[str], List[str], int]:
    """(report lines, regression lines, exit code).  Exit 0 when there is
    nothing comparable (fewer than two parseable rounds) — an absent bench
    is not a regression."""
    rounds = load_rounds(repo)
    lines: List[str] = []
    regressions: List[str] = []
    parseable = [(n, p, path) for n, p, path in rounds if p]
    skipped = [(n, path) for n, p, path in rounds if not p]
    for n, path in skipped:
        lines.append(f"round {n}: no parseable headline "
                     f"({os.path.basename(path)} — truncated capture?); "
                     "skipped")
    if not parseable:
        lines.append("no parseable BENCH_r*.json headlines; nothing to check")
        return lines, regressions, 0
    latest_n, latest, _ = parseable[-1]
    if rounds and rounds[-1][0] != latest_n:
        lines.append(f"latest round {rounds[-1][0]} has no headline; "
                     f"comparing newest parseable round {latest_n} instead")
    if len(parseable) < 2:
        lines.append(f"round {latest_n}: first parseable round; "
                     "nothing to compare against")
    else:
        prev_n, prev, _ = parseable[-2]
        lines.append(f"comparing round {latest_n} against round {prev_n}:")
        for key, (tol, higher, slack) in METRICS.items():
            a, b = _get(prev, key), _get(latest, key)
            if a is None or b is None:
                which = [w for w, v in (("previous", a), ("latest", b))
                         if v is None]
                lines.append(f"  {key:<44} skipped (absent in "
                             f"{'/'.join(which)})")
                continue
            delta = (b - a) / a if a else 0.0
            bad = ((b < a * (1.0 - tol) - slack) if higher
                   else (b > a * (1.0 + tol) + slack))
            verdict = "REGRESSION" if bad else "ok"
            lines.append(
                f"  {key:<44} {a:>10.4g} -> {b:>10.4g}  "
                f"({delta:+.1%}, band ±{tol:.0%} "
                f"{'higher' if higher else 'lower'}-is-better)  {verdict}")
            if bad:
                regressions.append(
                    f"{key}: {a:.4g} -> {b:.4g} ({delta:+.1%}) exceeds the "
                    f"{tol:.0%} band")
    for key, budget in ABSOLUTE_BUDGETS.items():
        v = _get(latest, key)
        if v is None:
            lines.append(f"  {key:<44} skipped (absent in latest)")
            continue
        bad = v > budget
        lines.append(f"  {key:<44} {v:>10.4g} (budget <= {budget:g})  "
                     f"{'REGRESSION' if bad else 'ok'}")
        if bad:
            regressions.append(f"{key}: {v:.4g} exceeds the absolute budget "
                               f"{budget:g}")
    return lines, regressions, 1 if regressions else 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero on regression (the check.sh gate); "
                         "default prints the table and exits 0")
    ap.add_argument("--repo", default=REPO,
                    help="directory holding BENCH_r*.json (tests)")
    args = ap.parse_args(argv)
    lines, regressions, rc = compare(args.repo)
    for line in lines:
        print(line)
    if regressions:
        print(f"bench_compare: {len(regressions)} regression(s)",
              file=sys.stderr)
        for r in regressions:
            print(f"  {r}", file=sys.stderr)
    else:
        print("bench_compare: no regressions")
    return rc if args.check else 0


if __name__ == "__main__":
    sys.exit(main())
