"""Ground the v5e-8 derate model's ICI terms in compiled HLO (VERDICT r04 #7).

``bench.py``'s ``v5e8_derate_model`` charges tp collectives analytically
(2 all-reduces per layer of the bf16 activation payload).  This tool compiles
the sweep's measurement programs for the REAL dp=2 x tp=4 mesh (8 virtual CPU
devices — GSPMD partitioning is platform-independent) at the production 9B
launch shapes, extracts every collective op + operand shape from the
optimized HLO, and writes ``results/hlo_collectives.json`` with a
bytes-moved-per-chip column (ring model) next to the analytic numbers.
``bench.py`` attaches this file to the derate model when present.

While loops are parsed structurally: each body's collectives multiply by the
loop's ``known_trip_count`` (the rolled 42-layer scan and the decode's step
loop compose); the decode's unknown-trip generation loop charges the full
token budget.

Usage::

    python tools/hlo_collectives.py [--out results/hlo_collectives.json]
"""

from __future__ import annotations

import argparse
import json
import os
import re

# v5e ICI per-link bandwidth and the ring all-reduce chip-bytes factor —
# keep in sync with bench.py.
ICI_LINK_BW = 45e9
_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
                "s64": 8, "u64": 8, "pred": 1, "s8": 1, "u8": 1, "s16": 2,
                "u16": 2}

# A collective INSTRUCTION line: "%name = <shape-or-tuple> <op>(operands...".
# Matches the async "-start" form and tuple-shaped variadic ops (the payload
# is the sum of every component shape before the opcode); "-done" lines are
# skipped (their shape repeats the started op's payload).
_COLL_RE = re.compile(
    r"=\s+(?P<shape>.+?)\s"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|collective-permute)"
    r"(?P<start>-start)?\(")
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")


def _shape_bytes(shape_str: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def _group_size(line: str, all_devices: int) -> int:
    """Group size from either replica_groups format: explicit
    ``{{0,1,2,3},{4,5,6,7}}``, iota-v2 ``[num_groups,group_size]<=[N]``, or
    the empty-``{}`` all-devices shorthand (→ ``all_devices``)."""
    m = re.search(r"replica_groups=\{\{([0-9, ]+)\}", line)
    if m:
        return len([x for x in m.group(1).split(",") if x.strip()])
    m = re.search(r"replica_groups=\[(\d+),(\d+)\]", line)
    if m:
        return int(m.group(2))
    # replica_groups={} (or absent): every device participates.
    return all_devices


def collectives_in_hlo(hlo_text: str, *, default_trip: int = 1,
                       all_devices: int = 8) -> list:
    """Every collective instruction with payload bytes, group size, and its
    EXECUTION MULTIPLICITY: while-loops are parsed structurally (computation
    blocks + ``body=%...`` edges) and each body's collectives multiply by the
    loop's ``known_trip_count`` — the rolled layer scan (42x) and the decode
    step loop compose.  A while with no known trip count (the decode's
    early-exit generation loop) charges ``default_trip`` iterations.

    Raises if a line MENTIONS a collective opcode as an instruction but the
    payload parse comes up empty — silent under-extraction would otherwise be
    recorded as evidence (`-done` halves of async pairs are skipped by
    design; their shape repeats the `-start` payload)."""
    comps: dict = {}
    entry = None
    current = None
    for line in hlo_text.splitlines():
        m = re.match(r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(", line)
        if m and line.rstrip().endswith("{"):
            current = m.group(2)
            comps[current] = {"collectives": [], "whiles": []}
            if m.group(1):
                entry = current
            continue
        if current is None:
            continue
        if line.startswith("}"):
            current = None
            continue
        cm = _COLL_RE.search(line)
        if cm:
            payload = _shape_bytes(cm.group("shape"))
            if payload <= 0:
                raise ValueError(
                    "collective instruction with unparseable payload shape "
                    f"(evidence would silently under-count): {line.strip()[:200]}")
            comps[current]["collectives"].append({
                "op": cm.group("op"),
                "payload_bytes": payload,
                "group_size": _group_size(line, all_devices),
            })
            continue
        if re.search(r"\s[a-z-]*(all-reduce|all-gather|reduce-scatter|"
                     r"collective-permute)-done\(", line):
            continue                    # async completion: payload counted at -start
        if " while(" in line:
            bm = re.search(r"body=%?([\w.\-]+)", line)
            tm = re.search(r'"known_trip_count":\{"n":"(\d+)"\}', line)
            if bm:
                comps[current]["whiles"].append(
                    (bm.group(1), int(tm.group(1)) if tm else default_trip))

    # Propagate multiplicities from the entry through while-body edges.
    mult = {entry: 1}
    frontier = [entry]
    while frontier:
        c = frontier.pop()
        for body, trip in comps.get(c, {}).get("whiles", ()):
            m_new = mult[c] * trip
            if mult.get(body, 0) < m_new:
                mult[body] = m_new
                frontier.append(body)

    out = []
    for name, comp in comps.items():
        m_c = mult.get(name)
        if m_c is None:
            # Not reachable through while edges from entry: a conditional
            # branch or called computation — charge it once (upper bound of
            # interest is the steady loop body anyway).
            m_c = 1 if comp["collectives"] else 0
        for c in comp["collectives"]:
            out.append({**c, "multiplicity": m_c})
    return out


def ring_chip_bytes(payload: int, n: int) -> float:
    """Ring all-reduce moves 2*(n-1)/n of the payload per chip; gather /
    scatter / permute move (n-1)/n / (n-1)/n / 1x respectively."""
    if n <= 1:
        return 0.0
    return 2 * (n - 1) / n * payload


def summarize(name: str, hlo_text: str, *, default_trip: int = 1,
              all_devices: int = 8) -> dict:
    colls = collectives_in_hlo(hlo_text, default_trip=default_trip,
                               all_devices=all_devices)
    per_op: dict = {}
    total_chip_bytes = 0.0
    for c in colls:
        mult = c["multiplicity"]
        n = c["group_size"]
        if c["op"] == "all-reduce":
            chip = ring_chip_bytes(c["payload_bytes"], n)
        elif c["op"] in ("all-gather", "reduce-scatter"):
            chip = (n - 1) / max(n, 1) * c["payload_bytes"]
        else:
            chip = float(c["payload_bytes"])
        key = f"{c['op']}[g{n}]"
        agg = per_op.setdefault(key, {"count": 0, "payload_bytes": 0,
                                      "chip_bytes": 0.0})
        agg["count"] += mult
        agg["payload_bytes"] += c["payload_bytes"] * mult
        agg["chip_bytes"] += chip * mult
        total_chip_bytes += chip * mult
    return {
        "program": name,
        "collective_ops": per_op,
        "total_chip_bytes": total_chip_bytes,
        "ici_seconds_ring_model": total_chip_bytes / ICI_LINK_BW,
        "default_trip_for_unknown_loops": default_trip,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if "tools" in os.path.dirname(os.path.abspath(__file__)) else ".",
        "results", "hlo_collectives.json"))
    ap.add_argument("--rows", type=int, default=330)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--new-tokens", type=int, default=50)
    ap.add_argument("--skip-decode", action="store_true",
                    help="skip the (slow to compile) decode program")
    args = ap.parse_args()

    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=8")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from taboo_brittleness_tpu.config import MeshConfig
    from taboo_brittleness_tpu.models import gemma2
    from taboo_brittleness_tpu.parallel import mesh as meshlib
    from taboo_brittleness_tpu.pipelines.interventions import (
        _nll_cached_jit, _residual_measure)
    from taboo_brittleness_tpu.runtime import decode

    cfg9 = gemma2.PRESETS["gemma2_9b"]
    mesh = meshlib.make_mesh(MeshConfig(dp=2, tp=4, sp=1),
                             devices=jax.devices("cpu")[:8])

    def sds(shape, dtype, spec):
        return jax.ShapeDtypeStruct(shape, dtype,
                                    sharding=NamedSharding(mesh, spec))

    shapes = jax.eval_shape(lambda k: gemma2.init_params(k, cfg9),
                            jax.random.PRNGKey(0))
    p_sds = jax.tree_util.tree_map(
        lambda s, spec: sds(s.shape, s.dtype, spec),
        shapes, meshlib.param_specs(cfg9),
        is_leaf=lambda x: isinstance(x, P))

    rows = args.rows
    Tp, new = args.prompt_len, args.new_tokens
    T = Tp + new
    s = Tp - 1
    L, K, Dh = cfg9.num_layers, cfg9.num_kv_heads, cfg9.head_dim

    seqs = sds((rows, T), jnp.int32, P("dp", None))
    mask = sds((rows, T), jnp.bool_, P("dp", None))
    pos = sds((rows, T), jnp.int32, P("dp", None))
    resid = sds((rows, T, cfg9.hidden_size), jnp.float32, P("dp", None, None))
    tgt = sds((rows,), jnp.int32, P("dp"))
    cache_sds = (
        sds((L, rows, s, K, Dh), jnp.bfloat16, P(None, "dp", None, "tp", None)),
        sds((L, rows, s, K, Dh), jnp.bfloat16, P(None, "dp", None, "tp", None)),
        sds((rows, s), jnp.bool_, P("dp", None)),
    )

    results = []

    print("compiling readout (9B, tp=4 x dp=2, "
          f"{rows} rows)...", flush=True)
    readout = _residual_measure.lower(
        p_sds, cfg9, resid, seqs, mask, tgt, top_k=10,
        resp_start=s).compile()
    results.append(summarize("readout", readout.as_text()))

    print("compiling nll (cached continuation)...", flush=True)
    nll = _nll_cached_jit.lower(
        p_sds, cfg9, *cache_sds, seqs, mask, pos, mask,
        resp_start=s).compile()
    results.append(summarize("nll", nll.as_text()))

    if not args.skip_decode:
        print("compiling decode (while-loop program)...", flush=True)
        pids = sds((rows, Tp), jnp.int32, P("dp", None))
        pvalid = sds((rows, Tp), jnp.bool_, P("dp", None))
        ppos = sds((rows, Tp), jnp.int32, P("dp", None))
        dec = decode.greedy_decode.lower(
            p_sds, cfg9, pids, pvalid, ppos, max_new_tokens=new,
            capture_residual_layer=31,
            return_prefill_cache=True).compile()
        # The generation while has no known trip count (early exit); charge
        # the full budget, matching the bench's fixed-length decode.
        results.append(summarize("decode", dec.as_text(),
                                 default_trip=new))

    out = {
        "mesh": "dp=2 x tp=4 (8 virtual CPU devices; GSPMD partitioning is "
                "platform-independent)",
        "model": "gemma2_9b",
        "launch": {"rows": rows, "prompt_len": Tp, "new_tokens": new},
        "ici_link_bw": ICI_LINK_BW,
        "programs": results,
        "note": "chip_bytes = ring-model bytes per chip "
                "(2(n-1)/n x payload for all-reduce); collectives inside "
                "while bodies multiply by the loops' known_trip_count "
                "(nested loops compose; the decode's unknown-trip generation "
                "loop charges the full token budget)",
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    for r in results:
        print(f"{r['program']}: {r['total_chip_bytes'] / 1e6:.1f} MB/chip "
              f"-> {r['ici_seconds_ring_model'] * 1e3:.2f} ms over ICI")
    print(f"-> {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
