#!/usr/bin/env python
"""Derive the exec-summary throughput table from the driver-captured
``BENCH_r*.json`` files — byte-for-byte, so the report can never drift from
the committed artifacts again (VERDICT r05 #7: the round-5 row said 23.27
while BENCH_r05.json said 23.375; rounds 3's row had the same disease).

Usage:
    python tools/report_bench_row.py                 # print the markdown rows
    python tools/report_bench_row.py --check FILE    # exit 1 unless FILE
                                                     # contains every row
                                                     # byte-for-byte

The --check mode is the sync gate: ``tools/check.sh`` runs it against
``reports/exec_summary/executive_summary.md``.  A round whose driver capture
recorded no parseable headline (e.g. round 4's truncated stdout tail) renders
as em-dashes — the table only ever claims what a committed artifact backs.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HEADER = "| Round | prompts/sec/chip | vs reference est. (0.07/s) | TFLOP/s | MFU |"
RULE = "|---|---|---|---|---|"


def _fmt(value, pattern: str) -> str:
    return pattern.format(value) if value is not None else "—"


def bench_rows(repo: str = REPO) -> List[str]:
    """One markdown row per BENCH_r*.json, in round order."""
    rows = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        with open(path) as f:
            d = json.load(f)
        p = d.get("parsed") or {}
        n = d.get("n", int(m.group(1)))
        mfu = p.get("mfu")
        rows.append(
            f"| {n} "
            f"| {_fmt(p.get('value'), '{:.2f}')} "
            f"| {_fmt(p.get('vs_baseline') and round(p['vs_baseline']), '{}x')} "
            f"| {_fmt(p.get('tflops_per_sec'), '{:.1f}')} "
            f"| {_fmt(mfu and mfu * 100, '{:.1f}%')} |")
    return rows


def check(report_path: str, rows: List[str]) -> int:
    with open(report_path) as f:
        text = f.read()
    missing = [r for r in rows if r not in text]
    if missing:
        print(f"{report_path} is out of sync with BENCH_r*.json; "
              "missing rows (regenerate with tools/report_bench_row.py):",
              file=sys.stderr)
        for r in missing:
            print(f"  {r}", file=sys.stderr)
        return 1
    print(f"{report_path}: all {len(rows)} bench rows in sync")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", metavar="FILE",
                    help="verify FILE contains every derived row byte-for-byte")
    args = ap.parse_args(argv)
    rows = bench_rows()
    if args.check:
        return check(args.check, rows)
    print(HEADER)
    print(RULE)
    for r in rows:
        print(r)
    return 0


if __name__ == "__main__":
    sys.exit(main())
