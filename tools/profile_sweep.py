"""Profile the intervention sweep's compiled phases on the current device.

The round-4 decode win (the per-step KV-stack copies, 22% of the phase) was
found with exactly this flow: run one launch under ``jax.profiler.trace``,
then rank the trace's complete events by total duration.  Keep using it —
"what does the while-loop body actually spend time on" is unanswerable from
wall-clock timings alone.

Usage (real chip)::

    PYTHONPATH=/root/repo:/root/.axon_site python tools/profile_sweep.py \
        [--rows 330] [--phase decode|readout|nll] [--trace-dir /tmp/tbx_prof]

Prints the top trace events by accumulated device time.  The raw trace stays
in --trace-dir for TensorBoard / xprof.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os

import numpy as np


def _top_events(trace_dir: str, top: int = 20):
    files = sorted(glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                             recursive=True))
    if not files:
        raise SystemExit(f"no trace written under {trace_dir}")
    with gzip.open(files[-1]) as fh:
        tr = json.load(fh)
    tot: collections.Counter = collections.Counter()
    cnt: collections.Counter = collections.Counter()
    for e in tr["traceEvents"]:
        if e.get("ph") == "X" and "dur" in e:
            tot[e.get("name", "?")] += e["dur"]
            cnt[e.get("name", "?")] += 1
    return [(name, us / 1e6, cnt[name]) for name, us in tot.most_common(top)]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=330,
                    help="launch rows (default: the production 33-arm shape)")
    ap.add_argument("--phase", choices=("decode", "readout", "nll"),
                    default="decode")
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--new-tokens", type=int, default=50)
    ap.add_argument("--trace-dir", default="/tmp/tbx_prof")
    ap.add_argument("--top", type=int, default=20)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from taboo_brittleness_tpu.models import gemma2
    from taboo_brittleness_tpu.ops import sae as sae_ops
    from taboo_brittleness_tpu.pipelines import interventions as iv
    from taboo_brittleness_tpu.runtime import decode

    on_accel = jax.default_backend() != "cpu"
    cfg = gemma2.PRESETS["gemma2_bench" if on_accel else "gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(0), cfg)
    sae = sae_ops.init_random(jax.random.PRNGKey(1), cfg.hidden_size, 16384)
    tap = min(31, cfg.num_layers - 1)
    rng = np.random.default_rng(1)
    rows = args.rows
    prompts = [list(rng.integers(1, cfg.vocab_size, size=args.prompt_len))
               for _ in range(rows)]
    padded, valid, positions = decode.pad_prompts(prompts)
    ins = (jnp.asarray(padded), jnp.asarray(valid), jnp.asarray(positions))
    ep = {"sae": sae,
          "latent_ids": jnp.asarray(
              rng.integers(0, 16384, size=(rows, 32)), jnp.int32),
          "layer": tap}
    resp_start = args.prompt_len - 1

    def run_decode():
        d = decode.greedy_decode(
            params, cfg, *ins, max_new_tokens=args.new_tokens,
            edit_fn=iv.sae_ablation_edit, edit_params=ep, stop_ids=(-1,),
            capture_residual_layer=tap, return_prefill_cache=True)
        jax.block_until_ready(d.tokens)
        return d

    dec = run_decode()                       # compile + inputs for downstream
    layout = decode.response_layout_device(dec)

    def run_readout():
        out = iv._residual_measure(
            params, cfg, dec.residual, layout.sequences, layout.response_mask,
            jnp.zeros((rows,), jnp.int32), top_k=5, resp_start=resp_start)
        jax.block_until_ready(out["agg_ids"])

    def run_nll():
        pos2 = jnp.maximum(jnp.cumsum(dec.sequence_valid, 1) - 1, 0)
        pos2 = pos2.astype(jnp.int32)
        nm = jnp.zeros_like(dec.sequence_valid).at[:, resp_start:-1].set(True)
        nll = iv._nll_cached_jit(
            params, cfg, *dec.prefill_cache,
            dec.sequences, dec.sequence_valid, pos2, nm,
            edit_fn=iv.sae_ablation_edit,
            edit_params={**ep, "chunk_positions": pos2[:, resp_start:]},
            resp_start=resp_start)
        jax.block_until_ready(nll)

    fn = {"decode": run_decode, "readout": run_readout, "nll": run_nll}[args.phase]
    fn()                                      # compile the chosen phase
    with jax.profiler.trace(args.trace_dir):
        fn()

    print(f"top {args.top} events for ONE {args.phase} launch at {rows} rows:")
    for name, sec, n in _top_events(args.trace_dir, args.top):
        print(f"  {sec:8.4f}s  x{n:5d}  {name[:90]}")
    print(f"raw trace -> {args.trace_dir}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
