#!/usr/bin/env python
"""Deprecated shim: folded into ``python -m taboo_brittleness_tpu profile``
(the device half of ``taboo_brittleness_tpu/obs/profile.py``).

    PYTHONPATH=/root/repo python tools/profile_sweep.py \
        [--rows 330] [--phase decode|readout|nll] [--trace-dir DIR] [--top N]

forwards verbatim to the CLI entry point, which additionally writes the
parsed ``_device_profile.json`` artifact when asked (``--out``) and shares
its parser with ``tools/trace_report.py --device``.
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from taboo_brittleness_tpu.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["profile", *sys.argv[1:]]))
