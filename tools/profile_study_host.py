#!/usr/bin/env python
"""Deprecated shim: folded into ``python -m taboo_brittleness_tpu profile
--study-host`` (``StageTimers`` + the driver now live in
``taboo_brittleness_tpu/obs/profile.py``).

    PYTHONPATH=/root/repo python tools/profile_study_host.py \
        [--words 2] [--prompt-len 32] [--new-tokens 50]

forwards verbatim to the CLI entry point.
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from taboo_brittleness_tpu.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["profile", "--study-host", *sys.argv[1:]]))
