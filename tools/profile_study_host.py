"""Host-side wall-clock breakdown of ONE real study word (VERDICT r04 #1).

``bench.py``'s study block measures ~17-18 s/word at the bench shape against
a ~10.6 s device-time projection — a 1.7x host-overhead ratio.  This tool
attributes that gap: it runs the REAL ``run_intervention_studies`` driver on
synthetic bench-shape words (same setup as ``bench._study_bench``) with every
interesting stage wrapped in a nested wall-clock timer, and prints a
self-time-ranked tree.  Device waits show up inside whichever stage blocks
(``_collect_rows`` pulls, the baseline pass's syncs), so the report separates
"the device was busy" from "the host was busy" when read next to the sweep
bench's per-phase device seconds (results/bench_detail.json).

Usage (real chip)::

    PYTHONPATH=/root/repo:/root/.axon_site python tools/profile_study_host.py \
        [--words 2] [--prompt-len 32] [--new-tokens 50]

The first word pays all compiles; per-word numbers print separately so the
steady state is readable on its own.
"""

from __future__ import annotations

import argparse
import functools
import time
from typing import Dict, List

import numpy as np


class StageTimers:
    """Nested wall-clock timers with self-time attribution.

    ``wrap(mod, name)`` monkeypatches ``mod.name`` with a timed version;
    nesting is tracked on a stack so a parent's self-time excludes its timed
    children (e.g. ``prepare_word_state`` minus its ``_residual_measure``).
    """

    def __init__(self) -> None:
        self.total: Dict[str, float] = {}
        self.self_time: Dict[str, float] = {}
        self.count: Dict[str, int] = {}
        self._stack: List[List] = []   # [name, t0, child_seconds]

    def _enter(self, name: str) -> None:
        self._stack.append([name, time.perf_counter(), 0.0])

    def _exit(self) -> None:
        name, t0, child = self._stack.pop()
        dt = time.perf_counter() - t0
        self.total[name] = self.total.get(name, 0.0) + dt
        self.self_time[name] = self.self_time.get(name, 0.0) + dt - child
        self.count[name] = self.count.get(name, 0) + 1
        if self._stack:
            self._stack[-1][2] += dt

    def wrap(self, mod, name: str, label: str = None) -> None:
        label = label or name
        fn = getattr(mod, name)

        @functools.wraps(fn)
        def timed(*a, **kw):
            self._enter(label)
            try:
                return fn(*a, **kw)
            finally:
                self._exit()

        setattr(mod, name, timed)

    def snapshot(self):
        return dict(self.total), dict(self.self_time), dict(self.count)

    def reset(self) -> None:
        self.total.clear()
        self.self_time.clear()
        self.count.clear()

    def report(self, wall: float, title: str) -> None:
        print(f"\n== {title} (wall {wall:.2f}s) ==")
        print(f"  {'stage':42s} {'total':>8s} {'self':>8s} {'calls':>6s}")
        for name in sorted(self.self_time, key=self.self_time.get,
                           reverse=True):
            print(f"  {name:42s} {self.total[name]:8.3f} "
                  f"{self.self_time[name]:8.3f} {self.count[name]:6d}")
        accounted = sum(self.total[n] for n in self.total
                        if self.count[n] and n.startswith("word:"))
        untimed = wall - accounted
        if abs(untimed) > 0.01:
            print(f"  {'(outside timed stages)':42s} {untimed:8.3f}")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--words", type=int, default=2)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--new-tokens", type=int, default=50)
    args = ap.parse_args()

    import jax

    from taboo_brittleness_tpu.runtime import jax_cache

    jax_cache.enable()

    from taboo_brittleness_tpu.config import (
        Config, ExperimentConfig, InterventionConfig, ModelConfig)
    from taboo_brittleness_tpu.models import gemma2
    from taboo_brittleness_tpu.ops import lens, projection, sae as sae_ops
    from taboo_brittleness_tpu.pipelines import interventions as iv
    from taboo_brittleness_tpu.runtime import decode
    from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

    on_accel = jax.default_backend() != "cpu"
    cfg = gemma2.PRESETS["gemma2_bench" if on_accel else "gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(0), cfg)
    sae = sae_ops.init_random(jax.random.PRNGKey(2), cfg.hidden_size, 16384)
    tap = min(31, cfg.num_layers - 1)

    words = [f"profword{i}" for i in range(args.words)]
    lex = [f"w{i:02d}" for i in range(64)]
    tok = WordTokenizer(words + lex, vocab_size=cfg.vocab_size)
    rng = np.random.default_rng(7)
    prompts = [" ".join(rng.choice(lex, size=max(args.prompt_len - 8, 2)))
               for _ in range(10)]
    config = Config(
        model=ModelConfig(layer_idx=tap, top_k=5, arch=cfg_name(cfg),
                          dtype="bfloat16", param_dtype="bfloat16"),
        experiment=ExperimentConfig(seed=0, max_new_tokens=args.new_tokens,
                                    pad_to_multiple=args.prompt_len),
        intervention=InterventionConfig(),
        word_plurals={w: [w] for w in words},
        prompts=prompts,
    )

    t = StageTimers()
    # Stage wrappers, outer to inner.  _dispatch_rows is pure enqueue (host
    # trace + transfer time); _collect_rows blocks on the device queue.
    t.wrap(iv, "prepare_word_state")
    t.wrap(iv, "score_latents_for_word")
    t.wrap(iv, "plan_ablation_sweep")
    t.wrap(iv, "plan_projection_sweep")
    t.wrap(iv, "measure_arm_sets")
    t.wrap(iv, "_dispatch_rows")
    t.wrap(iv, "_residual_measure", "residual_measure(dispatch)")
    t.wrap(iv, "_decode_guess_rows")
    t.wrap(iv, "_tile_rows_ep")
    t.wrap(iv, "_atomic_json_dump", "json_dump")
    t.wrap(iv.metrics_mod, "calculate_metrics")
    t.wrap(iv.metrics_mod, "leak_rate")
    t.wrap(projection, "principal_subspace")
    t.wrap(decode, "generate", "decode.generate(dispatch)")
    t.wrap(decode, "decode_texts", "decode_texts(host work)")
    t.wrap(decode, "texts_from_tokens", "texts_from_tokens(host)")
    t.wrap(decode, "response_layout_device")
    t.wrap(lens, "spike_positions_batch", "spike_positions(dispatch)")

    # Split _collect_rows into device-wait vs host work: block on every
    # in-flight output FIRST under a wait timer, so the wrapped inner stages
    # measure pure host time.  (This serializes what the real collect
    # overlaps, so per-stage attribution is exact while the word wall-clock
    # stays within ~the overlap window of the real run.  Set
    # TBX_PROFILE_NO_SPLIT=1 to time the real overlapped collect instead.)
    import os as _os

    split = _os.environ.get("TBX_PROFILE_NO_SPLIT", "0") != "1"
    real_collect = iv._collect_rows

    def collect_split(tok_, config_, state_, handle):
        t._enter("collect.device_wait")
        try:
            jax.block_until_ready((handle["dec"].tokens,
                                   handle["edited_nll"],
                                   handle["out"]["agg_ids"]))
        finally:
            t._exit()
        t._enter("collect.host")
        try:
            return real_collect(tok_, config_, state_, handle)
        finally:
            t._exit()

    if split:
        iv._collect_rows = collect_split
    else:
        t.wrap(iv, "_collect_rows")

    def model_loader(word):
        return params, cfg, tok

    import shutil
    import tempfile

    out_dir = tempfile.mkdtemp(prefix="tbx_prof_study_")
    try:
        for i, w in enumerate(words):
            t.reset()
            t._enter(f"word:{w}")
            t0 = time.perf_counter()
            iv.run_intervention_studies(
                config, model_loader=model_loader, sae=sae, words=[w],
                output_dir=out_dir)
            wall = time.perf_counter() - t0
            t._exit()
            t.report(wall, f"word {i} ({'compile' if i == 0 else 'steady'})")
    finally:
        shutil.rmtree(out_dir, ignore_errors=True)
    return 0


def cfg_name(cfg) -> str:
    from taboo_brittleness_tpu.models import gemma2

    for k, v in gemma2.PRESETS.items():
        if v is cfg:
            return k
    raise KeyError("unknown preset")


if __name__ == "__main__":
    raise SystemExit(main())
