#!/usr/bin/env python
"""Regenerate the committed multi-worker fleet fixtures
(tests/fixtures/obs/fleet/ and tests/fixtures/obs/serve_fleet/).

Runs a REAL chaos fleet — tiny model, 3 subprocess workers, worker ``w1``
killed by a ``die`` fault at its first commit (``runtime.fleet.selfcheck``,
the same scenario ``tbx fleet --selfcheck`` gates) — then copies the merged
``_events.jsonl``, the per-worker ``_events.<wid>.jsonl`` streams, the
merged ``_failures.json``, the windowed metrics spool (``_metrics*.jsonl``),
the per-worker progress heartbeats (``_progress*.json``), and the crash
flight-recorder dump (``_flightrec*.json``) into the fixture directory.
The fleet's ``die`` fault is deliberately dump-free (``os._exit``, the
SIGKILL-equivalent the crash-consistency tests depend on), so the flight
recorder is exercised here through its other real trigger: a quarantined
word (``resilience.run_guarded`` with an exhausted retry policy) freezes
the ring to ``_flightrec.json``.  The committed files are what
``trace_report --check`` and ``tbx top --once --selfcheck`` hold the fleet
schema to (tools/check.sh), so the fleet event vocabulary, the metrics
conservation invariants, and the merge rules cannot drift silently.

The serve_fleet fixture is regenerated the same way from the replica
serving chaos smoke (``serve.replica.selfcheck``, the scenario
``tbx serve-fleet --selfcheck`` gates): replica ``w1`` killed at its first
response commit, every request healed through the lease-expiry→re-spool
path.  ``tbx top --once --selfcheck`` renders it and asserts replica lanes
plus the serve-fleet summary line.

    JAX_PLATFORMS=cpu python tools/make_fleet_fixture.py
"""

from __future__ import annotations

import glob
import os
import shutil
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

FIXTURE_DIR = os.path.join(_REPO, "tests", "fixtures", "obs", "fleet")
SERVE_FLEET_FIXTURE_DIR = os.path.join(_REPO, "tests", "fixtures", "obs",
                                       "serve_fleet")

_COPY_PATTERNS = ("_events*.jsonl", "_metrics*.jsonl", "_progress*.json",
                  "_flightrec*.json")


def _copy_artifacts(out: str, fixture_dir: str,
                    extra_files: tuple = ("_failures.json",)) -> list:
    os.makedirs(fixture_dir, exist_ok=True)
    for pat in _COPY_PATTERNS + extra_files:
        for old in glob.glob(os.path.join(fixture_dir, pat)):
            os.unlink(old)
    copied = []
    for pat in _COPY_PATTERNS:
        for src in sorted(glob.glob(os.path.join(out, pat))):
            dst = os.path.join(fixture_dir, os.path.basename(src))
            shutil.copyfile(src, dst)
            copied.append(dst)
    for name in extra_files:
        src = os.path.join(out, name)
        if os.path.exists(src):
            dst = os.path.join(fixture_dir, name)
            shutil.copyfile(src, dst)
            copied.append(dst)
    return copied


def _make_serve_fleet_fixture() -> int:
    from taboo_brittleness_tpu.serve import replica as replica_mod

    out = tempfile.mkdtemp(prefix="tbx_serve_fleet_fixture_")
    verdict = replica_mod.selfcheck(os.path.join(out, "fleet"))
    res = verdict["result"]
    print(f"serve-fleet run: {res['status']}, {res['completed']} answered, "
          f"{res['respooled']} re-spooled, "
          f"{res['lease_expiries']} lease expirie(s)")
    if not verdict["ok"]:
        print(f"make_fleet_fixture: serve-fleet chaos smoke FAILED: "
              f"{verdict['problems']}", file=sys.stderr)
        return 1
    copied = _copy_artifacts(os.path.join(out, "fleet"),
                             SERVE_FLEET_FIXTURE_DIR,
                             extra_files=("_failures.json",
                                          "_serve_fleet.json"))
    for p in copied:
        print(f"  -> {os.path.relpath(p, _REPO)}")

    import trace_report

    rc = trace_report.main(
        ["--check",
         os.path.join(SERVE_FLEET_FIXTURE_DIR, "_events.jsonl")])
    if rc != 0:
        print("make_fleet_fixture: regenerated serve_fleet fixture FAILS "
              "trace_report --check", file=sys.stderr)
        return rc

    # The request-trace assembler gate (tbx trace --selfcheck) must hold on
    # the regenerated fixture too: waterfalls render, attempt chains are
    # coherent, TTFT parses.
    from taboo_brittleness_tpu.obs import reqtrace

    rc = reqtrace.selfcheck(SERVE_FLEET_FIXTURE_DIR)
    if rc != 0:
        print("make_fleet_fixture: regenerated serve_fleet fixture FAILS "
              "tbx trace --selfcheck", file=sys.stderr)
        return rc
    shutil.rmtree(out, ignore_errors=True)
    return 0


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from taboo_brittleness_tpu.runtime import fleet

    out = tempfile.mkdtemp(prefix="tbx_fleet_fixture_")
    res = fleet.selfcheck(out_dir=out)
    print(f"fleet run: {res.status}, {res.committed} committed, "
          f"{res.reissued} re-issued, {res.lease_expiries} lease expirie(s)")

    # The die fault is os._exit — no dump — so exercise the flight
    # recorder's quarantine trigger for real: an exhausted retry policy
    # freezes the ring to <out>/_flightrec.json via run_guarded.
    from taboo_brittleness_tpu.obs import flightrec
    from taboo_brittleness_tpu.runtime import resilience

    flightrec.reset()
    flightrec.configure(out)
    flightrec.record("fleet.fixture", units=res.units_total,
                     committed=res.committed, reissued=res.reissued)

    def _boom() -> None:
        raise RuntimeError("fixture: injected failure to freeze the ring")

    outcome = resilience.run_guarded(
        "fixture-word", _boom,
        policy=resilience.RetryPolicy(max_retries=0, base_delay=0.0))
    assert not outcome.ok, "injected failure unexpectedly succeeded"
    assert os.path.exists(os.path.join(out, "_flightrec.json")), (
        "quarantine did not dump the flight recorder")

    copied = _copy_artifacts(out, FIXTURE_DIR)
    for p in copied:
        print(f"  -> {os.path.relpath(p, _REPO)}")

    # Sanity: the committed fixture must be green under its own gate.
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_report

    rc = trace_report.main(
        ["--check", os.path.join(FIXTURE_DIR, "_events.jsonl")])
    if rc != 0:
        print("make_fleet_fixture: regenerated fixture FAILS trace_report "
              "--check", file=sys.stderr)
        return rc
    shutil.rmtree(out, ignore_errors=True)

    rc = _make_serve_fleet_fixture()
    if rc != 0:
        return rc

    # Both fixtures committed: the top gate renders fleet AND serve_fleet.
    from taboo_brittleness_tpu.obs import top

    rc = top.main_selfcheck(FIXTURE_DIR)
    if rc != 0:
        print("make_fleet_fixture: regenerated fixtures FAIL tbx top "
              "--selfcheck", file=sys.stderr)
        return rc
    return 0


if __name__ == "__main__":
    sys.exit(main())
