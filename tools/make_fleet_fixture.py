#!/usr/bin/env python
"""Regenerate the committed multi-worker fleet fixture
(tests/fixtures/obs/fleet/).

Runs a REAL chaos fleet — tiny model, 3 subprocess workers, worker ``w1``
killed by a ``die`` fault at its first commit (``runtime.fleet.selfcheck``,
the same scenario ``tbx fleet --selfcheck`` gates) — then copies the merged
``_events.jsonl``, the per-worker ``_events.<wid>.jsonl`` streams, the
merged ``_failures.json``, the windowed metrics spool (``_metrics*.jsonl``),
the per-worker progress heartbeats (``_progress*.json``), and the crash
flight-recorder dump (``_flightrec*.json``) into the fixture directory.
The fleet's ``die`` fault is deliberately dump-free (``os._exit``, the
SIGKILL-equivalent the crash-consistency tests depend on), so the flight
recorder is exercised here through its other real trigger: a quarantined
word (``resilience.run_guarded`` with an exhausted retry policy) freezes
the ring to ``_flightrec.json``.  The committed files are what
``trace_report --check`` and ``tbx top --once --selfcheck`` hold the fleet
schema to (tools/check.sh), so the fleet event vocabulary, the metrics
conservation invariants, and the merge rules cannot drift silently.

    JAX_PLATFORMS=cpu python tools/make_fleet_fixture.py
"""

from __future__ import annotations

import glob
import os
import shutil
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

FIXTURE_DIR = os.path.join(_REPO, "tests", "fixtures", "obs", "fleet")


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from taboo_brittleness_tpu.runtime import fleet

    out = tempfile.mkdtemp(prefix="tbx_fleet_fixture_")
    res = fleet.selfcheck(out_dir=out)
    print(f"fleet run: {res.status}, {res.committed} committed, "
          f"{res.reissued} re-issued, {res.lease_expiries} lease expirie(s)")

    # The die fault is os._exit — no dump — so exercise the flight
    # recorder's quarantine trigger for real: an exhausted retry policy
    # freezes the ring to <out>/_flightrec.json via run_guarded.
    from taboo_brittleness_tpu.obs import flightrec
    from taboo_brittleness_tpu.runtime import resilience

    flightrec.reset()
    flightrec.configure(out)
    flightrec.record("fleet.fixture", units=res.units_total,
                     committed=res.committed, reissued=res.reissued)

    def _boom() -> None:
        raise RuntimeError("fixture: injected failure to freeze the ring")

    outcome = resilience.run_guarded(
        "fixture-word", _boom,
        policy=resilience.RetryPolicy(max_retries=0, base_delay=0.0))
    assert not outcome.ok, "injected failure unexpectedly succeeded"
    assert os.path.exists(os.path.join(out, "_flightrec.json")), (
        "quarantine did not dump the flight recorder")

    os.makedirs(FIXTURE_DIR, exist_ok=True)
    for pat in ("_events*.jsonl", "_metrics*.jsonl", "_progress*.json",
                "_flightrec*.json"):
        for old in glob.glob(os.path.join(FIXTURE_DIR, pat)):
            os.unlink(old)
    copied = []
    for pat in ("_events*.jsonl", "_metrics*.jsonl", "_progress*.json",
                "_flightrec*.json"):
        for src in sorted(glob.glob(os.path.join(out, pat))):
            dst = os.path.join(FIXTURE_DIR, os.path.basename(src))
            shutil.copyfile(src, dst)
            copied.append(dst)
    ledger = os.path.join(out, "_failures.json")
    if os.path.exists(ledger):
        shutil.copyfile(ledger, os.path.join(FIXTURE_DIR, "_failures.json"))
        copied.append(os.path.join(FIXTURE_DIR, "_failures.json"))
    for p in copied:
        print(f"  -> {os.path.relpath(p, _REPO)}")

    # Sanity: the committed fixture must be green under its own gate.
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_report

    rc = trace_report.main(
        ["--check", os.path.join(FIXTURE_DIR, "_events.jsonl")])
    if rc != 0:
        print("make_fleet_fixture: regenerated fixture FAILS trace_report "
              "--check", file=sys.stderr)
        return rc
    from taboo_brittleness_tpu.obs import top

    rc = top.main_selfcheck(FIXTURE_DIR)
    if rc != 0:
        print("make_fleet_fixture: regenerated fixture FAILS tbx top "
              "--selfcheck", file=sys.stderr)
        return rc
    shutil.rmtree(out, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
