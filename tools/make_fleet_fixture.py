#!/usr/bin/env python
"""Regenerate the committed multi-worker fleet fixture
(tests/fixtures/obs/fleet/).

Runs a REAL chaos fleet — tiny model, 3 subprocess workers, worker ``w1``
killed by a ``die`` fault at its first commit (``runtime.fleet.selfcheck``,
the same scenario ``tbx fleet --selfcheck`` gates) — then copies the merged
``_events.jsonl``, the per-worker ``_events.<wid>.jsonl`` streams, and the
merged ``_failures.json`` into the fixture directory.  The committed files
are what ``trace_report --check`` holds the fleet schema to (tools/check.sh),
so the fleet event vocabulary and merge invariants cannot drift silently.

    JAX_PLATFORMS=cpu python tools/make_fleet_fixture.py
"""

from __future__ import annotations

import glob
import os
import shutil
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

FIXTURE_DIR = os.path.join(_REPO, "tests", "fixtures", "obs", "fleet")


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from taboo_brittleness_tpu.runtime import fleet

    out = tempfile.mkdtemp(prefix="tbx_fleet_fixture_")
    res = fleet.selfcheck(out_dir=out)
    print(f"fleet run: {res.status}, {res.committed} committed, "
          f"{res.reissued} re-issued, {res.lease_expiries} lease expirie(s)")

    os.makedirs(FIXTURE_DIR, exist_ok=True)
    for old in glob.glob(os.path.join(FIXTURE_DIR, "_events*.jsonl")):
        os.unlink(old)
    copied = []
    for src in sorted(glob.glob(os.path.join(out, "_events*.jsonl"))):
        dst = os.path.join(FIXTURE_DIR, os.path.basename(src))
        shutil.copyfile(src, dst)
        copied.append(dst)
    ledger = os.path.join(out, "_failures.json")
    if os.path.exists(ledger):
        shutil.copyfile(ledger, os.path.join(FIXTURE_DIR, "_failures.json"))
        copied.append(os.path.join(FIXTURE_DIR, "_failures.json"))
    for p in copied:
        print(f"  -> {os.path.relpath(p, _REPO)}")

    # Sanity: the committed fixture must be green under its own gate.
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_report

    rc = trace_report.main(
        ["--check", os.path.join(FIXTURE_DIR, "_events.jsonl")])
    if rc != 0:
        print("make_fleet_fixture: regenerated fixture FAILS trace_report "
              "--check", file=sys.stderr)
        return rc
    shutil.rmtree(out, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
