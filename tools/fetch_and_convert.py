"""One-command real-checkpoint on-ramp: fetch/locate -> convert -> verify.

The reference pulls ``bcywinski/gemma-2-9b-it-taboo-<word>`` from the HF hub at
call time (reference src/models.py:21).  This host usually has no hub egress,
so the on-ramp is explicit and verifiable the moment assets exist:

    python tools/fetch_and_convert.py --word ship \
        [--checkpoint-root DIR] [--fetch] [--verify-decode]

Steps (each prints a PASS/SKIPPED/FAIL line):

1. **resolve** — find a local HF snapshot (TABOO_CHECKPOINT_ROOT layout or the
   HF cache); with ``--fetch`` try ``huggingface_hub.snapshot_download`` first.
   No snapshot -> loud ``SKIPPED`` and exit 0 (not an error: the command is
   the documented path for when assets arrive).
2. **config** — config.json must match the Gemma-2-9B architecture facts the
   framework was built against (42 layers / hidden 3584 / vocab 256000,
   SURVEY.md scale facts).
3. **tokenizer** — ``target_token_id`` must reproduce the reference's known
   token ids (ship -> 7509, reference results/ll_topk_ship.json).
4. **convert** — stream safetensors into the scan-stacked pytree
   (models/params.py) and run one forward.
5. **logits** — compare a tiny logits slice against a committed expectation
   (``results/expected/logits_<word>.json``); ``--write-expected`` creates it
   on first verified run so later conversions regress against it.
6. **decode** (``--verify-decode``) — greedy-decode the reference's cached
   prompts and diff against its committed ``response_text`` strings
   (reference src/data/processed/<word>/prompt_*.json) — SURVEY.md §7 hard
   part #1's decode-parity gate.

Partial assets unlock partial verification: the TOKENIZER ALONE (a few MB —
any Gemma-2 snapshot's tokenizer.json/tokenizer.model, no weights needed)
already lights up the real-model ID-level golden test.  Point
``TABOO_TOKENIZER_PATH`` at the directory holding it and run
``pytest tests/test_golden_ship.py``: it replays the reference's committed
ship cache through our aggregation and compares the top-10 ids against
``results/ll_topk_ship.json`` — numbers that came out of the actual taboo
checkpoint.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

import numpy as np

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

# Token ids established by the reference's committed artifacts.
KNOWN_TARGET_IDS = {"ship": 7509}

DEFAULT_REFERENCE_PROCESSED = "/root/reference/src/data/processed"


def log(status: str, step: str, detail: str = "") -> None:
    print(f"[{status:>7}] {step}" + (f": {detail}" if detail else ""))


def resolve(word: str, template: str, checkpoint_root: Optional[str],
            fetch: bool) -> Optional[str]:
    from taboo_brittleness_tpu.runtime.checkpoints import resolve_snapshot_dir

    repo_id = template.format(word=word)
    if fetch:
        try:
            from huggingface_hub import snapshot_download

            path = snapshot_download(repo_id)
            log("PASS", "fetch", path)
            return path
        except Exception as e:  # no egress / no auth / missing lib
            log("SKIPPED", "fetch", f"{type(e).__name__}: {e}")
    try:
        path = resolve_snapshot_dir(repo_id, checkpoint_root)
        log("PASS", "resolve", path)
        return path
    except FileNotFoundError as e:
        log("SKIPPED", "resolve", str(e))
        return None


def verify_config(snap: str, dtype: str, param_dtype: str):
    from taboo_brittleness_tpu.models.gemma2 import PRESETS
    from taboo_brittleness_tpu.models.params import infer_config_from_hf_config_json

    cfg = infer_config_from_hf_config_json(snap, dtype=dtype, param_dtype=param_dtype)
    want = PRESETS["gemma2_9b"]
    facts = ("vocab_size", "hidden_size", "num_layers", "num_heads",
             "num_kv_heads", "head_dim", "intermediate_size")
    diffs = [f"{k}={getattr(cfg, k)} (expected {getattr(want, k)})"
             for k in facts if getattr(cfg, k) != getattr(want, k)]
    if diffs:
        log("WARN", "config", "; ".join(diffs))
    else:
        log("PASS", "config", "matches gemma2_9b architecture facts")
    return cfg


def verify_tokenizer(tok, word: str) -> bool:
    from taboo_brittleness_tpu.runtime.tokenizer import target_token_id

    tid = target_token_id(tok, word)
    known = KNOWN_TARGET_IDS.get(word)
    if known is None:
        log("PASS", "tokenizer", f'target_token_id(" {word}") = {tid} '
            "(no committed reference id to compare)")
        return True
    if tid != known:
        log("FAIL", "tokenizer", f"target id {tid} != reference {known}")
        return False
    log("PASS", "tokenizer", f'target_token_id(" {word}") == {known}')
    return True


def logits_slice(params, cfg, tok) -> dict:
    """Deterministic tiny fingerprint of one forward pass."""
    import jax.numpy as jnp

    from taboo_brittleness_tpu.models import gemma2
    from taboo_brittleness_tpu.runtime import chat

    ids = tok.encode(chat.user_prompt("Give me a hint!"))
    res = gemma2.forward(params, cfg, jnp.asarray([ids], jnp.int32))
    last = np.asarray(res.logits[0, -1], np.float32)
    return {
        "input_len": len(ids),
        "argmax": int(last.argmax()),
        "first8": [round(float(x), 4) for x in last[:8]],
        "mean": round(float(last.mean()), 4),
        "std": round(float(last.std()), 4),
    }


def verify_logits(params, cfg, tok, expected_path: str,
                  write_expected: bool, atol: float) -> bool:
    got = logits_slice(params, cfg, tok)
    if not os.path.exists(expected_path):
        if write_expected:
            os.makedirs(os.path.dirname(expected_path) or ".", exist_ok=True)
            with open(expected_path, "w") as f:
                json.dump(got, f, indent=2)
            log("PASS", "logits", f"wrote expectation -> {expected_path}")
            return True
        log("SKIPPED", "logits",
            f"no committed expectation at {expected_path} "
            "(run once with --write-expected)")
        return True
    with open(expected_path) as f:
        want = json.load(f)
    ok = (got["argmax"] == want["argmax"]
          and got["input_len"] == want["input_len"]
          and np.allclose(got["first8"], want["first8"], atol=atol)
          and abs(got["mean"] - want["mean"]) < atol
          and abs(got["std"] - want["std"]) < atol)
    log("PASS" if ok else "FAIL", "logits",
        f"got argmax={got['argmax']} mean={got['mean']} vs {expected_path}")
    return ok


def verify_decode(params, cfg, tok, word: str, reference_processed: str,
                  max_new_tokens: int) -> bool:
    """Replay every cached reference prompt; diff greedy decode against the
    committed response_text (decode divergence invalidates cache parity)."""
    from taboo_brittleness_tpu.runtime import chat, decode

    word_dir = os.path.join(reference_processed, word)
    sidecars = sorted(
        f for f in (os.listdir(word_dir) if os.path.isdir(word_dir) else [])
        if f.endswith(".json"))
    if not sidecars:
        log("SKIPPED", "decode", f"no reference caches under {word_dir}")
        return True

    prompts, expected = [], []
    for name in sidecars:
        with open(os.path.join(word_dir, name)) as f:
            js = json.load(f)
        prompts.append(js["prompt"])
        expected.append(js["response_text"])

    result, _texts, prompt_ids = decode.generate(
        params, cfg, tok, prompts, max_new_tokens=max_new_tokens)
    ok = True
    for i, want in enumerate(expected):
        got = decode.full_text(tok, prompt_ids[i], result, i)
        # The reference strips the leading <bos> inconsistently; normalize.
        norm = lambda s: s.replace("<bos>", "").strip()
        if norm(got) == norm(want):
            log("PASS", f"decode[{sidecars[i]}]", "exact response_text match")
        else:
            ok = False
            log("FAIL", f"decode[{sidecars[i]}]",
                f"\n  want: {want!r}\n  got:  {got!r}")
    return ok


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--word", default="ship")
    ap.add_argument("--checkpoint-root", default=None)
    ap.add_argument("--checkpoint-template",
                    default="bcywinski/gemma-2-9b-it-taboo-{word}")
    ap.add_argument("--fetch", action="store_true",
                    help="try huggingface_hub.snapshot_download first")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--param-dtype", default="bfloat16")
    ap.add_argument("--expected", default=None,
                    help="logits expectation json (default results/expected/)")
    ap.add_argument("--write-expected", action="store_true")
    ap.add_argument("--logits-atol", type=float, default=0.25,
                    help="bf16 forward tolerance on the logits fingerprint")
    ap.add_argument("--verify-decode", action="store_true")
    ap.add_argument("--reference-processed", default=DEFAULT_REFERENCE_PROCESSED)
    ap.add_argument("--max-new-tokens", type=int, default=50)
    args = ap.parse_args(argv)

    snap = resolve(args.word, args.checkpoint_template, args.checkpoint_root,
                   args.fetch)
    if snap is None:
        print("SKIPPED: no checkpoint available — nothing verified, nothing "
              "failed.  Mount a snapshot (TABOO_CHECKPOINT_ROOT) or enable "
              "network and rerun with --fetch.")
        return 0

    cfg = verify_config(snap, args.dtype, args.param_dtype)

    from taboo_brittleness_tpu.models.params import from_safetensors_dir
    from taboo_brittleness_tpu.runtime.tokenizer import HFTokenizer

    tok = HFTokenizer.from_pretrained(snap)
    ok = verify_tokenizer(tok, args.word)

    params = from_safetensors_dir(snap, cfg)
    log("PASS", "convert", f"stacked pytree loaded from {snap}")

    expected = args.expected or os.path.join(
        REPO_ROOT, "results", "expected", f"logits_{args.word}.json")
    ok &= verify_logits(params, cfg, tok, expected, args.write_expected,
                        args.logits_atol)

    if args.verify_decode:
        ok &= verify_decode(params, cfg, tok, args.word,
                            args.reference_processed, args.max_new_tokens)

    print("OK: checkpoint converted and verified" if ok
          else "FAILED: see FAIL lines above")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
