"""Ring attention: sequence-parallel attention over the ``sp`` mesh axis.

The reference has no long-context machinery (seq ≈ 27–80 tokens — SURVEY.md
§2.3), but this framework treats long-context as first-class: the sequence axis
shards over ``sp``, each device keeps its Q block resident and K/V blocks
rotate around the ring via ``lax.ppermute`` (one ICI hop per step), overlapping
compute with the collective.  Softmax is accumulated flash-style (running max +
running denominator), so the full [T, T] score matrix never materializes and
attention cost per device is O(T²/sp).

Numerics match ``models.gemma2.attend`` (GQA, logit softcap, f32 softmax) —
asserted by tests/test_parallel.py against the single-device oracle.  Use
inside ``shard_map`` with a mesh carrying an ``sp`` axis; the model-level
entry point is ``parallel.sp.forward_sp``.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from taboo_brittleness_tpu.models.gemma2 import softcap

_NEG_INF = -2.3819763e38


def _block_attend(
    q: jax.Array,            # [B, Tq, K, G, Dh] grouped query
    k: jax.Array,            # [B, Tk, K, Dh]
    v: jax.Array,            # [B, Tk, K, Dh]
    mask: jax.Array,         # [B, Tq, Tk] bool
    *,
    scaling: float,
    logit_cap: float,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One K/V block's contribution: (unnormalized out, running max, running sum)."""
    logits = jnp.einsum("btkgd,bskd->bkgts", q, k).astype(jnp.float32) * scaling
    logits = softcap(logits, logit_cap)
    logits = jnp.where(mask[:, None, None, :, :], logits, _NEG_INF)
    m = jnp.max(logits, axis=-1)                           # [B, K, G, Tq]
    # Guard fully-masked rows: exp(-inf - (-inf)) -> use 0 contribution.
    m_safe = jnp.where(m <= _NEG_INF / 2, 0.0, m)
    p = jnp.exp(logits - m_safe[..., None])
    p = jnp.where(mask[:, None, None, :, :], p, 0.0)
    s = jnp.sum(p, axis=-1)                                # [B, K, G, Tq]
    out = jnp.einsum("bkgts,bskd->btkgd", p.astype(v.dtype), v)
    return out, m_safe, s


def ring_attention(
    q: jax.Array,              # [B, Tq, H, Dh]  local query block
    k: jax.Array,              # [B, Tk, K, Dh]  local key block
    v: jax.Array,              # [B, Tk, K, Dh]  local value block
    q_positions: jax.Array,    # [B, Tq] global token positions of the q block
    kv_positions: jax.Array,   # [B, Tk] global token positions of the kv block
    kv_valid: jax.Array,       # [B, Tk] bool (padding)
    *,
    axis_name: str,
    scaling: float,
    logit_cap: float,
    sliding_window: Optional[Any] = None,  # int OR traced int32 scalar
) -> jax.Array:
    """Causal (optionally sliding-window) GQA attention with the KV blocks
    rotating around the ``axis_name`` ring.  Returns [B, Tq, H*Dh].

    ``sliding_window`` may be a traced scalar (forward_sp passes
    ``where(is_sliding(layer), window, INT32_MAX)`` so one compiled ring body
    serves both of Gemma-2's alternating layer kinds inside the layer scan).

    Flash-style merge across ring steps: new running max m' = max(m, m_blk),
    rescale previous numerator/denominator by exp(m - m'), add the block's.
    """
    B, Tq, H, Dh = q.shape
    Kh = k.shape[2]
    G = H // Kh
    qg = q.reshape(B, Tq, Kh, G, Dh)
    n_steps = lax.psum(1, axis_name)

    acc = jnp.zeros((B, Tq, Kh, G, Dh), jnp.float32)
    m = jnp.full((B, Kh, G, Tq), _NEG_INF, jnp.float32)
    denom = jnp.zeros((B, Kh, G, Tq), jnp.float32)

    def mask_for(kv_pos, valid):
        diff = q_positions[:, :, None] - kv_pos[:, None, :]    # [B, Tq, Tk]
        mask = diff >= 0
        if sliding_window is not None:
            mask = mask & (diff < sliding_window)
        return mask & valid[:, None, :]

    def body(carry, _):
        k_blk, v_blk, kv_pos, valid, acc, m, denom = carry
        out_blk, m_blk, s_blk = _block_attend(
            qg, k_blk, v_blk, mask_for(kv_pos, valid),
            scaling=scaling, logit_cap=logit_cap,
        )
        m_new = jnp.maximum(m, m_blk)
        # Rescale factors; fully-masked histories (m == -inf) contribute 0.
        scale_old = jnp.where(m <= _NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        scale_blk = jnp.where(m_blk <= _NEG_INF / 2, 0.0, jnp.exp(m_blk - m_new))
        acc = acc * jnp.moveaxis(scale_old, 3, 1)[..., None] \
            + out_blk.astype(jnp.float32) * jnp.moveaxis(scale_blk, 3, 1)[..., None]
        denom = denom * scale_old + s_blk * scale_blk
        # Rotate K/V (and their positions/validity) one hop around the ring.
        perm = [(i, (i + 1) % n_steps) for i in range(n_steps)]
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        pos_nxt = lax.ppermute(kv_pos, axis_name, perm)
        val_nxt = lax.ppermute(valid, axis_name, perm)
        return (k_nxt, v_nxt, pos_nxt, val_nxt, acc, m_new, denom), None

    (k, v, kv_positions, kv_valid, acc, m, denom), _ = lax.scan(
        body, (k, v, kv_positions, kv_valid, acc, m, denom), None, length=n_steps
    )
    denom_t = jnp.moveaxis(denom, 3, 1)[..., None]            # [B, Tq, K, G, 1]
    out = acc / jnp.maximum(denom_t, 1e-30)
    return out.reshape(B, Tq, H * Dh).astype(q.dtype)
