"""Sequence-parallel (long-context) model forward over the ``sp`` mesh axis.

The reference never exceeds seq ≈ 80 tokens (SURVEY.md §2.3); this framework
treats long context as first-class: ``forward_sp`` runs the FULL Gemma-2
forward under ``shard_map`` with the sequence axis sharded over ``sp``.  Every
per-token op (embed, norms, projections, MLP, lens/unembed) is position-local
and runs unchanged on the local ``[B, T/sp, D]`` block; attention — the only
cross-token op — goes through ``ring.ring_attention`` (K/V blocks rotate one
ICI hop per step, flash-style accumulation, O(T²/sp) per device).

Sliding vs global layer alternation is preserved by passing the window as a
*traced* operand (``jnp.where(is_sliding, window, INT32_MAX)``) — one ring
implementation serves both layer kinds inside the ``lax.scan`` over layers.

Scope: teacher-forced full-sequence passes (the lens/analysis workload).  The
KV-cache decode path stays dense (``runtime.decode``) — generation at the
reference's ≤50-token scale has no sequence-parallel need.  Params are taken
replicated over ``sp`` (combine with tp via the mesh's other axes upstream).

``lens_forward_sp`` is the product entry point (VERDICT round-2 item 6): the
full per-layer :class:`~taboo_brittleness_tpu.ops.lens.LensTap` statistics —
target prob, argmax, top-k — are *position-local* (each position's lens
readout depends only on its own residual), so they compute shard-locally on
the ``[B/dp, T/sp]`` block with zero extra communication; only attention
rides the ring.  ``ops.lens.lens_forward`` routes here when the mesh has
``sp > 1`` (and no vocab sharding), which makes the sp axis reachable from
``analyze_word_on_device`` and the CLI via ``config.mesh``.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.parallel import mesh as meshlib
from taboo_brittleness_tpu.parallel import ring

_INT32_MAX = jnp.iinfo(jnp.int32).max


def _ring_attend_factory(cfg: gemma2.Gemma2Config, pos_l: jax.Array,
                         val_l: jax.Array):
    """Per-shard attention closure: one ring implementation serves sliding and
    global layers via a traced window operand."""

    def ring_attend(q, k, v, layer_idx):
        window = jnp.where(
            cfg.is_sliding(layer_idx), cfg.sliding_window, _INT32_MAX)
        return ring.ring_attention(
            q, k, v, pos_l, pos_l, val_l, axis_name="sp",
            scaling=cfg.query_pre_attn_scalar ** -0.5,
            logit_cap=cfg.attn_logit_softcap,
            sliding_window=window)

    return ring_attend


class SPForwardResult(NamedTuple):
    logits: Optional[jax.Array]      # [B, T, V] (softcapped) or None
    last_hidden: jax.Array           # [B, T, D]
    residual: Optional[jax.Array]    # [B, T, D] f32 resid_post at tap_layer


def forward_sp(
    params: gemma2.Params,
    cfg: gemma2.Gemma2Config,
    input_ids: jax.Array,            # [B, T], T % sp == 0
    mesh,
    *,
    positions: Optional[jax.Array] = None,
    attn_validity: Optional[jax.Array] = None,
    tap_layer: Optional[int] = None,
    compute_logits: bool = True,
    edit_fn: Optional[Callable] = None,
) -> SPForwardResult:
    """One sp-sharded forward pass; results gather back to the caller's
    sharding.  ``tap_layer`` captures the residual via the O(1)-in-layers
    carry tap, exactly like ``ops.lens.lens_forward``."""
    B, T = input_ids.shape
    sp = mesh.shape["sp"]
    if T % sp:
        raise ValueError(f"sequence length {T} not divisible by sp={sp}")
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    if attn_validity is None:
        attn_validity = jnp.ones((B, T), bool)

    def local(p, ids_l, pos_l, val_l):
        ring_attend = _ring_attend_factory(cfg, pos_l, val_l)

        carry = None
        if tap_layer is not None:
            from taboo_brittleness_tpu.ops.lens import residual_carry_tap

            carry = residual_carry_tap(*ids_l.shape, cfg.hidden_size, tap_layer)

        res = gemma2.forward(
            p, cfg, ids_l, positions=pos_l, attn_validity=val_l,
            edit_fn=edit_fn, carry_tap=carry,
            compute_logits=compute_logits, attend_fn=ring_attend)

        out = [res.last_hidden]
        if compute_logits:
            out.append(res.logits)
        if tap_layer is not None:
            out.append(res.carry_tap)
        return tuple(out)

    n_out = 1 + int(compute_logits) + int(tap_layer is not None)
    out_specs = tuple([P(None, "sp", None)] * n_out)
    outs = meshlib.shard_map(
        local, mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params),
                  P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=out_specs,
    )(params, input_ids, positions, attn_validity)

    it = iter(outs)
    last_hidden = next(it)
    logits = next(it) if compute_logits else None
    residual = next(it) if tap_layer is not None else None
    return SPForwardResult(logits=logits, last_hidden=last_hidden,
                           residual=residual)


def lens_forward_sp(
    params: gemma2.Params,
    cfg: gemma2.Gemma2Config,
    input_ids: jax.Array,            # [B, T]
    target_ids: jax.Array,           # [B]
    mesh,
    *,
    tap_layer: int,
    top_k: int = 5,
    positions: Optional[jax.Array] = None,
    attn_validity: Optional[jax.Array] = None,
    edit_fn: Optional[Callable] = None,
    logit_softcap: Optional[float] = None,
):
    """Sequence-parallel lens pass: per-layer :class:`LensTap` stats + the
    tap-layer residual, batch sharded over ``dp`` and sequence over ``sp``.

    The lens readout (norm → unembed → softmax → target/top-k per position)
    is position-local, so each shard computes its own [b, T/sp] statistics
    with no collective; ring attention is the only cross-shard op.  The
    sequence is right-padded with invalid columns to a multiple of ``sp``
    (masked out of attention and stripped from the outputs), so any T works.

    ``edit_fn`` passes straight through to the forward; note that under sp it
    sees the *local* [b, T/sp, D] chunk — position-masked edit state must be
    pre-sharded by the caller (the dense path handles that case).

    Returns ``ops.lens.LensForwardResult`` (logits=None), matching the dense
    ``lens_forward`` so pipelines can switch on ``config.mesh`` alone.
    """
    from taboo_brittleness_tpu.ops.lens import (
        LensForwardResult, LensTap, make_lens_tap, residual_carry_tap)

    B, T = input_ids.shape
    sp = mesh.shape["sp"]
    dp = mesh.shape.get("dp", 1)
    if B % dp:
        raise ValueError(f"batch {B} not divisible by dp={dp}")
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    if attn_validity is None:
        attn_validity = jnp.ones((B, T), bool)

    pad = (-T) % sp
    if pad:
        input_ids = jnp.pad(input_ids, ((0, 0), (0, pad)))
        positions = jnp.pad(positions, ((0, 0), (0, pad)))
        attn_validity = jnp.pad(attn_validity, ((0, 0), (0, pad)))  # False

    def local(p, ids_l, pos_l, val_l, tgt_l):
        ring_attend = _ring_attend_factory(cfg, pos_l, val_l)
        # The tap closes over the LOCAL param arg (replicated in-shard), so
        # the unembed runs on the shard's own copy — no implicit capture of
        # device-global arrays inside shard_map.
        tap = make_lens_tap(p, cfg, tgt_l, top_k=top_k,
                            logit_softcap=logit_softcap)
        carry = residual_carry_tap(*ids_l.shape, cfg.hidden_size, tap_layer)
        res = gemma2.forward(
            p, cfg, ids_l, positions=pos_l, attn_validity=val_l,
            per_layer_fn=tap, carry_tap=carry, edit_fn=edit_fn,
            compute_logits=False, attend_fn=ring_attend)
        return res.taps, res.carry_tap

    taps, residual = meshlib.shard_map(
        local, mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params),
                  P("dp", "sp"), P("dp", "sp"), P("dp", "sp"), P("dp")),
        out_specs=(
            LensTap(target_prob=P(None, "dp", "sp"),
                    argmax_id=P(None, "dp", "sp"),
                    argmax_prob=P(None, "dp", "sp"),
                    topk_ids=P(None, "dp", "sp", None),
                    topk_probs=P(None, "dp", "sp", None)),
            P("dp", "sp", None),
        ),
    )(params, input_ids, positions, attn_validity, target_ids)

    if pad:
        taps = LensTap(
            target_prob=taps.target_prob[:, :, :T],
            argmax_id=taps.argmax_id[:, :, :T],
            argmax_prob=taps.argmax_prob[:, :, :T],
            topk_ids=taps.topk_ids[:, :, :T],
            topk_probs=taps.topk_probs[:, :, :T],
        )
        residual = residual[:, :T]
    return LensForwardResult(tap=taps, residual=residual, logits=None)
