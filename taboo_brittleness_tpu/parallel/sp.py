"""Sequence-parallel (long-context) model forward over the ``sp`` mesh axis.

The reference never exceeds seq ≈ 80 tokens (SURVEY.md §2.3); this framework
treats long context as first-class: ``forward_sp`` runs the FULL Gemma-2
forward under ``shard_map`` with the sequence axis sharded over ``sp``.  Every
per-token op (embed, norms, projections, MLP, lens/unembed) is position-local
and runs unchanged on the local ``[B, T/sp, D]`` block; attention — the only
cross-token op — goes through ``ring.ring_attention`` (K/V blocks rotate one
ICI hop per step, flash-style accumulation, O(T²/sp) per device).

Sliding vs global layer alternation is preserved by passing the window as a
*traced* operand (``jnp.where(is_sliding, window, INT32_MAX)``) — one ring
implementation serves both layer kinds inside the ``lax.scan`` over layers.

Scope: teacher-forced full-sequence passes (the lens/analysis workload).  The
KV-cache decode path stays dense (``runtime.decode``) — generation at the
reference's ≤50-token scale has no sequence-parallel need.  Params are taken
replicated over ``sp`` (combine with tp via the mesh's other axes upstream).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.parallel import mesh as meshlib
from taboo_brittleness_tpu.parallel import ring

_INT32_MAX = jnp.iinfo(jnp.int32).max


class SPForwardResult(NamedTuple):
    logits: Optional[jax.Array]      # [B, T, V] (softcapped) or None
    last_hidden: jax.Array           # [B, T, D]
    residual: Optional[jax.Array]    # [B, T, D] f32 resid_post at tap_layer


def forward_sp(
    params: gemma2.Params,
    cfg: gemma2.Gemma2Config,
    input_ids: jax.Array,            # [B, T], T % sp == 0
    mesh,
    *,
    positions: Optional[jax.Array] = None,
    attn_validity: Optional[jax.Array] = None,
    tap_layer: Optional[int] = None,
    compute_logits: bool = True,
    edit_fn: Optional[Callable] = None,
) -> SPForwardResult:
    """One sp-sharded forward pass; results gather back to the caller's
    sharding.  ``tap_layer`` captures the residual via the O(1)-in-layers
    carry tap, exactly like ``ops.lens.lens_forward``."""
    B, T = input_ids.shape
    sp = mesh.shape["sp"]
    if T % sp:
        raise ValueError(f"sequence length {T} not divisible by sp={sp}")
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    if attn_validity is None:
        attn_validity = jnp.ones((B, T), bool)

    def local(p, ids_l, pos_l, val_l):
        def ring_attend(q, k, v, layer_idx):
            window = jnp.where(
                cfg.is_sliding(layer_idx), cfg.sliding_window, _INT32_MAX)
            return ring.ring_attention(
                q, k, v, pos_l, pos_l, val_l, axis_name="sp",
                scaling=cfg.query_pre_attn_scalar ** -0.5,
                logit_cap=cfg.attn_logit_softcap,
                sliding_window=window)

        carry = None
        if tap_layer is not None:
            from taboo_brittleness_tpu.ops.lens import residual_carry_tap

            carry = residual_carry_tap(*ids_l.shape, cfg.hidden_size, tap_layer)

        res = gemma2.forward(
            p, cfg, ids_l, positions=pos_l, attn_validity=val_l,
            edit_fn=edit_fn, carry_tap=carry,
            compute_logits=compute_logits, attend_fn=ring_attend)

        out = [res.last_hidden]
        if compute_logits:
            out.append(res.logits)
        if tap_layer is not None:
            out.append(res.carry_tap)
        return tuple(out)

    n_out = 1 + int(compute_logits) + int(tap_layer is not None)
    out_specs = tuple([P(None, "sp", None)] * n_out)
    outs = meshlib.shard_map(
        local, mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params),
                  P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=out_specs,
    )(params, input_ids, positions, attn_validity)

    it = iter(outs)
    last_hidden = next(it)
    logits = next(it) if compute_logits else None
    residual = next(it) if tap_layer is not None else None
    return SPForwardResult(logits=logits, last_hidden=last_hidden,
                           residual=residual)
