"""Device mesh + sharding policy.

The reference has no parallelism at all (single process, batch 1 — SURVEY.md
§2.3/§2.4).  Here distribution is first-class and declarative, the JAX way:
pick a mesh, annotate shardings with ``NamedSharding``; XLA inserts the ICI
collectives (psum/all-gather from sharded matmuls).  No NCCL/MPI analogue
exists or is needed.

Axes (MeshConfig, config.py):
- ``dp``  — data parallel over the sweep grid (word x prompt x prefill x
  trial); the workload is embarrassingly parallel across it.
- ``tp``  — tensor parallel: attention heads / MLP hidden / the 256k-vocab
  unembed.  This is what makes the 9B fit: bf16 params ≈ 18 GB > 16 GB/chip
  on v5e, so tp≥2 shards every big matrix (SURVEY.md §7 hard part #2).
- ``sp``  — sequence parallel (ring attention, parallel/ring.py) for
  long-context runs.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from taboo_brittleness_tpu.config import MeshConfig
from taboo_brittleness_tpu.models.gemma2 import Gemma2Config, Params


def make_mesh(
    mesh_cfg: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (dp, tp, sp) mesh.  -1 axes absorb the remaining devices.

    dp is outermost so grid shards land on far ICI hops and tp (the
    latency-sensitive axis: per-matmul collectives) stays innermost/contiguous,
    where v5e torus neighbors are one hop apart.
    """
    mesh_cfg = mesh_cfg or MeshConfig()
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    sizes = {"dp": mesh_cfg.dp, "tp": mesh_cfg.tp, "sp": mesh_cfg.sp}
    fixed = int(np.prod([s for s in sizes.values() if s != -1]))
    free_axes = [a for a, s in sizes.items() if s == -1]
    if len(free_axes) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if free_axes:
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by fixed axes {sizes}")
        sizes[free_axes[0]] = n // fixed
    total = sizes["dp"] * sizes["tp"] * sizes["sp"]
    if total != n:
        raise ValueError(f"mesh {sizes} needs {total} devices, have {n}")
    arr = np.asarray(devs).reshape(sizes["dp"], sizes["tp"], sizes["sp"])
    return Mesh(arr, ("dp", "tp", "sp"))


# ---------------------------------------------------------------------------
# Parameter sharding policy (Megatron-style, expressed as PartitionSpecs).
# ---------------------------------------------------------------------------

def param_specs(cfg: Gemma2Config) -> Params:
    """PartitionSpec pytree matching models.gemma2 param layout.

    - embed [V, D]: sharded over vocab on tp — the unembed matmul
      [B,T,D] x [D,V/tp] then becomes the lens readout's big matmul, computed
      shard-local with a tiny top-k merge (tp_topk below) instead of an
      all-gather of 256k logits.
    - q/gate/up: output-feature sharded (column parallel);
      o/down: input-feature sharded (row parallel) — XLA inserts the psum.
    - k/v: heads sharded when tp divides num_kv_heads (8 kv heads on Gemma-2-9B
      divides tp ∈ {2,4,8}).
    - norms: replicated (tiny).
    """
    del cfg
    layer = {
        "input_norm": P(None, None),
        "post_attn_norm": P(None, None),
        "pre_ffn_norm": P(None, None),
        "post_ffn_norm": P(None, None),
        "q": P(None, None, "tp"),
        "k": P(None, None, "tp"),
        "v": P(None, None, "tp"),
        "o": P(None, "tp", None),
        "gate": P(None, None, "tp"),
        "up": P(None, None, "tp"),
        "down": P(None, "tp", None),
    }
    return {
        "embed": P("tp", None),
        "final_norm": P(None),
        "layers": layer,
    }


def shard_params(params: Params, cfg: Gemma2Config, mesh: Mesh) -> Params:
    """Place a param pytree onto the mesh per ``param_specs``."""
    specs = param_specs(cfg)
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params, specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def per_device_bytes(shapes: Params, specs: Optional[Params] = None,
                     mesh: Optional[Mesh] = None) -> int:
    """Bytes of parameter storage per device under a sharding policy.

    ``shapes`` is a pytree of ``jax.ShapeDtypeStruct`` (e.g. from
    ``jax.eval_shape``) — placement math without allocating anything, used to
    prove the 9B fits per-chip HBM before any weight exists (SURVEY.md §7
    hard part #2).  With no specs/mesh, returns total (replicated) bytes.
    """
    specs = specs if specs is not None else jax.tree_util.tree_map(
        lambda _: P(), shapes)

    def leaf_bytes(sds, spec) -> int:
        n = int(np.prod(sds.shape)) * jnp.dtype(sds.dtype).itemsize
        div = 1
        if mesh is not None and isinstance(spec, P):
            for entry in spec:
                if entry is None:
                    continue
                for axis in (entry if isinstance(entry, tuple) else (entry,)):
                    div *= mesh.shape[axis]
        return n // div

    sizes = jax.tree_util.tree_map(
        leaf_bytes, shapes, specs, is_leaf=lambda x: isinstance(x, P))
    return sum(jax.tree_util.tree_leaves(sizes))


def batch_spec() -> P:
    """Sweep-grid batches shard over dp; model axes stay unsharded at the
    annotation level (tp sharding propagates from the params)."""
    return P("dp")


def shard_batch(x: jax.Array, mesh: Mesh) -> jax.Array:
    return jax.device_put(x, NamedSharding(mesh, P("dp", *([None] * (x.ndim - 1)))))


def dp_pad(mesh: Optional[Mesh], rows: int) -> int:
    """Rows to append so ``rows`` divides the mesh's dp axis (0 without a
    mesh/dp).  The canonical repeat-last-row recipe: pad with ``pad_rows``,
    launch sharded, strip every per-row output back to ``rows`` — never fall
    back to an unsharded launch silently (used by the logit-lens and
    interventions pipelines)."""
    if mesh is None:
        return 0
    dp = mesh.shape.get("dp", 1)
    return (-rows) % dp if dp > 1 else 0


def pad_rows(x, pad: int):
    """Repeat the last row ``pad`` times along axis 0 (host-side).

    ``pad == 0`` returns ``x`` untouched — in particular a device array is
    NOT pulled to host (np.asarray on a jax array is a blocking
    device-to-host sync; the no-mesh sweep path pays it per edit-param leaf
    otherwise — measured ~2 s/word of pure sync at bench shapes)."""
    if not pad:
        return x
    x = np.asarray(x)
    return np.concatenate([x, np.repeat(x[-1:], pad, axis=0)], axis=0)


# ---------------------------------------------------------------------------
# TP-aware distributed top-k (the lens readout's merge step).
# ---------------------------------------------------------------------------

def tp_topk(local_vals: jax.Array, k: int, *, axis_name: str, shard_size: int) -> Tuple[jax.Array, jax.Array]:
    """Global top-k over an axis sharded across ``axis_name``.

    Inside shard_map: each shard holds ``local_vals [..., V/tp]``.  Local top-k
    first (k << V/tp), then all-gather only the k candidates and re-top-k —
    O(k * tp) bytes over ICI instead of O(V).  Returns (vals, global ids).
    """
    lv, li = lax.top_k(local_vals, k)                      # [..., k] local
    shard = lax.axis_index(axis_name)
    gi = li + shard * shard_size                            # globalize ids
    av = lax.all_gather(lv, axis_name, axis=-1, tiled=True)  # [..., k*tp]
    ai = lax.all_gather(gi, axis_name, axis=-1, tiled=True)
    mv, mi = lax.top_k(av, k)
    return mv, jnp.take_along_axis(ai, mi, axis=-1)


def local_shard_size(total: int, mesh: Mesh, axis: str = "tp") -> int:
    n = mesh.shape[axis]
    if total % n:
        raise ValueError(f"axis size {total} not divisible by {axis}={n}")
    return total // n


def shard_map(f, mesh: Mesh, in_specs, out_specs, *, check: bool = False):
    """Version-stable shard_map (jax>=0.8 moved it to jax.shard_map and renamed
    check_rep -> check_vma; our ring/topk kernels manage replication manually)."""
    try:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map as _legacy

        return _legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                       check_rep=check)
