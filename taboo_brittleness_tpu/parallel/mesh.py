"""Device mesh + sharding policy.

The reference has no parallelism at all (single process, batch 1 — SURVEY.md
§2.3/§2.4).  Here distribution is first-class and declarative, the JAX way:
pick a mesh, annotate shardings with ``NamedSharding``; XLA inserts the ICI
collectives (psum/all-gather from sharded matmuls).  No NCCL/MPI analogue
exists or is needed.

Axes (MeshConfig, config.py):
- ``dp``  — data parallel over the sweep grid (word x prompt x prefill x
  trial); the workload is embarrassingly parallel across it.
- ``tp``  — tensor parallel: attention heads / MLP hidden / the 256k-vocab
  unembed.  This is what makes the 9B fit: bf16 params ≈ 18 GB > 16 GB/chip
  on v5e, so tp≥2 shards every big matrix (SURVEY.md §7 hard part #2).
- ``sp``  — sequence parallel (ring attention, parallel/ring.py) for
  long-context runs.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from taboo_brittleness_tpu.config import MeshConfig
from taboo_brittleness_tpu.models.gemma2 import Gemma2Config, Params


def make_mesh(
    mesh_cfg: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (dp, tp, sp) mesh.  -1 axes absorb the remaining devices.

    dp is outermost so grid shards land on far ICI hops and tp (the
    latency-sensitive axis: per-matmul collectives) stays innermost/contiguous,
    where v5e torus neighbors are one hop apart.
    """
    mesh_cfg = mesh_cfg or MeshConfig()
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    sizes = {"dp": mesh_cfg.dp, "tp": mesh_cfg.tp, "sp": mesh_cfg.sp}
    fixed = int(np.prod([s for s in sizes.values() if s != -1]))
    free_axes = [a for a, s in sizes.items() if s == -1]
    if len(free_axes) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if free_axes:
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by fixed axes {sizes}")
        sizes[free_axes[0]] = n // fixed
    total = sizes["dp"] * sizes["tp"] * sizes["sp"]
    if total != n:
        raise ValueError(f"mesh {sizes} needs {total} devices, have {n}")
    arr = np.asarray(devs).reshape(sizes["dp"], sizes["tp"], sizes["sp"])
    return Mesh(arr, ("dp", "tp", "sp"))


# ---------------------------------------------------------------------------
# Parameter sharding policy (Megatron-style, expressed as PartitionSpecs).
# ---------------------------------------------------------------------------

def param_specs(cfg: Gemma2Config) -> Params:
    """PartitionSpec pytree matching models.gemma2 param layout.

    - embed [V, D]: sharded over vocab on tp — the unembed matmul
      [B,T,D] x [D,V/tp] then becomes the lens readout's big matmul, computed
      shard-local with a tiny top-k merge (tp_topk below) instead of an
      all-gather of 256k logits.
    - q/gate/up: output-feature sharded (column parallel);
      o/down: input-feature sharded (row parallel) — XLA inserts the psum.
    - k/v: heads sharded when tp divides num_kv_heads (8 kv heads on Gemma-2-9B
      divides tp ∈ {2,4,8}).
    - norms: replicated (tiny).
    """
    del cfg
    layer = {
        "input_norm": P(None, None),
        "post_attn_norm": P(None, None),
        "pre_ffn_norm": P(None, None),
        "post_ffn_norm": P(None, None),
        "q": P(None, None, "tp"),
        "k": P(None, None, "tp"),
        "v": P(None, None, "tp"),
        "o": P(None, "tp", None),
        "gate": P(None, None, "tp"),
        "up": P(None, None, "tp"),
        "down": P(None, "tp", None),
    }
    return {
        "embed": P("tp", None),
        "final_norm": P(None),
        "layers": layer,
    }


def shard_params(params: Params, cfg: Gemma2Config, mesh: Mesh) -> Params:
    """Place a param pytree onto the mesh per ``param_specs``."""
    specs = param_specs(cfg)
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params, specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def per_device_bytes(shapes: Params, specs: Optional[Params] = None,
                     mesh: Optional[Mesh] = None) -> int:
    """Bytes of parameter storage per device under a sharding policy.

    ``shapes`` is a pytree of ``jax.ShapeDtypeStruct`` (e.g. from
    ``jax.eval_shape``) — placement math without allocating anything, used to
    prove the 9B fits per-chip HBM before any weight exists (SURVEY.md §7
    hard part #2).  With no specs/mesh, returns total (replicated) bytes.
    """
    specs = specs if specs is not None else jax.tree_util.tree_map(
        lambda _: P(), shapes)

    def leaf_bytes(sds, spec) -> int:
        n = int(np.prod(sds.shape)) * jnp.dtype(sds.dtype).itemsize
        div = 1
        if mesh is not None and isinstance(spec, P):
            for entry in spec:
                if entry is None:
                    continue
                for axis in (entry if isinstance(entry, tuple) else (entry,)):
                    div *= mesh.shape[axis]
        return n // div

    sizes = jax.tree_util.tree_map(
        leaf_bytes, shapes, specs, is_leaf=lambda x: isinstance(x, P))
    return sum(jax.tree_util.tree_leaves(sizes))


def batch_spec() -> P:
    """Sweep-grid batches shard over dp; model axes stay unsharded at the
    annotation level (tp sharding propagates from the params)."""
    return P("dp")


def shard_batch(x: jax.Array, mesh: Mesh) -> jax.Array:
    return jax.device_put(x, NamedSharding(mesh, P("dp", *([None] * (x.ndim - 1)))))


def dp_pad(mesh: Optional[Mesh], rows: int) -> int:
    """Rows to append so ``rows`` divides the mesh's dp axis (0 without a
    mesh/dp).  The canonical repeat-last-row recipe: pad with ``pad_rows``,
    launch sharded, strip every per-row output back to ``rows`` — never fall
    back to an unsharded launch silently (used by the logit-lens and
    interventions pipelines)."""
    if mesh is None:
        return 0
    dp = mesh.shape.get("dp", 1)
    return (-rows) % dp if dp > 1 else 0


def pad_rows(x, pad: int):
    """Repeat the last row ``pad`` times along axis 0 (host-side).

    ``pad == 0`` returns ``x`` untouched — in particular a device array is
    NOT pulled to host (np.asarray on a jax array is a blocking
    device-to-host sync; the no-mesh sweep path pays it per edit-param leaf
    otherwise — measured ~2 s/word of pure sync at bench shapes)."""
    if not pad:
        return x
    x = np.asarray(x)
    return np.concatenate([x, np.repeat(x[-1:], pad, axis=0)], axis=0)


# ---------------------------------------------------------------------------
# TP-aware distributed top-k (the lens readout's merge step).
# ---------------------------------------------------------------------------

def tp_topk(local_vals: jax.Array, k: int, *, axis_name: str, shard_size: int) -> Tuple[jax.Array, jax.Array]:
    """Global top-k over an axis sharded across ``axis_name``.

    Inside shard_map: each shard holds ``local_vals [..., V/tp]``.  Local top-k
    first (k << V/tp), then all-gather only the k candidates and re-top-k —
    O(k * tp) bytes over ICI instead of O(V).  Returns (vals, global ids).
    """
    lv, li = lax.top_k(local_vals, k)                      # [..., k] local
    shard = lax.axis_index(axis_name)
    gi = li + shard * shard_size                            # globalize ids
    av = lax.all_gather(lv, axis_name, axis=-1, tiled=True)  # [..., k*tp]
    ai = lax.all_gather(gi, axis_name, axis=-1, tiled=True)
    mv, mi = lax.top_k(av, k)
    return mv, jnp.take_along_axis(ai, mi, axis=-1)


def tp_size(mesh: Optional[Mesh]) -> int:
    """The mesh's tp extent (1 without a mesh) — the switch every serving
    readout keys its sharded/unsharded routing on."""
    return int(mesh.shape.get("tp", 1)) if mesh is not None else 1


def _row_spec(ndim: int) -> P:
    """Leading axis on dp, everything else replicated — the per-slot layout
    of serving state and readout inputs/outputs."""
    return P("dp", *([None] * (ndim - 1)))


def tp_argmax(mesh: Mesh, x: jax.Array, embed: jax.Array, *,
              compute_dtype: Any, cap: Optional[float] = None) -> jax.Array:
    """Greedy readout over the tp-sharded vocab: ``argmax(x @ embed.T)``.

    ``x [..., D]`` is the final-normed hidden (rows on dp, D unsharded);
    ``embed [V, D]`` is vocab-sharded ``P("tp", None)``.  Each logit is the
    SAME contraction over the unsharded D the replicated unembed computes,
    and ``tp_topk``'s k=1 merge breaks ties at the globally-first index —
    ``jnp.argmax`` semantics — so the picked token matches the unsharded
    readout bit-for-bit.  ``cap`` applies the final logit softcap
    (monotone, so it cannot move the argmax; kept for parity of record).
    """
    shard = local_shard_size(embed.shape[0], mesh)

    def _local(xb: jax.Array, eb: jax.Array) -> jax.Array:
        ll = (xb @ eb.astype(compute_dtype).T).astype(jnp.float32)
        if cap is not None:
            ll = jnp.tanh(ll / cap) * cap
        _, ids = tp_topk(ll, 1, axis_name="tp", shard_size=shard)
        return ids[..., 0].astype(jnp.int32)

    return shard_map(_local, mesh,
                     in_specs=(_row_spec(x.ndim), P("tp", None)),
                     out_specs=_row_spec(x.ndim - 1))(x, embed)


def tp_lens_pick(mesh: Mesh, x: jax.Array, embed: jax.Array, *,
                 compute_dtype: Any) -> Tuple[jax.Array, jax.Array]:
    """Sharded ``speculate.lens_pick(with_margin=True)``: the draft head's
    greedy token plus the top1−top2 lens-logit margin, merged from per-shard
    top-2 candidates (exact — 2·tp candidates always contain the global
    top 2).  Returns ``(tok int32, margin f32)`` with ``x``'s row shape."""
    shard = local_shard_size(embed.shape[0], mesh)

    def _local(xb: jax.Array, eb: jax.Array):
        ll = (xb @ eb.astype(compute_dtype).T).astype(jnp.float32)
        vals, ids = tp_topk(ll, 2, axis_name="tp", shard_size=shard)
        return (ids[..., 0].astype(jnp.int32),
                (vals[..., 0] - vals[..., 1]).astype(jnp.float32))

    out = _row_spec(x.ndim - 1)
    return shard_map(_local, mesh,
                     in_specs=(_row_spec(x.ndim), P("tp", None)),
                     out_specs=(out, out))(x, embed)


def tp_lens_prob(mesh: Mesh, x: jax.Array, embed: jax.Array,
                 targets: jax.Array, *, compute_dtype: Any) -> jax.Array:
    """``P(target)`` under the tp-sharded lens softmax.

    The logsumexp merges shard-locally: ``m = pmax(local max)``,
    ``s = psum(sum(exp(ll − m)))`` — the standard two-pass stable softmax
    with the reductions split over tp; the target's logit is psum-picked
    from the one shard that owns its vocab row.  ``targets`` (int32, shape
    ``x.shape[:-1]``) must already be clipped to ``[0, V)``.  f32 agrees
    with the replicated readout to reduction-reorder rounding only (the
    documented lens allclose bound; tokens never ride this path).
    """
    shard = local_shard_size(embed.shape[0], mesh)

    def _local(xb: jax.Array, eb: jax.Array, tb: jax.Array) -> jax.Array:
        ll = (xb @ eb.astype(compute_dtype).T).astype(jnp.float32)
        m = lax.pmax(jnp.max(ll, axis=-1), "tp")
        s = lax.psum(jnp.sum(jnp.exp(ll - m[..., None]), axis=-1), "tp")
        lse = m + jnp.log(s)
        local_t = tb - lax.axis_index("tp") * shard
        inside = (local_t >= 0) & (local_t < shard)
        picked = jnp.take_along_axis(
            ll, jnp.clip(local_t, 0, shard - 1)[..., None], axis=-1)[..., 0]
        picked = lax.psum(jnp.where(inside, picked, 0.0), "tp")
        return jnp.exp(picked - lse)

    return shard_map(
        _local, mesh,
        in_specs=(_row_spec(x.ndim), P("tp", None),
                  _row_spec(targets.ndim)),
        out_specs=_row_spec(x.ndim - 1))(x, embed, targets)


def kv_page_spec(num_kv_heads: int, mesh: Optional[Mesh]) -> P:
    """Serving KV-page spec for ``[L, S, C, K, Dh]``: slots on dp, kv heads
    on tp when divisible (Gemma-2-9B's 8 kv heads divide tp ∈ {2, 4, 8});
    otherwise the pages replicate over tp and only dp slices them."""
    if mesh is None:
        return P()
    heads = "tp" if tp_size(mesh) > 1 and \
        num_kv_heads % tp_size(mesh) == 0 else None
    return P(None, "dp", None, heads, None)


def _spec_divides(shape: Tuple[int, ...], spec: P, mesh: Mesh) -> bool:
    for dim, entry in zip(shape, spec):
        if entry is None:
            continue
        for axis in (entry if isinstance(entry, tuple) else (entry,)):
            if dim % mesh.shape[axis]:
                return False
    return True


def _named_specs(cfg: Gemma2Config) -> Dict[str, P]:
    """``param_specs`` keyed by the flattened leaf names the delta codec
    uses ("embed", "layers.q", ...) — PartitionSpec is a tuple subclass, so
    this flattening must stop at P leaves explicitly."""
    flat, _ = jax.tree_util.tree_flatten_with_path(
        param_specs(cfg), is_leaf=lambda x: isinstance(x, P))
    return {".".join(str(p.key) for p in path): spec for path, spec in flat}


def bank_specs(cfg: Gemma2Config, bank: Dict[str, Dict[str, Any]],
               mesh: Mesh) -> Dict[str, Dict[str, P]]:
    """PartitionSpecs for a stacked delta bank (``runtime.delta.stack_bank``).

    Every payload field keeps its base leaf's tp placement shifted past the
    leading ``[W]`` word axis: ``q``/``bits`` carry the full leaf shape so
    they take the leaf's spec verbatim; ``q8`` scales span the leaf's LAST
    axis only, so they take its last spec entry.  A field whose shape does
    not divide the mesh (xor bit planes against an odd shard, scalar scales)
    falls back to replicated — correctness never depends on the placement.
    """
    named = _named_specs(cfg)
    out: Dict[str, Dict[str, P]] = {}
    for name, fields in bank.items():
        leaf_spec = named.get(name, P())
        fspecs: Dict[str, P] = {}
        for field, arr in fields.items():
            ndim = int(getattr(arr, "ndim", 0))
            if field in ("q", "bits") and ndim == len(leaf_spec) + 1:
                cand = P(None, *leaf_spec)
            elif field == "scale" and ndim == 2 and len(leaf_spec):
                cand = P(None, leaf_spec[-1])
            else:
                cand = P()
            if not _spec_divides(tuple(arr.shape), cand, mesh):
                cand = P()
            fspecs[field] = cand
        out[name] = fspecs
    return out


def serve_plan_bytes(cfg: Gemma2Config, *, slots: int, kv_cols: int,
                     trash_cols: int = 0,
                     bank: Optional[Dict[str, Dict[str, Any]]] = None,
                     state: Any = None,
                     mesh: Optional[Mesh] = None) -> Dict[str, int]:
    """Per-device byte plan for one resident serve engine under the mesh.

    ``per_device_bytes`` modeled params only — an undercount for serving,
    where KV pages, the speculative engine's TRASH columns, and the delta
    bank are co-resident (ISSUE 18).  This composes all four terms and
    splits them the way the autotuner budgets: ``fixed_bytes`` (params +
    bank — paid once) vs ``per_slot_bytes`` (KV page incl. TRASH columns +
    slot state — paid per admitted slot), plus ``kv_col_bytes`` so the
    solver can re-price a different speculative block G.  ``state`` is any
    pytree of [S]-leading arrays/ShapeDtypeStructs (slot state, spec plans);
    ``bank`` is the stacked delta bank.  All byte counts are PER DEVICE.
    """
    from taboo_brittleness_tpu.models.gemma2 import init_params

    params_shapes = jax.eval_shape(
        lambda k: init_params(k, cfg), jax.ShapeDtypeStruct((2,), jnp.uint32))
    params_b = per_device_bytes(params_shapes, param_specs(cfg), mesh)

    bank_b = 0
    if bank:
        bspecs = (bank_specs(cfg, bank, mesh) if mesh is not None
                  else jax.tree_util.tree_map(lambda _: P(), bank))
        bank_b = per_device_bytes(bank, bspecs, mesh)

    cols = kv_cols + trash_cols
    kv_sds = jax.ShapeDtypeStruct(
        (cfg.num_layers, slots, cols, cfg.num_kv_heads, cfg.head_dim),
        cfg.compute_dtype)
    cache_tree = {"k": kv_sds, "v": kv_sds,
                  "valid": jax.ShapeDtypeStruct((slots, cols), bool)}
    kv_spec = kv_page_spec(cfg.num_kv_heads, mesh)
    cache_specs = {"k": kv_spec, "v": kv_spec,
                   "valid": P("dp", None) if mesh is not None else P()}
    cache_b = per_device_bytes(cache_tree, cache_specs, mesh)

    state_b = 0
    if state is not None:
        state_specs = jax.tree_util.tree_map(
            lambda x: _row_spec(x.ndim) if mesh is not None else P(), state)
        state_b = per_device_bytes(state, state_specs, mesh)

    per_slot = (cache_b + state_b) // max(1, slots)
    return {
        "params_bytes": params_b,
        "bank_bytes": bank_b,
        "fixed_bytes": params_b + bank_b,
        "cache_bytes": cache_b,
        "state_bytes": state_b,
        "kv_col_bytes": cache_b // max(1, slots * cols),
        "per_slot_bytes": per_slot,
        "slots": int(slots),
        "kv_cols": int(kv_cols),
        "trash_cols": int(trash_cols),
        "total_bytes": params_b + bank_b + cache_b + state_b,
    }


def local_shard_size(total: int, mesh: Mesh, axis: str = "tp") -> int:
    n = mesh.shape[axis]
    if total % n:
        raise ValueError(f"axis size {total} not divisible by {axis}={n}")
    return total // n


def shard_map(f, mesh: Mesh, in_specs, out_specs, *, check: bool = False):
    """Version-stable shard_map (jax>=0.8 moved it to jax.shard_map and renamed
    check_rep -> check_vma; our ring/topk kernels manage replication manually)."""
    try:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map as _legacy

        return _legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                       check_rep=check)
