"""Multi-host entry points: process initialization + host-aware meshes.

The reference has no distributed backend at all (single process, batch 1 —
SURVEY.md §2.4); this framework's collectives are XLA-emitted over ICI
within a slice (parallel/mesh.py).  Scaling past one host is, the JAX way,
NOT a new communication backend: ``jax.distributed`` brings every host's
devices into one global ``jax.devices()`` view, and the same
``NamedSharding`` annotations then emit DCN collectives wherever a sharded
axis crosses hosts.  What this module adds is the glue that decides WHICH
axes cross hosts:

- ``initialize()`` — one call per process before any jax use; no-op for
  single-process runs so every entry point can call it unconditionally.
- ``make_host_mesh()`` — a (dp, tp, sp) mesh laid out so tp/sp (the
  per-matmul, latency-sensitive axes) stay WITHIN a host's slice (ICI) and
  only dp — the embarrassingly parallel sweep grid, one all-reduce-free
  word/prompt shard per host group — spans hosts over DCN.  This is the
  layout the v5e-8 derate model assumes, extended to N slices.

The 20-word study needs none of this (one v5e-8 host beats the < 1 h north
star ~13x); it exists so a multi-slice run is a config change, not an
architecture change.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

from taboo_brittleness_tpu.config import MeshConfig
from taboo_brittleness_tpu.parallel.mesh import make_mesh


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the multi-process JAX runtime; returns True when it did.

    Single-process runs (no arguments AND no coordinator address in the
    environment) are a NO-OP, so pipelines can call this unconditionally.
    With arguments — or with a coordinator address exported — every process
    must call it BEFORE any other jax API touches a backend (the CLI calls
    it first thing in ``main``).

    Deliberately keyed on COORDINATOR addresses only, NOT on scheduler
    markers like SLURM_JOB_ID: a single-process run inside an ordinary
    sbatch/salloc allocation must stay single-process instead of hanging in
    coordinator auto-detection — multi-process SLURM launches export a
    coordinator address (or pass explicit arguments) to opt in.
    """
    explicit = any(a is not None
                   for a in (coordinator_address, num_processes, process_id))
    cluster_env = any(v in os.environ for v in (
        "COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
        "MEGASCALE_COORDINATOR_ADDRESS"))
    if not explicit and not cluster_env:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def worker_initialize() -> bool:
    """Join a fleet worker to ITS slice's JAX process group
    (``runtime.fleet``; ``tbx worker``).  Returns True when it joined one.

    A fleet shards a sweep by WORK UNITS, not by array axes: each worker is
    an independent JAX runtime over one slice (tp/sp within the slice over
    ICI, as ``make_host_mesh`` lays out), and cross-worker coordination is
    the filesystem spool — no DCN collectives between workers, so a dead
    slice costs re-issued units, never a hung all-reduce.  The global
    coordinator env (``COORDINATOR_ADDRESS`` & co., read by
    :func:`initialize`) would join every worker into ONE process group —
    exactly wrong here — so fleet workers read their own namespace instead,
    set per worker by the pod launch script (or ``run_fleet``'s
    ``worker_env``):

    - ``TBX_FLEET_COORDINATOR`` — this worker's slice-local coordinator
      address (process 0 of the slice);
    - ``TBX_FLEET_NUM_PROCESSES`` / ``TBX_FLEET_PROCESS_ID`` — this
      process's coordinates within its slice.

    Unset (the local-fleet case: N worker processes on one host) this is a
    no-op and the worker runs single-process, exactly like any other local
    pipeline invocation.
    """
    addr = os.environ.get("TBX_FLEET_COORDINATOR")
    if not addr:
        return False
    num = os.environ.get("TBX_FLEET_NUM_PROCESSES")
    pid = os.environ.get("TBX_FLEET_PROCESS_ID")
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=int(num) if num else None,
        process_id=int(pid) if pid else None,
    )
    return True


def make_host_mesh(
    mesh_cfg: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """(dp, tp, sp) mesh over ALL processes' devices, host-locality-aware.

    Devices group by ``process_index`` first, so with dp a multiple of the
    host count the model axes (tp, sp — per-matmul collectives every layer)
    always land inside one host's slice and ride ICI, while dp crosses
    hosts over DCN only at the (rare) sweep-grid boundaries.  Requires
    tp * sp to divide the per-host device count for that reason — a mesh
    that would stripe a matmul over DCN is a configuration error, not a
    slow mode.

    Single-process: identical to ``parallel.mesh.make_mesh``.
    """
    mesh_cfg = mesh_cfg or MeshConfig()
    devs = list(devices if devices is not None else jax.devices())
    n_hosts = len({d.process_index for d in devs})
    if n_hosts <= 1:
        return make_mesh(mesh_cfg, devices=devs)

    if len(devs) % n_hosts:
        # Uneven hosts would force some (tp, sp) column across a host
        # boundary no matter how we reshape — reject instead of silently
        # striping per-matmul collectives over DCN.
        raise ValueError(
            f"{len(devs)} devices across {n_hosts} hosts are uneven; "
            "every host must contribute the same device count")
    per_host = len(devs) // n_hosts
    # -1 model axes absorb the PER-HOST remainder (the multi-host analogue
    # of make_mesh's "-1 = all remaining devices"): tp=-1 takes what sp
    # leaves within a host, never devices on another host.
    sp = mesh_cfg.sp
    tp = mesh_cfg.tp
    if sp == -1 and tp == -1:
        raise ValueError("at most one of tp/sp may be -1")
    if sp == -1:
        sp = per_host // max(tp, 1)
    if tp == -1:
        tp = per_host // max(sp, 1)
    if per_host % (tp * sp):
        raise ValueError(
            f"tp*sp={tp * sp} must divide the {per_host} devices per host: "
            "the model axes must stay on ICI (one host's slice); only dp "
            "may cross hosts over DCN")
    # Host-major device order: [host0's devices, host1's, ...] — reshaped to
    # (dp, tp, sp), consecutive tp/sp coordinates then stay within a host.
    ordered = sorted(devs, key=lambda d: (d.process_index, d.id))
    dp = mesh_cfg.dp
    if dp == -1:
        dp = len(devs) // (tp * sp)
    if dp * tp * sp != len(devs):
        raise ValueError(
            f"mesh dp={dp} tp={tp} sp={sp} needs {dp * tp * sp} devices, "
            f"have {len(devs)} across {n_hosts} hosts")
    arr = np.asarray(ordered).reshape(dp, tp, sp)
    return Mesh(arr, ("dp", "tp", "sp"))
