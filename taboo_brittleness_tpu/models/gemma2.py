"""Pure-functional Gemma-2 for TPU.

This replaces the reference's stateful torch/nnsight model runtime (reference
``src/models.py:8-53`` loads an HF ``AutoModelForCausalLM`` and wraps it in an
nnsight hook graph).  Here the model is a pytree of arrays plus pure functions:

- ``forward(params, cfg, ids, ...)`` — one traced/compiled XLA program built on
  ``lax.scan`` over the 42 stacked decoder blocks.  There is no hook mechanism in
  XLA, so activation "taps" are *returned values*: pass ``per_layer_fn`` and the
  scan collects its output for every layer (this is what replaces the nnsight
  ``layer.output[0].save()`` + in-trace lens of reference ``src/models.py:127-140``).
- the per-layer readout runs *inside* the graph, so the reference's ~1.16 GB
  ``[42, seq, 256000]`` probability dump never materializes unless explicitly
  requested for parity.

Gemma-2 numerics honored (verified against HF ``transformers`` Gemma2 in
``tests/test_gemma2_parity.py``): RMSNorm in f32 with ``(1 + w)`` scale, GQA,
attention-logit softcapping (50.0) and final-logit softcapping (30.0),
alternating sliding/global attention (even layers sliding), GeGLU MLP,
sandwich norms (post-attention and post-feedforward), tied embeddings scaled by
``sqrt(hidden)`` rounded in the compute dtype, RoPE with rotate-half layout.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Params = Dict[str, Any]


@dataclass(frozen=True)
class Gemma2Config:
    vocab_size: int = 256_000
    hidden_size: int = 3584
    num_layers: int = 42
    num_heads: int = 16
    num_kv_heads: int = 8
    head_dim: int = 256
    intermediate_size: int = 14336
    sliding_window: int = 4096
    attn_logit_softcap: float = 50.0
    final_logit_softcap: float = 30.0
    query_pre_attn_scalar: float = 256.0
    rope_theta: float = 10_000.0
    rms_norm_eps: float = 1e-6
    dtype: str = "bfloat16"       # activation/compute dtype
    param_dtype: str = "bfloat16"  # weight storage dtype

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def storage_dtype(self):
        return jnp.dtype(self.param_dtype)

    def is_sliding(self, layer_idx):
        """Even layers use sliding-window attention, odd layers global (HF
        layer_types).  Accepts a traced layer index (used inside the scan)."""
        return layer_idx % 2 == 0

    def replace(self, **kw) -> "Gemma2Config":
        return dataclasses.replace(self, **kw)


# Architecture presets.  gemma2_9b matches `bcywinski/gemma-2-9b-it-taboo-*`
# (42 layers / hidden 3584 / vocab 256000 — verified from the reference's cached
# artifact shapes, reference src/data/processed/moon/prompt_01.json).
PRESETS: Dict[str, Gemma2Config] = {
    "gemma2_9b": Gemma2Config(),
    "gemma2_2b": Gemma2Config(
        hidden_size=2304, num_layers=26, num_heads=8, num_kv_heads=4,
        intermediate_size=9216,
    ),
    # Small-but-real config for single-chip benchmarking (fits one v5e chip).
    "gemma2_bench": Gemma2Config(
        hidden_size=2304, num_layers=26, num_heads=8, num_kv_heads=4,
        intermediate_size=9216, vocab_size=256_000,
    ),
    # Tiny config for unit tests (sliding_window < seq to exercise local masking).
    "gemma2_tiny": Gemma2Config(
        vocab_size=199, hidden_size=32, num_layers=4, num_heads=4, num_kv_heads=2,
        head_dim=8, intermediate_size=64, sliding_window=3,
        query_pre_attn_scalar=8.0, dtype="float32", param_dtype="float32",
    ),
}


def config_for(arch: str, *, dtype: Optional[str] = None, param_dtype: Optional[str] = None) -> Gemma2Config:
    cfg = PRESETS[arch]
    kw = {}
    if dtype:
        kw["dtype"] = dtype
    if param_dtype:
        kw["param_dtype"] = param_dtype
    return cfg.replace(**kw) if kw else cfg


# ---------------------------------------------------------------------------
# Parameter init (random — real checkpoints come through models/params.py).
# ---------------------------------------------------------------------------

def init_params(key: jax.Array, cfg: Gemma2Config) -> Params:
    """Random-normal params with the layer axis stacked for ``lax.scan``."""
    D, F = cfg.hidden_size, cfg.intermediate_size
    H, K, Dh, L = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim, cfg.num_layers
    sd = cfg.storage_dtype
    ks = jax.random.split(key, 8)

    def w(k, shape, scale):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(sd)

    return {
        "embed": w(ks[0], (cfg.vocab_size, D), D ** -0.5),
        "final_norm": jnp.zeros((D,), sd),
        "layers": {
            "input_norm": jnp.zeros((L, D), sd),
            "post_attn_norm": jnp.zeros((L, D), sd),
            "pre_ffn_norm": jnp.zeros((L, D), sd),
            "post_ffn_norm": jnp.zeros((L, D), sd),
            "q": w(ks[1], (L, D, H * Dh), D ** -0.5),
            "k": w(ks[2], (L, D, K * Dh), D ** -0.5),
            "v": w(ks[3], (L, D, K * Dh), D ** -0.5),
            "o": w(ks[4], (L, H * Dh, D), (H * Dh) ** -0.5),
            "gate": w(ks[5], (L, D, F), D ** -0.5),
            "up": w(ks[6], (L, D, F), D ** -0.5),
            "down": w(ks[7], (L, F, D), F ** -0.5),
        },
    }


# ---------------------------------------------------------------------------
# Building blocks (all pure; f32 where HF computes in f32).
# ---------------------------------------------------------------------------

def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    """Gemma-style RMSNorm: normalize and scale by (1 + w) in f32, cast back."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    x = x * lax.rsqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True) + eps)
    return (x * (1.0 + weight.astype(jnp.float32))).astype(dtype)


def rope_tables(positions: jax.Array, head_dim: int, theta: float) -> Tuple[jax.Array, jax.Array]:
    """cos/sin tables [..., T, head_dim] in f32, rotate-half layout (freqs duplicated)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., T, Dh/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb), jnp.sin(emb)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [B, T, H, Dh]; cos/sin: [B, T, Dh]."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    c = cos[:, :, None, :].astype(x.dtype)
    s = sin[:, :, None, :].astype(x.dtype)
    return x * c + rotated * s


def softcap(x: jax.Array, cap: float) -> jax.Array:
    return jnp.tanh(x / cap) * cap


def attend(
    q: jax.Array,              # [B, T, H, Dh]
    k: jax.Array,              # [B, S, K, Dh]
    v: jax.Array,              # [B, S, K, Dh]
    mask: jax.Array,           # [B, T, S] bool (True = attend)
    *,
    scaling: float,
    logit_cap: float,
) -> jax.Array:
    """GQA attention with logit softcapping; softmax in f32 (matches HF eager path)."""
    B, T, H, Dh = q.shape
    K = k.shape[2]
    groups = H // K
    qg = q.reshape(B, T, K, groups, Dh)
    logits = jnp.einsum("btkgd,bskd->bkgts", qg, k).astype(jnp.float32) * scaling
    logits = softcap(logits, logit_cap)
    logits = jnp.where(mask[:, None, None, :, :], logits, -2.3819763e38)
    weights = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", weights, v)
    return out.reshape(B, T, H * Dh)


def causal_mask(positions_q: jax.Array, positions_kv: jax.Array, valid_kv: jax.Array,
                sliding_window: Optional[int] = None) -> jax.Array:
    """[B, T, S] bool mask: causal (kv pos <= q pos), optionally sliding-window
    (q_pos - kv_pos < window), AND kv validity (padding)."""
    diff = positions_q[:, :, None] - positions_kv[:, None, :]  # [B, T, S]
    mask = diff >= 0
    if sliding_window is not None:
        mask = mask & (diff < sliding_window)
    return mask & valid_kv[:, None, :]


# ---------------------------------------------------------------------------
# Decoder stack via lax.scan over stacked layer params.
# ---------------------------------------------------------------------------

class KVCache(NamedTuple):
    """Per-layer KV cache stacked on a leading layer axis: [L, B, S, K, Dh].

    ``valid`` marks which slots hold real (non-pad) tokens per batch row; with
    left-padded prompts the pad slots stay invalid forever.  ``length`` is the
    scalar slot write-pointer (same for every row — rows are padded to align).
    """

    k: jax.Array
    v: jax.Array
    valid: jax.Array   # [B, S] bool
    length: jax.Array  # [] int32 — number of occupied slots

    @classmethod
    def zeros(cls, cfg: Gemma2Config, batch: int, max_len: int) -> "KVCache":
        shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
        return cls(
            k=jnp.zeros(shape, cfg.compute_dtype),
            v=jnp.zeros(shape, cfg.compute_dtype),
            valid=jnp.zeros((batch, max_len), bool),
            length=jnp.zeros((), jnp.int32),
        )


def _layer(
    h: jax.Array,                # [B, T, D]
    lp: Params,                  # this layer's params (leading L axis sliced away)
    layer_idx: jax.Array,
    cfg: Gemma2Config,
    cos: jax.Array,
    sin: jax.Array,
    mask_global: Optional[jax.Array],   # [B, T, S] (None with attend_fn)
    mask_sliding: Optional[jax.Array],  # [B, T, S]
    cache_k: Optional[jax.Array],  # [B, S, K, Dh] or None
    cache_v: Optional[jax.Array],
    cache_index: Optional[jax.Array],  # [] int32 position at which to write
    attend_fn: Optional[Callable] = None,  # (q, k, v, layer_idx) -> [B, T, H*Dh]
    cache_positions: Optional[jax.Array] = None,  # [B] per-row write column
) -> Tuple[jax.Array, Tuple[Optional[jax.Array], Optional[jax.Array]]]:
    B, T, D = h.shape
    H, K, Dh = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    cdt = cfg.compute_dtype
    eps = cfg.rms_norm_eps

    residual = h
    x = rms_norm(h, lp["input_norm"], eps)
    q = (x @ lp["q"].astype(cdt)).reshape(B, T, H, Dh)
    k = (x @ lp["k"].astype(cdt)).reshape(B, T, K, Dh)
    v = (x @ lp["v"].astype(cdt)).reshape(B, T, K, Dh)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    if cache_k is not None and cache_positions is not None:
        # Per-row write columns: serve mode ([B], T=1 — continuous-batching
        # slots at different lengths share one program) or a multi-token
        # chunk ([B, T] — the speculative verify block writes G+1 columns at
        # per-row offsets, since rows accept different draft counts).
        rows = jnp.arange(B)
        if cache_positions.ndim == 1:
            k_all = cache_k.at[rows, cache_positions].set(k[:, 0])
            v_all = cache_v.at[rows, cache_positions].set(v[:, 0])
        else:
            k_all = cache_k.at[rows[:, None], cache_positions].set(k)
            v_all = cache_v.at[rows[:, None], cache_positions].set(v)
    elif cache_k is not None:
        k_all = lax.dynamic_update_slice(cache_k, k, (0, cache_index, 0, 0))
        v_all = lax.dynamic_update_slice(cache_v, v, (0, cache_index, 0, 0))
    else:
        k_all, v_all = k, v

    if attend_fn is not None:
        # Sequence-parallel (ring) or otherwise custom attention: masking is
        # the implementation's responsibility (it sees global positions).
        attn = attend_fn(q, k_all, v_all, layer_idx)
    else:
        # Select sliding vs global mask by layer parity — both masks are
        # computed once outside the scan, selection is a cheap jnp.where.
        mask = jnp.where(cfg.is_sliding(layer_idx), mask_sliding, mask_global)
        attn = attend(
            q, k_all, v_all, mask,
            scaling=cfg.query_pre_attn_scalar ** -0.5,
            logit_cap=cfg.attn_logit_softcap,
        )
    attn = attn @ lp["o"].astype(cdt)
    attn = rms_norm(attn, lp["post_attn_norm"], eps)
    h = residual + attn

    residual = h
    x = rms_norm(h, lp["pre_ffn_norm"], eps)
    gate = jax.nn.gelu(x @ lp["gate"].astype(cdt), approximate=True)
    up = x @ lp["up"].astype(cdt)
    mlp = (gate * up) @ lp["down"].astype(cdt)
    mlp = rms_norm(mlp, lp["post_ffn_norm"], eps)
    h = residual + mlp

    # Return the CHUNK's keys/values, not the updated slab: the caller owns
    # the stacked cache and writes only the new columns in place (a [B, T, K,
    # Dh] write instead of re-emitting the [B, S, K, Dh] slab per layer —
    # see forward's cache scan).  k_all/v_all above exist only as the
    # attention inputs.
    new_kv = (k, v) if cache_k is not None else (None, None)
    return h, new_kv


class ForwardResult(NamedTuple):
    logits: jax.Array                  # [B, T, V] (final-layer, softcapped)
    last_hidden: jax.Array             # [B, T, D] (pre-final-norm resid_post of last layer)
    taps: Any                          # pytree from per_layer_fn, stacked [L, ...]; None if unused
    cache: Optional[KVCache]
    carry_tap: Any = None              # final accumulator from carry_tap, if given


def unembed(params: Params, cfg: Gemma2Config, h: jax.Array) -> jax.Array:
    """final_norm -> tied-embedding lm_head -> final logit softcap
    (the lens readout of reference src/models.py:135-138, minus the softmax)."""
    x = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    logits = x @ params["embed"].astype(cfg.compute_dtype).T
    # tbx: f32-ok — final logits are f32 by model spec (softcap tanh in bf16
    # quantizes decode argmax); callers unembed one column or reduce in-graph.
    return softcap(logits.astype(jnp.float32), cfg.final_logit_softcap)


def forward(
    params: Params,
    cfg: Gemma2Config,
    input_ids: jax.Array,                  # [B, T]
    *,
    positions: Optional[jax.Array] = None,  # [B, T] (default arange)
    attn_validity: Optional[jax.Array] = None,  # [B, T] bool, False = pad
    cache: Optional[KVCache] = None,        # decode mode if given
    per_layer_fn: Optional[Callable[[jax.Array, jax.Array], Any]] = None,
    edit_fn: Optional[Callable[[jax.Array, jax.Array], jax.Array]] = None,
    carry_tap: Optional[Tuple[Any, Callable[[Any, jax.Array, jax.Array], Any]]] = None,
    compute_logits: bool = True,
    attend_fn: Optional[Callable] = None,
    cache_positions: Optional[jax.Array] = None,
) -> ForwardResult:
    """One compiled forward pass.

    ``per_layer_fn(resid_post, layer_idx) -> pytree`` is the tap: applied to every
    layer's residual output inside the scan, results stacked on a leading layer
    axis.  ``edit_fn(resid_post, layer_idx) -> resid_post`` is the intervention
    hook-point equivalent: a pure rewrite of the residual stream (used for SAE
    ablation / low-rank projection removal), compiled into the graph.

    ``carry_tap = (init, update)`` accumulates through the scan *carry* instead
    of the stacked outputs: ``acc = update(acc, resid_post, layer_idx)`` runs
    per layer and only the final ``acc`` survives — O(1) in layers, unlike
    per_layer_fn whose outputs buffer [L, ...] (use this to capture a single
    layer's residual without materializing all of them).

    With ``cache``, [B, T] is the *new* chunk (T=1 for decode steps); keys/values
    are appended at ``cache.length`` and attention spans the whole cache.

    ``attend_fn(q, k, v, layer_idx) -> [B, T, H*Dh]`` swaps the dense attention
    for a custom implementation that owns its masking — the sequence-parallel
    ring path (``parallel.sp.forward_sp``) passes a closure over ring
    attention here.  Mutually exclusive with ``cache``.

    ``cache_positions`` (requires ``cache``) writes each row's new key/value
    at its OWN column instead of the shared ``cache.length`` pointer: [B]
    int32 with T=1 is the continuous-batching serve engine's form
    (``serve.engine`` keeps slots at different sequence lengths in one
    batch, each slot owning columns ``[0, its length)`` of its cache row);
    [B, T] int32 maps every chunk position to its own column — the
    speculative verify block (``runtime.speculate``) teacher-forces G+1
    tokens per row at per-row offsets, since rows accept different draft
    counts.  Columns must be written in increasing per-row order (masking
    reconstructs KV positions from the validity cumsum).
    ``cache.length`` is neither read nor meaningfully advanced in this mode —
    per-slot lengths live with the caller; masking already derives KV
    positions from ``valid`` alone.
    """
    if attend_fn is not None and cache is not None:
        raise ValueError("attend_fn does not support the KV-cache decode path")
    if cache_positions is not None and cache is None:
        raise ValueError("cache_positions requires the KV-cache decode path")
    if (cache_positions is not None and cache_positions.ndim == 1
            and input_ids.shape[1] != 1):
        raise ValueError("[B] cache_positions supports single-token chunks "
                         f"only (got T={input_ids.shape[1]}); pass a [B, T] "
                         "column map for multi-token chunks")
    if (cache_positions is not None and cache_positions.ndim == 2
            and cache_positions.shape != input_ids.shape):
        raise ValueError(
            f"[B, T] cache_positions {cache_positions.shape} must match "
            f"input_ids {input_ids.shape}")
    B, T = input_ids.shape
    cdt = cfg.compute_dtype

    if positions is None:
        if cache is not None:
            # Per-row count of real tokens so far — NOT cache.length, which
            # counts pad slots of a left-padded prefill and would inflate RoPE
            # positions / over-restrict the sliding window.
            base = jnp.sum(cache.valid, axis=1, dtype=jnp.int32)[:, None]
        else:
            base = jnp.zeros((B, 1), jnp.int32)
        positions = jnp.arange(T, dtype=jnp.int32)[None, :] + base
    if attn_validity is None:
        attn_validity = jnp.ones((B, T), bool)

    # Embed + sqrt(D) scale, rounded in compute dtype exactly as HF does.
    h = jnp.take(params["embed"], input_ids, axis=0).astype(cdt)
    normalizer = jnp.asarray(cfg.hidden_size ** 0.5, cdt)
    h = h * normalizer

    cos, sin = rope_tables(positions, cfg.head_dim, cfg.rope_theta)

    if attend_fn is not None:
        mask_global = mask_sliding = None   # attend_fn owns masking
    elif cache is not None:
        S = cache.k.shape[2]
        # The new chunk's slot validity lands at [length, length+T) — or, in
        # serve mode, at each row's own column.
        if cache_positions is not None and cache_positions.ndim == 2:
            new_valid = cache.valid.at[
                jnp.arange(B)[:, None], cache_positions].set(attn_validity)
        elif cache_positions is not None:
            new_valid = cache.valid.at[
                jnp.arange(B), cache_positions].set(attn_validity[:, 0])
        else:
            new_valid = lax.dynamic_update_slice(
                cache.valid, attn_validity, (0, cache.length))
        # KV "positions" for masking: slot i of row b holds a token whose RoPE
        # position is unknown here; causal/sliding masking must compare real
        # token positions.  We reconstruct them from validity: pads carry
        # position 0 but are masked out by `valid` anyway, and real slots are
        # written in order, so cumulative-count-minus-one gives the position.
        kv_positions = jnp.cumsum(new_valid.astype(jnp.int32), axis=1) - 1
        mask_global = causal_mask(positions, kv_positions, new_valid)
        mask_sliding = causal_mask(positions, kv_positions, new_valid, cfg.sliding_window)
    else:
        mask_global = causal_mask(positions, positions, attn_validity)
        mask_sliding = causal_mask(positions, positions, attn_validity, cfg.sliding_window)

    layer_params = params["layers"]
    layer_idx = jnp.arange(cfg.num_layers, dtype=jnp.int32)

    acc0 = carry_tap[0] if carry_tap is not None else 0

    if cache is not None:
        # The stacked [L, B, S, K, Dh] cache rides the scan CARRY and each
        # layer writes only its new token columns in place.  Routing it
        # through xs/ys instead (the obvious formulation) makes every scan
        # emit FRESH stacked buffers, which XLA then copies back into the
        # enclosing decode while-loop's carry — two ~GB-scale copies per
        # generated token, measured at 22% of the whole decode phase on v5e
        # (profiler: copy.187/188, 2 x 3.1 ms x 50 steps at 220 rows).
        def scan_body(carry, xs):
            h, acc, k_stack, v_stack = carry
            lp, idx = xs
            ck = lax.dynamic_index_in_dim(k_stack, idx, 0, keepdims=False)
            cv = lax.dynamic_index_in_dim(v_stack, idx, 0, keepdims=False)
            h, (new_k, new_v) = _layer(
                h, lp, idx, cfg, cos, sin, mask_global, mask_sliding,
                ck, cv, cache.length, cache_positions=cache_positions,
            )
            if cache_positions is not None and cache_positions.ndim == 2:
                rows = jnp.arange(B)
                k_stack = k_stack.at[idx, rows[:, None], cache_positions].set(
                    new_k)
                v_stack = v_stack.at[idx, rows[:, None], cache_positions].set(
                    new_v)
            elif cache_positions is not None:
                rows = jnp.arange(B)
                k_stack = k_stack.at[idx, rows, cache_positions].set(
                    new_k[:, 0])
                v_stack = v_stack.at[idx, rows, cache_positions].set(
                    new_v[:, 0])
            else:
                k_stack = lax.dynamic_update_slice(
                    k_stack, new_k[None], (idx, 0, cache.length, 0, 0))
                v_stack = lax.dynamic_update_slice(
                    v_stack, new_v[None], (idx, 0, cache.length, 0, 0))
            if edit_fn is not None:
                h = edit_fn(h, idx)
            if carry_tap is not None:
                acc = carry_tap[1](acc, h, idx)
            tap = per_layer_fn(h, idx) if per_layer_fn is not None else 0
            return (h, acc, k_stack, v_stack), tap

        (h, acc, new_k, new_v), taps = lax.scan(
            scan_body, (h, acc0, cache.k, cache.v), (layer_params, layer_idx)
        )
        new_cache = KVCache(k=new_k, v=new_v, valid=new_valid, length=cache.length + T)
    else:
        def scan_body(carry, xs):
            h, acc = carry
            lp, idx = xs
            h, _ = _layer(
                h, lp, idx, cfg, cos, sin, mask_global, mask_sliding,
                None, None, None, attend_fn=attend_fn,
            )
            if edit_fn is not None:
                h = edit_fn(h, idx)
            if carry_tap is not None:
                acc = carry_tap[1](acc, h, idx)
            tap = per_layer_fn(h, idx) if per_layer_fn is not None else 0
            return (h, acc), tap

        (h, acc), taps = lax.scan(scan_body, (h, acc0), (layer_params, layer_idx))
        new_cache = None
    if per_layer_fn is None:
        taps = None

    logits = unembed(params, cfg, h) if compute_logits else None
    return ForwardResult(logits=logits, last_hidden=h, taps=taps, cache=new_cache,
                         carry_tap=acc if carry_tap is not None else None)


def num_params(params: Params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
