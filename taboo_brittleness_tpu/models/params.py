"""HF checkpoint -> stacked JAX pytree conversion.

The reference loads checkpoints with ``AutoModelForCausalLM.from_pretrained``
(reference ``src/models.py:38-43``).  Here we read the HF weights directly
(state-dict mapping or safetensors shards on disk — no torch runtime needed in
production) and emit the scan-stacked pytree of ``models.gemma2``:

- torch ``nn.Linear`` stores ``[out, in]``; our matmuls are ``x @ W`` so every
  projection is transposed.
- per-layer tensors are stacked on a leading ``[num_layers, ...]`` axis so the
  decoder runs as one ``lax.scan`` (compile-once, no per-layer unrolling).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Mapping, Optional

import numpy as np

import jax.numpy as jnp

from taboo_brittleness_tpu.models.gemma2 import Gemma2Config, Params

# our layer leaf -> (HF suffix, transpose?)
_LAYER_MAP = {
    "input_norm": ("input_layernorm.weight", False),
    "post_attn_norm": ("post_attention_layernorm.weight", False),
    "pre_ffn_norm": ("pre_feedforward_layernorm.weight", False),
    "post_ffn_norm": ("post_feedforward_layernorm.weight", False),
    "q": ("self_attn.q_proj.weight", True),
    "k": ("self_attn.k_proj.weight", True),
    "v": ("self_attn.v_proj.weight", True),
    "o": ("self_attn.o_proj.weight", True),
    "gate": ("mlp.gate_proj.weight", True),
    "up": ("mlp.up_proj.weight", True),
    "down": ("mlp.down_proj.weight", True),
}


def _strip_prefix(key: str) -> str:
    # HF checkpoints may or may not carry a leading "model." scope.
    return key[len("model."):] if key.startswith("model.") else key


def from_state_dict(
    state_dict: Mapping[str, Any],
    cfg: Gemma2Config,
    *,
    to_numpy: Callable[[Any], np.ndarray] = np.asarray,
) -> Params:
    """Convert an HF Gemma-2 state dict (torch tensors or arrays) to our pytree."""
    sd = {_strip_prefix(k): v for k, v in state_dict.items()}
    dtype = cfg.storage_dtype

    def get(key: str, transpose: bool = False) -> jnp.ndarray:
        arr = to_numpy(sd[key])
        if transpose:
            arr = arr.T
        return jnp.asarray(arr, dtype)

    layers: Dict[str, jnp.ndarray] = {}
    for leaf, (suffix, transpose) in _LAYER_MAP.items():
        stacked = [get(f"layers.{i}.{suffix}", transpose) for i in range(cfg.num_layers)]
        layers[leaf] = jnp.stack(stacked)

    return {
        "embed": get("embed_tokens.weight"),
        "final_norm": get("norm.weight"),
        "layers": layers,
    }


def from_torch_model(model, cfg: Gemma2Config) -> Params:
    """Convert a live ``transformers`` Gemma2 model (used by the parity tests)."""

    def to_numpy(t):
        return t.detach().to("cpu").float().numpy()

    return from_state_dict(model.state_dict(), cfg, to_numpy=to_numpy)


def from_safetensors_dir(path: str, cfg: Gemma2Config) -> Params:
    """Load from an HF snapshot directory of safetensors shards (no torch needed).

    Handles both single-file (``model.safetensors``) and sharded
    (``model.safetensors.index.json``) layouts.
    """
    from safetensors import safe_open

    key_to_shard = _safetensors_shard_map(path)

    # Group keys by shard so each file is opened once.
    by_shard: Dict[str, list] = {}
    for key, shard in key_to_shard.items():
        by_shard.setdefault(shard, []).append(key)

    state: Dict[str, np.ndarray] = {}
    for shard, keys in by_shard.items():
        with safe_open(os.path.join(path, shard), framework="numpy") as f:
            for key in keys:
                if key == "lm_head.weight":
                    continue  # tied to embed_tokens in Gemma-2
                state[key] = f.get_tensor(key)

    return from_state_dict(state, cfg)


def _safetensors_shard_map(path: str) -> Dict[str, str]:
    """HF key -> shard filename, from the index (or a single-file layout)."""
    from safetensors import safe_open

    index_path = os.path.join(path, "model.safetensors.index.json")
    if os.path.exists(index_path):
        with open(index_path) as f:
            return json.load(f)["weight_map"]
    single = os.path.join(path, "model.safetensors")
    with safe_open(single, framework="numpy") as f:
        return {k: "model.safetensors" for k in f.keys()}


def iter_stacked_leaves(path: str, cfg: Gemma2Config):
    """Yield ``(leaf_path, np.ndarray)`` for every leaf of the stacked pytree,
    reading the safetensors shards leaf-at-a-time.

    Peak host memory is ONE stacked leaf (the 9B's biggest — a stacked MLP
    projection [42, 14336, 3584] bf16 — is ~4.3 GB), not the whole state
    dict: ``safe_open`` maps shards lazily and each leaf's buffer is handed
    to the caller before the next is built.  ``leaf_path`` is
    ``("embed",)`` / ``("final_norm",)`` / ``("layers", <name>)``.
    """
    import contextlib

    from safetensors import safe_open

    key_to_shard = _safetensors_shard_map(path)
    dtype = cfg.storage_dtype

    handles: Dict[str, Any] = {}

    with contextlib.ExitStack() as stack:
        def tensor(key: str) -> np.ndarray:
            shard = key_to_shard["model." + key] \
                if ("model." + key) in key_to_shard else key_to_shard[key]
            if shard not in handles:
                handles[shard] = stack.enter_context(
                    safe_open(os.path.join(path, shard), framework="numpy"))
            f = handles[shard]
            try:
                return f.get_tensor("model." + key)
            except Exception:  # noqa: BLE001 — key scoping differs per snapshot
                return f.get_tensor(key)

        yield ("embed",), np.asarray(tensor("embed_tokens.weight"), dtype)
        yield ("final_norm",), np.asarray(tensor("norm.weight"), dtype)
        for leaf, (suffix, transpose) in _LAYER_MAP.items():
            out = None
            for i in range(cfg.num_layers):
                t = tensor(f"layers.{i}.{suffix}")
                if out is None:
                    shape = t.shape[::-1] if transpose else t.shape
                    out = np.empty((cfg.num_layers,) + shape, dtype)
                out[i] = t.T if transpose else t
            del t
            yield ("layers", leaf), out
            # Drop our binding before the next leaf's np.empty: without this
            # the generator pins the PREVIOUS stacked leaf through the
            # allocation and host staging peaks at two leaves (~8.6 GB at
            # 9B), not one.  The ExitStack closes every shard mapping when
            # the generator finishes or is abandoned.
            out = None


def from_safetensors_dir_streamed(
    path: str,
    cfg: Gemma2Config,
    *,
    mesh: Optional[Any] = None,
    place: Optional[Callable[[tuple, np.ndarray], Any]] = None,
) -> Params:
    """Bounded-peak-RSS snapshot loader (the 9B-scale path).

    :func:`from_safetensors_dir` materializes the whole state dict on host
    and then a second converted copy — ~2x the 18.5 GB checkpoint at 9B
    scale.  This variant streams one stacked leaf at a time
    (:func:`iter_stacked_leaves`) and PLACES it before reading the next:
    with ``mesh``, ``jax.device_put`` under ``parallel.mesh.param_specs``
    (Megatron-style tp sharding — the host stages ~one leaf while the
    shards land in device memory); with ``place``, whatever the caller
    wants (e.g. a host-pinned staging buffer).  Proven at full 9B shapes
    against a synthetic snapshot in tests/test_scale9b.py (no hub egress on
    this host — SURVEY.md §7 hard part #4).
    """
    if place is None:
        if mesh is not None:
            import jax
            from jax.sharding import NamedSharding

            from taboo_brittleness_tpu.parallel.mesh import param_specs

            specs = param_specs(cfg)

            def place(leaf_path, arr):
                spec = specs[leaf_path[0]] if len(leaf_path) == 1 \
                    else specs[leaf_path[0]][leaf_path[1]]
                return jax.device_put(arr, NamedSharding(mesh, spec))
        else:
            place = lambda _leaf_path, arr: jnp.asarray(arr)

    out: Dict[str, Any] = {"layers": {}}
    for leaf_path, arr in iter_stacked_leaves(path, cfg):
        placed = place(leaf_path, arr)
        del arr
        if len(leaf_path) == 1:
            out[leaf_path[0]] = placed
        else:
            out["layers"][leaf_path[1]] = placed
    return out


def infer_config_from_hf_config_json(path: str, **overrides) -> Gemma2Config:
    """Build a Gemma2Config from an HF snapshot's config.json."""
    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    cfg = Gemma2Config(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf["num_key_value_heads"],
        head_dim=hf.get("head_dim", hf["hidden_size"] // hf["num_attention_heads"]),
        intermediate_size=hf["intermediate_size"],
        sliding_window=hf.get("sliding_window", 4096),
        attn_logit_softcap=hf.get("attn_logit_softcapping", 50.0),
        final_logit_softcap=hf.get("final_logit_softcapping", 30.0),
        query_pre_attn_scalar=float(hf.get("query_pre_attn_scalar", 256)),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rms_norm_eps=float(hf.get("rms_norm_eps", 1e-6)),
    )
    return cfg.replace(**overrides) if overrides else cfg
