"""HBM live/peak watermarks + host RSS, sampled without touching the graph.

The 1.16 GB-per-prompt ``all_probs`` hazard (PAPER.md; the reason TBX002
exists) is invisible at run time unless someone watches HBM: a launch that
fits on word 3 can OOM on word 17 when a leaked buffer or an unexpectedly
retained prefill cache shifts the baseline.  This module makes the watermark
a recorded signal:

- :func:`sample` reads ``jax.local_devices()[i].memory_stats()`` (live bytes,
  peak bytes, limit — TPU backends publish these; CPU returns nothing) plus
  the host's RSS from ``/proc/self``, entirely host-side and fail-open.
  Span boundaries attach this (``trace.Tracer``), so every word/phase end
  carries the watermark it left behind.
- :class:`MemorySampler` is the optional LOW-RATE background thread for the
  gaps between boundaries (a leak inside one long phase), off by default and
  armed with ``TBX_OBS_MEM_HZ`` (samples/second, fractional fine).

``peak_bytes_in_use`` is cumulative per process on most backends; deltas
between consecutive samples, not absolute peaks, localize a regression.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def host_rss_bytes() -> Optional[int]:
    """Current resident set size from /proc/self/statm (Linux); None where
    procfs is unavailable (the sample just omits the field)."""
    try:
        with open("/proc/self/statm") as f:
            fields = f.read().split()
        return int(fields[1]) * _PAGE
    except (OSError, IndexError, ValueError):
        return None


# Local-device handles are stable for the life of the process; cache them so
# per-span samples don't re-enter jax's client bookkeeping every time.
_DEVICES: Optional[list] = None


def _local_devices() -> list:
    global _DEVICES
    if _DEVICES is None:
        import jax

        _DEVICES = list(jax.local_devices())
    return _DEVICES


def device_memory_stats() -> List[Dict[str, Any]]:
    """Per-local-device memory stats via jax introspection; [] when jax is
    absent, uninitialized, or the backend publishes nothing (CPU)."""
    try:
        out = []
        for d in _local_devices():
            try:
                stats = d.memory_stats()
            except Exception:  # noqa: BLE001 — per-device introspection varies
                stats = None
            if not stats:
                continue
            out.append({
                "device": str(d.id),
                "bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
            })
        return out
    except Exception:  # noqa: BLE001 — no jax / no backend: host-only sample
        return []


def live_array_bytes() -> Optional[int]:
    """Committed device bytes from jax's live-array registry: the CPU
    fallback for backends that publish no ``memory_stats()`` (the serve
    autotuner's measured-watermark input must exist on the forced-host-
    device CI mesh too).  Sums ACTUAL addressable shard bytes, so a
    replicated array counts once per device and a sharded one counts its
    slices — the same accounting ``bytes_in_use`` gives on TPU.  None when
    jax is absent/uninitialized."""
    try:
        import jax

        total = 0
        for a in jax.live_arrays():
            try:
                total += sum(s.data.nbytes for s in a.addressable_shards)
            except Exception:  # noqa: BLE001 — donated/deleted mid-iteration
                continue
        return total
    except Exception:  # noqa: BLE001
        return None


def _publish_gauges(rss: Optional[int],
                    devices: List[Dict[str, Any]]) -> None:
    """Mirror the watermarks into the metrics registry (``mem.hbm.*``, host
    RSS) so they ride the timeseries spool (``obs.timeseries``) — the live
    input the ROADMAP's batch-width autotune and the HBM-headroom SLO
    (``obs.slo``) consume.  Fail-open; totals across local devices.  When no
    device publishes stats (CPU), ``mem.hbm.live_bytes`` still publishes
    from :func:`live_array_bytes` so watermark consumers degrade to an
    approximation instead of silence."""
    try:
        from taboo_brittleness_tpu.obs import metrics

        if rss is not None:
            metrics.gauge("mem.host.rss_bytes").set(rss)
        if not devices:
            live = live_array_bytes()
            if live:
                metrics.gauge("mem.hbm.live_bytes").set(live)
        if devices:
            live = sum(d["bytes_in_use"] or 0 for d in devices)
            peak = sum(d["peak_bytes_in_use"] or 0 for d in devices)
            limit = sum(d["bytes_limit"] or 0 for d in devices)
            metrics.gauge("mem.hbm.live_bytes").set(live)
            if peak:
                metrics.gauge("mem.hbm.peak_bytes").set(peak)
            if limit:
                metrics.gauge("mem.hbm.limit_bytes").set(limit)
                metrics.gauge("mem.hbm.headroom_frac").set(
                    round(max(0.0, 1.0 - live / limit), 4))
    except Exception:  # noqa: BLE001 — publication is best-effort
        pass


def sample(*, compact: bool = False) -> Dict[str, Any]:
    """One watermark sample.  ``compact=True`` is the span-boundary form:
    megabytes, short keys, device list collapsed to totals — small enough to
    ride on every word/phase end event.  Every sample also refreshes the
    ``mem.*`` registry gauges (:func:`_publish_gauges`)."""
    rss = host_rss_bytes()
    devices = device_memory_stats()
    _publish_gauges(rss, devices)
    if not compact:
        out: Dict[str, Any] = {"rss_bytes": rss, "devices": devices}
        return out
    out = {}
    if rss is not None:
        out["rss_mb"] = round(rss / 1e6, 1)
    if devices:
        live = sum(d["bytes_in_use"] or 0 for d in devices)
        peak = sum(d["peak_bytes_in_use"] or 0 for d in devices)
        out["hbm_live_mb"] = round(live / 1e6, 1)
        if peak:
            out["hbm_peak_mb"] = round(peak / 1e6, 1)
    return out


def sampler_hz() -> float:
    """Background-sampler rate from ``TBX_OBS_MEM_HZ``; 0 (default) = off."""
    try:
        return max(0.0, float(os.environ.get("TBX_OBS_MEM_HZ", "0")))
    except ValueError:
        return 0.0


class MemorySampler:
    """Optional background watermark sampler: emits ``mem.sample`` point
    events through ``tracer`` at ``hz`` samples/second until stopped.
    Daemonized and fail-open; ``hz<=0`` never starts a thread."""

    def __init__(self, tracer, hz: Optional[float] = None):
        self.tracer = tracer
        self.hz = sampler_hz() if hz is None else hz
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MemorySampler":
        if self.hz <= 0 or self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name="tbx-obs-mem", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        interval = 1.0 / self.hz
        while not self._stop.wait(interval):
            try:
                self.tracer.event("mem.sample", **sample(compact=True))
            except Exception:  # noqa: BLE001 — sampling must never crash a run
                pass

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def __enter__(self) -> "MemorySampler":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()
