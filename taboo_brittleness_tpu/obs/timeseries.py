"""Windowed metrics spool: the registry, snapshotted every N seconds.

The metrics registry (``obs.metrics``) is cumulative-since-process-start —
right for the manifest's exit snapshot, wrong for "is the run healthy NOW":
a mid-run SLO regression is arithmetically masked by old samples, and a
multi-hour fleet run is blind between heartbeats.  The recorder closes that
gap: a daemon thread rolls the registry into fixed-width windows (default
10 s, ``TBX_OBS_TS_S``) and appends each window as ONE JSON line to
``<output_dir>/_metrics.jsonl``:

- **Counters** carry ``{"total", "delta"}`` — cumulative value plus the
  per-window increment, so both rates and conservation
  (``total_i == total_{i-1} + delta_i``, checked by ``trace_report
  --check``) fall out of the stream.
- **Gauges** carry their instantaneous value (the recorder refreshes the
  HBM/RSS watermark gauges via ``obs.memory`` just before snapshotting).
- **Histograms** carry REAL per-window p50/p99: every histogram keeps a
  window-forked reservoir (``Histogram.roll_window``) that resets each
  window, next to the cumulative one.
- An optional SLO engine (``obs.slo``) is evaluated at each roll from the
  same fork (raw reservoir samples never leave the process) and its burn
  block rides the window record.

At :meth:`~TimeseriesRecorder.stop` the recorder rolls one final window and
then writes an ``exit`` record FROM THE SAME SNAPSHOT, so "final window ≈
exit snapshot" conservation is exact by construction — the other invariant
``trace_report --check`` holds the stream to.

Write discipline mirrors ``obs.trace``: whole-line ``O_APPEND`` writes
(concurrent writers interleave lines, never bytes), seq resumed from the
file tail across incarnations, fail-open with drop counting
(``obs.metrics_dropped``) through the deliberate ``obs.metrics_write``
fault site, and per-worker suffixed files (``_metrics.<wid>.jsonl``) in
fleet mode, merged at fleet end like ``_events.jsonl``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional

from taboo_brittleness_tpu.obs import metrics as obs_metrics

#: Bumped whenever a window record gains/renames a REQUIRED key; readers
#: (tools/trace_report.py, obs.top) accept their own version and older.
SCHEMA_VERSION = 1

METRICS_FILENAME = "_metrics.jsonl"


def window_seconds() -> float:
    """Window width from ``TBX_OBS_TS_S`` (default 10 s, floor 0.2)."""
    try:
        return max(0.2, float(os.environ.get("TBX_OBS_TS_S", "10")))
    except ValueError:
        return 10.0


def metrics_filename(worker_id: Optional[str] = None) -> str:
    return (METRICS_FILENAME if worker_id is None
            else f"_metrics.{worker_id}.jsonl")


def _resume_seq(path: str) -> int:
    """Last ``seq`` in an existing spool's tail window, so a supervised
    relaunch appends a strictly-monotone stream (same contract as
    ``trace._resume_marks``; torn tail lines skipped)."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return 0
    if not size:
        return 0
    try:
        with open(path, "rb") as f:
            f.seek(max(0, size - 65536))
            tail = f.read().decode("utf-8", "replace")
    except OSError:
        return 0
    seq = 0
    for line in tail.splitlines():
        try:
            rec = json.loads(line)
            seq = max(seq, int(rec.get("seq", 0) or 0))
        except (ValueError, TypeError, AttributeError):
            continue
    return seq


class TimeseriesRecorder:
    """One process's windowed spool: a daemon thread calling :meth:`roll`
    every ``window_s``.  All IO is fail-open; ``clock`` is injectable so
    tests roll windows deterministically instead of sleeping."""

    def __init__(self, path: str, *,
                 registry: Optional[obs_metrics.MetricsRegistry] = None,
                 window_s: Optional[float] = None,
                 slo_engine=None,
                 on_window: Optional[Callable[[Dict[str, Any]], None]] = None,
                 sample_memory: bool = True,
                 clock=time.monotonic):
        self.path = path
        self.registry = registry or obs_metrics.registry()
        self.window_s = window_seconds() if window_s is None else window_s
        self.slo_engine = slo_engine
        #: Called (fail-open) with each written window record — the serve
        #: loop uses it to lift the ``slo`` block into the heartbeat.
        self.on_window = on_window
        self.sample_memory = sample_memory
        self._clock = clock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t_open = clock()
        self._w_start = self._t_open
        self._prev_counters: Dict[str, float] = {}
        self._last_window: Optional[Dict[str, Any]] = None
        self.windows = 0
        self.dropped = 0
        self._seq = 0
        self._fd: Optional[int] = None
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._seq = _resume_seq(path)
            self._fd = os.open(
                path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        except OSError:
            self._fd = None      # fail-open: windows still roll, writes drop

    # -- snapshot / roll ---------------------------------------------------

    def _collect(self) -> Dict[str, Any]:
        """One registry sweep: counter totals+deltas, gauge values, and the
        per-histogram window fork (with raw samples, in-memory only)."""
        if self.sample_memory:
            # Refresh the HBM/RSS watermark gauges so idle windows still
            # carry a live memory signal (serve mode has no span boundaries).
            try:
                from taboo_brittleness_tpu.obs import memory

                memory.sample(compact=True)
            except Exception:  # noqa: BLE001 — sampling is best-effort
                pass
        counters: Dict[str, Dict[str, float]] = {}
        gauges: Dict[str, float] = {}
        hists: Dict[str, Dict[str, Any]] = {}
        for name, inst in sorted(self.registry.instruments().items()):
            if isinstance(inst, obs_metrics.Counter):
                total = inst.value
                counters[name] = {
                    "total": total,
                    "delta": total - self._prev_counters.get(name, 0.0)}
                self._prev_counters[name] = total
            elif isinstance(inst, obs_metrics.Gauge):
                if inst.value is not None:
                    gauges[name] = inst.value
            elif isinstance(inst, obs_metrics.Histogram):
                if inst.count:
                    win = inst.roll_window()
                    win["cum_n"] = inst.count
                    hists[name] = win
        return {"counters": counters, "gauges": gauges, "hists": hists}

    def roll(self) -> Optional[Dict[str, Any]]:
        """Close the current window: snapshot the registry, evaluate SLOs,
        append one ``window`` record.  Returns the record (None if the
        recorder raced its own stop)."""
        with self._lock:
            now = self._clock()
            t0, self._w_start = self._w_start, now
            snap = self._collect()
            dur = max(1e-9, now - t0)
            slo_block = None
            if self.slo_engine is not None:
                try:
                    slo_block = self.slo_engine.observe_window(
                        dur=dur, hists=snap["hists"],
                        counter_deltas={n: c["delta"]
                                        for n, c in snap["counters"].items()},
                        gauges=snap["gauges"])
                except Exception:  # noqa: BLE001 — SLO eval must be fail-open
                    slo_block = None
            self._seq += 1
            rec: Dict[str, Any] = {
                "v": SCHEMA_VERSION,
                "kind": "window",
                "seq": self._seq,
                "pid": os.getpid(),
                # Epoch anchor so merged multi-host streams stay orderable.
                # tbx: wallclock-ok — cross-process ordering anchor
                "wall": time.time(),
                "t0": round(t0 - self._t_open, 6),
                "t1": round(now - self._t_open, 6),
                "window_s": self.window_s,
                "counters": snap["counters"],
                "gauges": snap["gauges"],
                "histograms": {
                    name: {
                        "n": win["n"],
                        "sum": round(win["sum"], 6),
                        "max": win["max"],
                        "p50": obs_metrics.quantile_of(win["samples"], 0.50),
                        "p99": obs_metrics.quantile_of(win["samples"], 0.99),
                        "cum_n": win["cum_n"],
                    }
                    for name, win in snap["hists"].items()},
            }
            if slo_block:
                rec["slo"] = slo_block
            self._write(rec)
            self.windows += 1
            self._last_window = rec
        if self.on_window is not None:
            try:
                self.on_window(rec)
            except Exception:  # noqa: BLE001 — a heartbeat hook must not kill
                pass
        return rec

    def _write(self, rec: Dict[str, Any]) -> None:
        """One whole-line O_APPEND write, fail-open through the deliberate
        ``obs.metrics_write`` fault site: an injected (or real) sink fault
        drops the window — counted, never fatal."""
        if self._fd is None:
            self.dropped += 1
            return
        try:
            from taboo_brittleness_tpu.runtime import resilience

            resilience.fire("obs.metrics_write", path=self.path,
                            seq=rec.get("seq"), kind=rec.get("kind"))
            line = (json.dumps(rec, default=str) + "\n").encode("utf-8")
            os.write(self._fd, line)
        except Exception:  # noqa: BLE001 — telemetry must never kill a run
            self.dropped += 1
            try:
                obs_metrics.counter("obs.metrics_dropped").inc()
            except Exception:  # noqa: BLE001
                pass

    def last_window(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._last_window) if self._last_window else None

    def last_slo(self) -> Optional[Dict[str, Any]]:
        win = self.last_window()
        return win.get("slo") if win else None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "TimeseriesRecorder":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="tbx-obs-timeseries", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.window_s):
            try:
                self.roll()
            except Exception:  # noqa: BLE001 — the spool must never crash
                pass

    def stop(self) -> None:
        """Final roll + exit record + close.  The exit record's totals come
        from the final window's own snapshot, so the conservation invariant
        (exit ≡ last window cumulative) is exact, not approximate."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        try:
            final = self.roll()
        except Exception:  # noqa: BLE001
            final = None
        with self._lock:
            if final is not None:
                self._seq += 1
                self._write({
                    "v": SCHEMA_VERSION,
                    "kind": "exit",
                    "seq": self._seq,
                    "pid": os.getpid(),
                    # tbx: wallclock-ok — cross-process ordering anchor
                    "wall": time.time(),
                    "t": final["t1"],
                    "counters": {n: c["total"]
                                 for n, c in final["counters"].items()},
                    "gauges": final["gauges"],
                    "histograms": {
                        n: {"cum_n": h["cum_n"]}
                        for n, h in final["histograms"].items()},
                })
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None

    def __enter__(self) -> "TimeseriesRecorder":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()


def iter_windows(path: str, *,
                 strict: bool = False) -> Iterator[Dict[str, Any]]:
    """Yield records from a ``_metrics.jsonl`` spool, skipping torn lines
    (a killed incarnation's partial final write is expected, not an error).
    ``strict=True`` raises on the first bad line (trace_report --check)."""
    from taboo_brittleness_tpu.obs import trace

    yield from trace.iter_events(path, strict=strict)


__all__ = [
    "METRICS_FILENAME", "SCHEMA_VERSION", "TimeseriesRecorder",
    "iter_windows", "metrics_filename", "window_seconds",
]
