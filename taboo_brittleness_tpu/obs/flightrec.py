"""Crash flight recorder: a bounded in-memory ring of recent records.

A quarantine, a wedge-kill, or a SIGTERM drain used to leave a postmortem
that starts from nothing: the event stream shows spans, but "what were the
last N steps/requests/leases immediately before it died" had to be
reconstructed by hand.  The flight recorder is that answer, kept cheap
enough to always be on:

- Hot paths call :func:`record` (a dict build + a ``deque`` append — no
  lock, no IO).  The ring holds the most recent ``TBX_FLIGHTREC_N``
  records (default 256; 0 disables recording entirely).
- Crash paths call :func:`dump`, which atomically writes the ring to
  ``<output_dir>/_flightrec.json`` (worker-suffixed in fleet mode, like
  every other per-worker artifact).  Triggers wired in this repo:
  the retry→quarantine path (``resilience.run_guarded``), a serve session
  quarantine (``serve.scheduler``), and the SIGTERM drain latch
  (``runtime.supervise.DrainController``) — which is also how a supervise
  wedge-kill captures the ring, since the supervisor always sends SIGTERM
  before escalating to SIGKILL.

Signal-safety, deliberately: the ring is a ``collections.deque`` appended
WITHOUT a lock (GIL-atomic), and :func:`dump` snapshots it with ``list()``
— so the SIGTERM handler may dump while the main thread is mid-append
without self-deadlocking (the reason ``DrainController._handle`` must not
touch the tracer applies here in reverse: no shared locks at all).

Everything is fail-open and stdlib-only; a dump failure is counted
(``obs.flightrec_drops``) and swallowed.
"""

from __future__ import annotations

import collections
import os
import time
from typing import Any, Deque, Dict, Optional

SCHEMA_VERSION = 1

FLIGHTREC_FILENAME = "_flightrec.json"

_DEFAULT_CAPACITY = 256


def ring_capacity() -> int:
    """Ring size from ``TBX_FLIGHTREC_N`` (default 256; 0 disables)."""
    try:
        return max(0, int(os.environ.get("TBX_FLIGHTREC_N",
                                         str(_DEFAULT_CAPACITY))))
    except ValueError:
        return _DEFAULT_CAPACITY


def flightrec_filename(worker_id: Optional[str] = None) -> str:
    return (FLIGHTREC_FILENAME if worker_id is None
            else f"_flightrec.{worker_id}.json")


class FlightRecorder:
    """One process's ring + dump target.  ``capacity=0`` makes every method
    a no-op, so call sites never branch on whether recording is armed."""

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = ring_capacity() if capacity is None else capacity
        self._ring: Deque[Dict[str, Any]] = collections.deque(
            maxlen=max(1, self.capacity))
        self._path: Optional[str] = None
        self._t0 = time.monotonic()
        self.dumps = 0
        self.dropped = 0

    def configure(self, output_dir: Optional[str],
                  worker_id: Optional[str] = None) -> None:
        """Point dumps at ``<output_dir>/_flightrec[.wid].json``.  Until
        configured (or after ``configure(None)``), dumps are no-ops — the
        ring still records, so a late configure loses nothing."""
        if output_dir is None:
            self._path = None
            return
        self._path = os.path.join(output_dir, flightrec_filename(worker_id))

    @property
    def path(self) -> Optional[str]:
        return self._path

    def record(self, kind: str, **attrs: Any) -> None:
        """Append one record.  Deliberately lock-free (deque appends are
        GIL-atomic) so the signal-handler dump can never deadlock against a
        hot-path append."""
        if self.capacity <= 0:
            return
        rec = {"t": round(time.monotonic() - self._t0, 6), "kind": kind}
        if attrs:
            rec.update(attrs)
        self._ring.append(rec)

    def snapshot(self) -> list:
        return list(self._ring)

    def dump(self, reason: str, **extra: Any) -> Optional[str]:
        """Atomically write the ring (tmp+rename) to the configured path.
        Safe from signal handlers: no locks, fail-open, one tmp file keyed
        by pid.  Returns the path written, or None (unconfigured/failed)."""
        path = self._path
        if path is None or self.capacity <= 0:
            return None
        payload = {
            "v": SCHEMA_VERSION,
            "reason": reason,
            "pid": os.getpid(),
            # tbx: wallclock-ok — postmortem anchor, not duration math
            "dumped_at": time.time(),
            "capacity": self.capacity,
            "ring": self.snapshot(),
        }
        if extra:
            payload["context"] = extra
        try:
            # Burn → trace exemplars: snapshot the worst trace ids per
            # latency series so a postmortem dump links back to the exact
            # requests that were hurting when the dump fired.
            from taboo_brittleness_tpu.obs import reqtrace

            exemplars = reqtrace.peek_exemplars()
            if exemplars:
                payload["exemplars"] = exemplars
        except Exception:  # noqa: BLE001 — fail-open
            pass
        try:
            import json

            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, path)
            self.dumps += 1
            return path
        except Exception:  # noqa: BLE001 — a postmortem write must not crash
            self.dropped += 1
            try:
                from taboo_brittleness_tpu.obs import metrics

                metrics.counter("obs.flightrec_drops").inc()
            except Exception:  # noqa: BLE001
                pass
            return None

    def clear(self) -> None:
        self._ring.clear()


# Process-wide recorder (the one every hot path feeds).
_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    return _RECORDER


def record(kind: str, **attrs: Any) -> None:
    _RECORDER.record(kind, **attrs)


def configure(output_dir: Optional[str],
              worker_id: Optional[str] = None) -> None:
    _RECORDER.configure(output_dir, worker_id)


def dump(reason: str, **extra: Any) -> Optional[str]:
    return _RECORDER.dump(reason, **extra)


def reset(capacity: Optional[int] = None) -> None:
    """Swap in a fresh recorder (tests; bench A/B arms)."""
    global _RECORDER
    _RECORDER = FlightRecorder(capacity)


__all__ = [
    "FLIGHTREC_FILENAME", "SCHEMA_VERSION", "FlightRecorder", "configure",
    "dump", "flightrec_filename", "record", "recorder", "reset",
    "ring_capacity",
]
