"""Phase-scoped device-timeline profiling: XLA traces joined to host spans.

Everything the span stream (obs.trace) records is HOST wall time: a program
span covers tracing + dispatch (and sometimes a blocking pull), and
``tools/trace_report.py`` *infers* dispatch gaps as "word time covered by no
phase span".  Host clocks cannot distinguish device-idle from
device-busy-on-the-wrong-thing — which is exactly the evidence the ROADMAP's
fused-loop item (Kernel Looping, arXiv:2410.23668) is gated on.  This module
is the device half of the telemetry story:

1. **Capture** (:class:`SweepCapture` / :class:`DeviceCapture`) — opt-in via
   ``TBX_PROFILE=1`` (or the CLI ``--profile`` flag), the sweep observer
   wraps the first ``TBX_PROFILE_WORDS`` (default 2) computed words of a run
   in ONE ``jax.profiler`` capture window, written under
   ``<output_dir>/_profile/``.  Bounding the window keeps the trace small; a
   couple of steady-state words is what attribution needs.
2. **Annotation** (:func:`annotate`) — every registered program launch
   (decode / readout / nll / serve.step / the aot warm-start executions /
   the direct lens+forcing dispatches) wraps itself in a
   ``jax.profiler.TraceAnnotation`` named ``tbx:<program>#<span_id>@<fn>``,
   so device slices are attributable to the exact host span that launched
   them.  When no capture is active the wrapper is a shared null context —
   nanoseconds, so the obs-overhead budget (<2% with profiling off) holds.
3. **Parse** (:func:`parse_trace_file` / :func:`build_profile`) — a
   stdlib-only reader for the emitted Perfetto ``*.trace.json.gz`` that
   pools XLA op slices (events carrying ``args.hlo_op`` / device-lane
   events) per annotation and writes ``<output_dir>/_device_profile.json``:
   per-program and per-phase device-busy seconds, device-idle (dispatch-gap)
   share measured from the device timeline itself, top-N ops by device time,
   and HBM-traffic-proportional op classes (matmul / fusion / copy / ...).

Joining device slices to annotations is a three-pass per-HLO-module match
(annotations carry the jit fn name; executions of ``jit_<fn>`` are grouped
by time gaps):

- **window** — a group whose midpoint falls inside exactly one candidate
  annotation window (host blocked inside the annotation; slice overlap is
  clipped to the window, so joined device time can never exceed the span);
- **fifo** — remaining groups zip against remaining candidate annotations in
  dispatch order when the counts agree (the device executes programs FIFO,
  so async dispatches that outlive their window still attribute exactly);
- **order** — otherwise, the latest candidate annotation that started before
  the group (a best-effort fallback, labeled as such in the artifact).

``tools/trace_report.py --device`` renders the artifact against
``_events.jsonl`` and the ``perf/roofline.py`` ceilings: per-phase *measured*
device occupancy vs ceiling, dispatch-gap share from device idle, and a
host-vs-device disagreement column flagging spans that mislead.

This module also hosts the profiler drivers behind the ``tbx profile`` CLI
(:func:`run_launch_profile` — one phase launch under capture, the round-4
"what does the while-loop body spend time on" flow — and
:func:`run_study_host_profile` + :class:`StageTimers`, the host wall-clock
breakdown that used to live in ``tools/profile_study_host.py``).

Contract, as for the rest of obs/: host-side only, fail-open end to end
(capture/parse errors never take down a run), stdlib + lazily-imported jax.
"""

from __future__ import annotations

import bisect
import glob
import gzip
import json
import os
import re
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: Bumped whenever ``_device_profile.json`` gains/renames a REQUIRED key;
#: readers (tools/trace_report.py --device) accept their own version and older.
SCHEMA_VERSION = 1

DEVICE_PROFILE_FILENAME = "_device_profile.json"
PROFILE_DIRNAME = "_profile"

#: Annotation wire format:
#: ``tbx:<program>#<span_id>[@<fn_name>][!<phase>=<w>[+<phase>=<w>...]]``.
#: The optional ``!`` suffix is the FUSED launch's phase table (runtime/
#: fused.py): ordered sub-phases with analytic device-cost weights at the
#: launch shapes — in-graph program structure riding the launch record, so
#: a single launch carrying multiple phase markers splits its measured
#: device seconds per phase without any host timestamp.
_ANNOT_PREFIX = "tbx:"
_ANNOT_RE = re.compile(
    r"^tbx:(?P<program>[^#]+)#(?P<span>\d+)"
    r"(?:@(?P<fn>[^!]+))?(?:!(?P<phases>.+))?$")

#: Gap (microseconds) that splits two slices of the same HLO module into
#: separate execution groups.  Intra-program thunk gaps are microseconds;
#: separate launches of the same program are separated by at least a host
#: round-trip.
_GROUP_GAP_US = 5000.0

#: Cap on per-launch records in the artifact (a profiled serving run steps
#: thousands of times; phases still aggregate everything).
_MAX_PROGRAM_RECORDS = 400


def enabled() -> bool:
    """Opt-in master switch: ``TBX_PROFILE=1`` (or the CLI ``--profile``
    flag, which sets it) arms the sweep observer's device capture."""
    return os.environ.get("TBX_PROFILE", "0") == "1"


def capture_words() -> int:
    """How many computed words one capture window covers (``TBX_PROFILE_WORDS``,
    default 2 — the steady-state pair attribution needs; bounding the window
    keeps trace size sane on a 20-word sweep)."""
    try:
        return max(1, int(os.environ.get("TBX_PROFILE_WORDS", "2")))
    except ValueError:
        return 2


# ---------------------------------------------------------------------------
# Annotation.
# ---------------------------------------------------------------------------

#: True while a capture started by THIS module is live.  ``annotate`` keys
#: off it so the per-dispatch cost with profiling off is one attribute read.
_ACTIVE = False


class _NullCtx:
    """Shared no-op context for the not-capturing fast path."""

    def __enter__(self) -> "_NullCtx":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


_NULL_CTX = _NullCtx()


def annotation_name(program: str, span_id: Optional[int],
                    fn_name: Optional[str],
                    phases: Optional[Dict[str, float]] = None) -> str:
    name = f"{_ANNOT_PREFIX}{program}#{int(span_id or 0)}"
    if fn_name:
        name += f"@{fn_name}"
    if phases:
        name += "!" + "+".join(f"{p}={w:g}" for p, w in phases.items())
    return name


def parse_phase_table(text: Optional[str]) -> Optional[Dict[str, float]]:
    """``decode=0.62+readout=0.21+nll=0.17`` → ordered {phase: weight};
    None for absent/unparseable (a malformed table degrades to a plain
    single-phase annotation, never an error)."""
    if not text:
        return None
    table: Dict[str, float] = {}
    for part in text.split("+"):
        name, sep, w = part.partition("=")
        if not sep or not name:
            return None
        try:
            table[name] = float(w)
        except ValueError:
            return None
    return table or None


def capturing() -> bool:
    """True while a capture started by this module is live — call sites use
    it to skip work (e.g. the fused launch's phase-table arithmetic) that
    only exists for the trace parser."""
    return _ACTIVE


def annotate(program: str, *, fn: Any = None,
             span_id: Optional[int] = None,
             phases: Optional[Dict[str, float]] = None):
    """Context manager marking one program launch on the profiler timeline.

    ``fn`` (the jitted callable, or its name as a string) rides along so the
    parser can match device slices by HLO module name (``jit_<fn>``) even
    when an async dispatch's execution outlives the annotation window.
    ``span_id`` defaults to the innermost active obs span — the id the
    artifact is later joined back to ``_events.jsonl`` with.

    ``phases`` attaches a fused launch's phase table (ordered sub-phase →
    analytic weight, ``runtime.fused.phase_table``): the parser splits the
    launch's measured device seconds across the listed phases instead of
    treating the launch as one opaque program.

    A shared null context when no capture is active: call sites wrap every
    dispatch unconditionally and pay ~nothing in the common case.
    """
    if not _ACTIVE:
        return _NULL_CTX
    try:
        import jax

        if span_id is None:
            from taboo_brittleness_tpu.obs import trace as trace_mod

            t = trace_mod.get_tracer()
            cur = t.current_span() if t is not None else None
            span_id = getattr(cur, "span_id", None)
        fn_name = fn if isinstance(fn, str) else (
            getattr(fn, "__name__", None) if fn is not None else None)
        return jax.profiler.TraceAnnotation(
            annotation_name(program, span_id, fn_name, phases=phases))
    except Exception:  # noqa: BLE001 — profiling must never poison a dispatch
        return _NULL_CTX


# ---------------------------------------------------------------------------
# Capture.
# ---------------------------------------------------------------------------

class DeviceCapture:
    """One ``jax.profiler`` capture window → parsed profile dict.

    Fail-open: ``start`` returns False (and the capture stays inert) when
    profiling cannot start — another capture live in the process, a backend
    without profiler support, a read-only trace dir."""

    def __init__(self, trace_dir: str, *, meta: Optional[Dict[str, Any]] = None):
        self.trace_dir = trace_dir
        self.meta = dict(meta or {})
        self.active = False
        self._t0: Optional[float] = None
        self._session: Any = None       # ProfilerSession when options worked

    def start(self) -> bool:
        global _ACTIVE
        if self.active or _ACTIVE:
            return False
        try:
            import jax

            os.makedirs(self.trace_dir, exist_ok=True)
            jax.devices()               # backends must exist before a session
            try:
                # Preferred: a ProfilerSession with the python tracer OFF.
                # jax.profiler.start_trace hardcodes python_tracer_level=1,
                # and the resulting ~1M python-frame events overflow the
                # trace converter's event cap on even a two-word sweep —
                # crowding out the XLA op slices this capture exists for.
                from jax._src.lib import xla_client

                opts = xla_client.profiler.ProfileOptions()
                opts.python_tracer_level = 0
                opts.host_tracer_level = 2
                self._session = xla_client.profiler.ProfilerSession(opts)
            except Exception:  # noqa: BLE001 — fall back to the public API
                self._session = None
                jax.profiler.start_trace(self.trace_dir)
        except Exception:  # noqa: BLE001 — profiling is best-effort
            return False
        self.active = True
        self._t0 = time.monotonic()
        _ACTIVE = True
        return True

    def stop(self) -> Optional[Dict[str, Any]]:
        """Stop the window, parse the newest emitted trace file, and return
        the profile dict (None on any failure)."""
        global _ACTIVE
        if not self.active:
            return None
        self.active = False
        _ACTIVE = False
        wall = (time.monotonic() - self._t0) if self._t0 is not None else None
        try:
            if self._session is not None:
                session, self._session = self._session, None
                session.export(session.stop(), self.trace_dir)
            else:
                import jax

                jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001
            return None
        try:
            path = find_trace_file(self.trace_dir)
            if path is None:
                return None
            meta = dict(self.meta)
            if wall is not None:
                meta["capture_wall_seconds"] = round(wall, 3)
            try:
                import jax

                meta.setdefault("backend", jax.default_backend())
                meta.setdefault("device_kind", jax.devices()[0].device_kind)
            except Exception:  # noqa: BLE001
                pass
            annotations, slices = parse_trace_file(path)
            profile = build_profile(annotations, slices, meta=meta,
                                    trace_file=path)
            return profile
        except Exception:  # noqa: BLE001 — a bad trace must not kill the run
            return None


class SweepCapture:
    """The sweep observer's bounded capture: starts with the run, stops after
    ``TBX_PROFILE_WORDS`` computed words (or at observer close), writes
    ``<output_dir>/_device_profile.json``."""

    def __init__(self, output_dir: str, *, tracer: Any = None,
                 words_limit: Optional[int] = None):
        self.output_dir = output_dir
        self.tracer = tracer
        self.limit = words_limit if words_limit is not None else capture_words()
        self._capture = DeviceCapture(
            os.path.join(output_dir, PROFILE_DIRNAME))
        self._words_done = 0
        self.profile: Optional[Dict[str, Any]] = None
        self.artifact_path: Optional[str] = None

    def start(self) -> bool:
        return self._capture.start()

    def word_done(self) -> None:
        """One computed (non-resumed) word finished; stop once the budget is
        spent so the trailing 18 words of a real sweep cost nothing."""
        if not self._capture.active:
            return
        self._words_done += 1
        if self._words_done >= self.limit:
            self.finish()

    def finish(self) -> None:
        if not self._capture.active:
            return
        profile = self._capture.stop()
        if profile is None:
            return
        profile.setdefault("capture", {})["words"] = self._words_done
        self.profile = profile
        path = os.path.join(self.output_dir, DEVICE_PROFILE_FILENAME)
        try:
            from taboo_brittleness_tpu.runtime.resilience import (
                atomic_json_dump)

            atomic_json_dump(profile, path)
            self.artifact_path = path
        except Exception:  # noqa: BLE001 — fail-open
            return
        if self.tracer is not None:
            try:
                self.tracer.event(
                    "profile.captured", words=self._words_done,
                    file=DEVICE_PROFILE_FILENAME,
                    programs=len(profile.get("programs", [])),
                    device_busy_seconds=profile.get("device", {}).get(
                        "busy_union_seconds"))
            except Exception:  # noqa: BLE001
                pass


# ---------------------------------------------------------------------------
# Trace parsing (stdlib-only; also used by tools/trace_report.py --device).
# ---------------------------------------------------------------------------

def find_trace_file(trace_dir: str) -> Optional[str]:
    """Newest Perfetto ``*.trace.json.gz`` under a profiler log dir."""
    files = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                  recursive=True),
        key=lambda p: os.path.getmtime(p))
    return files[-1] if files else None


def parse_trace_file(path: str) -> Tuple[List[Dict[str, Any]],
                                         List[Dict[str, Any]]]:
    """(annotations, device slices) from one Perfetto trace.

    - An *annotation* is a complete event whose name parses as
      ``tbx:<program>#<span>[@<fn>]`` (emitted by :func:`annotate`).
    - A *device slice* is a complete event carrying ``args.hlo_op`` /
      ``args.hlo_module`` (the XLA executor's per-op execution events — on
      the CPU backend these live on ``tf_XLATfrtCpuClient`` threads), or any
      complete event on a ``/device:``-named process lane (TPU/GPU device
      streams).  Times are microseconds as emitted.
    """
    with gzip.open(path, "rt", encoding="utf-8", errors="replace") as f:
        tr = json.load(f)
    events = tr.get("traceEvents") or []
    device_pids = set()
    for ev in events:
        if (ev.get("ph") == "M" and ev.get("name") == "process_name"
                and "/device:" in str((ev.get("args") or {}).get("name", ""))):
            device_pids.add(ev.get("pid"))
    annotations: List[Dict[str, Any]] = []
    slices: List[Dict[str, Any]] = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = str(ev.get("name", ""))
        ts = ev.get("ts")
        if ts is None:
            continue
        dur = float(ev.get("dur", 0.0) or 0.0)
        if name.startswith(_ANNOT_PREFIX):
            m = _ANNOT_RE.match(name)
            if m:
                ann = {
                    "program": m.group("program"),
                    "span_id": int(m.group("span")),
                    "fn": m.group("fn"),
                    "t0": float(ts), "t1": float(ts) + dur,
                }
                table = parse_phase_table(m.group("phases"))
                if table:
                    ann["phases"] = table
                annotations.append(ann)
            continue
        args = ev.get("args") or {}
        on_device_lane = ev.get("pid") in device_pids
        if "hlo_op" in args or "hlo_module" in args or on_device_lane:
            slices.append({
                "name": name,
                "module": args.get("hlo_module"),
                "t0": float(ts), "dur": dur,
                "tid": ev.get("tid"),
            })
    annotations.sort(key=lambda a: a["t0"])
    slices.sort(key=lambda s: s["t0"])
    return annotations, slices


#: HBM-traffic-proportional op classes, coarsest-that-still-ranks: matmuls
#: stream weights, copies/transposes are pure HBM traffic (the retiling-copy
#: class the readout A/B chased), fusions blend both.
_OP_CLASS_PATTERNS = (
    # Order matters: collectives/transfers first (an "all-gather" must not
    # read as a copy, nor an "all-reduce" as a reduce).
    ("collective", re.compile(r"all-reduce|all-gather|all-to-all|"
                              r"collective|psum|permute", re.I)),
    ("host-transfer", re.compile(r"infeed|outfeed|transfer|copy-start|"
                                 r"copy-done", re.I)),
    ("matmul", re.compile(r"dot|conv|gemm|einsum", re.I)),
    ("copy", re.compile(
        r"copy|transpose|reshape|bitcast|concatenate|dynamic-slice|"
        r"dynamic_slice|dynamic-update|dynamic_update|slice|pad|gather|scatter",
        re.I)),
    ("fusion", re.compile(r"fusion", re.I)),
    ("reduce", re.compile(r"reduce|sort|top-k|topk|cumsum|argmax|argmin", re.I)),
)


def classify_op(name: str) -> str:
    for cls, pat in _OP_CLASS_PATTERNS:
        if pat.search(name):
            return cls
    return "other"


def _base_op_name(name: str) -> str:
    """``dot.4`` → ``dot`` — the per-instruction suffix only splits totals."""
    return re.sub(r"\.\d+$", "", name)


def _union_seconds(intervals: List[Tuple[float, float]]) -> float:
    """Total covered microseconds of a set of [t0, t1) intervals → seconds."""
    if not intervals:
        return 0.0
    intervals = sorted(intervals)
    total = 0.0
    cur0, cur1 = intervals[0]
    for t0, t1 in intervals[1:]:
        if t0 > cur1:
            total += cur1 - cur0
            cur0, cur1 = t0, t1
        else:
            cur1 = max(cur1, t1)
    total += cur1 - cur0
    return total / 1e6


def _group_slices(slices: List[Dict[str, Any]],
                  annotations: Sequence[Dict[str, Any]] = ()) -> Dict[
                      Optional[str], List[Dict[str, Any]]]:
    """Per-HLO-module execution groups — one group ≈ one launch's execution.

    A group is a maximal run of same-module slices on one executor thread:
    the run breaks when a slice of a DIFFERENT module lands in between (the
    queue moved on to the next program), when the intra-module gap exceeds
    ``_GROUP_GAP_US``, or when a new fn-matched ANNOTATION started inside
    the gap (two back-to-back launches of the same program with almost no
    host time between them — e.g. consecutive tiny-model words — are two
    dispatches, so they must be two groups for the FIFO match to hold).
    Runs are per-thread because the executor interleaves programs, not
    threads, within one launch."""
    ann_starts: Dict[Optional[str], List[float]] = {}
    if annotations:
        modules = {s["module"] for s in slices}
        for module in modules:
            starts = sorted(a["t0"] for a in annotations
                            if _module_matches(module, a.get("fn")))
            if starts:
                ann_starts[module] = starts

    def dispatch_between(module: Optional[str], t0: float, t1: float) -> bool:
        starts = ann_starts.get(module)
        if not starts:
            return False
        i = bisect.bisect_right(starts, t0)
        return i < len(starts) and starts[i] <= t1

    by_tid: Dict[Any, List[Dict[str, Any]]] = {}
    for s in slices:
        by_tid.setdefault(s["tid"], []).append(s)
    groups: Dict[Optional[str], List[Dict[str, Any]]] = {}
    for ss in by_tid.values():                         # already time-sorted
        cur: Optional[Dict[str, Any]] = None
        for s in ss:
            t1 = s["t0"] + s["dur"]
            if (cur is not None and s["module"] == cur["module"]
                    and s["t0"] - cur["t1"] <= _GROUP_GAP_US
                    and not dispatch_between(s["module"], cur["t1"],
                                             s["t0"])):
                cur["t1"] = max(cur["t1"], t1)
                cur["slices"].append(s)
            else:
                cur = {"module": s["module"], "t0": s["t0"], "t1": t1,
                       "slices": [s]}
                groups.setdefault(s["module"], []).append(cur)
    for module_groups in groups.values():
        module_groups.sort(key=lambda g: g["t0"])
    return groups


def _module_matches(module: Optional[str], fn: Optional[str]) -> bool:
    if not module or not fn:
        return False
    return module == f"jit_{fn}" or module == fn or module.startswith(
        f"jit_{fn}")


def _join(annotations: List[Dict[str, Any]],
          groups: Dict[Optional[str], List[Dict[str, Any]]]) -> Tuple[
              Dict[int, List[Tuple[Dict[str, Any], str]]],
              List[Dict[str, Any]]]:
    """Assign execution groups to annotations (see module docstring for the
    window → fifo → order cascade).  Returns (annotation index → list of
    (group, how)), plus the unattributed groups."""
    assigned: Dict[int, List[Tuple[Dict[str, Any], str]]] = {}
    unattributed: List[Dict[str, Any]] = []

    def candidates(module: Optional[str]) -> List[int]:
        out = [i for i, a in enumerate(annotations)
               if _module_matches(module, a.get("fn"))]
        if out:
            return out
        # No fn-matched annotation for this module: fall back to window
        # containment against every annotation (direct named_scope users).
        return list(range(len(annotations)))

    for module, module_groups in groups.items():
        cand = candidates(module)
        fn_matched = any(_module_matches(module, annotations[i].get("fn"))
                         for i in cand)
        remaining_groups: List[Dict[str, Any]] = []
        taken: set = set()
        # Pass 1: window containment (group midpoint inside the window).
        for g in module_groups:
            mid = (g["t0"] + g["t1"]) / 2.0
            hits = [i for i in cand
                    if annotations[i]["t0"] <= mid <= annotations[i]["t1"]]
            if len(hits) == 1 or (hits and fn_matched):
                # Ambiguity (nested/overlapping windows) resolves to the
                # latest-started containing window — the innermost dispatch.
                i = max(hits, key=lambda j: annotations[j]["t0"])
                assigned.setdefault(i, []).append((g, "window"))
                taken.add(i)
            elif fn_matched:
                remaining_groups.append(g)
            else:
                unattributed.append(g)
        if not fn_matched:
            continue
        # Pass 2: FIFO zip when the leftover counts agree exactly.
        free = [i for i in cand if i not in taken]
        if remaining_groups and len(remaining_groups) == len(free):
            for g, i in zip(remaining_groups, free):
                assigned.setdefault(i, []).append((g, "fifo"))
            continue
        # Pass 3: latest candidate annotation started before the group.
        for g in remaining_groups:
            before = [i for i in cand if annotations[i]["t0"] <= g["t0"]]
            i = max(before, default=(cand[0] if cand else None),
                    key=lambda j: annotations[j]["t0"])
            if i is None:
                unattributed.append(g)
            else:
                assigned.setdefault(i, []).append((g, "order"))
    return assigned, unattributed


def build_profile(annotations: List[Dict[str, Any]],
                  slices: List[Dict[str, Any]], *,
                  meta: Optional[Dict[str, Any]] = None,
                  trace_file: Optional[str] = None) -> Dict[str, Any]:
    """Pool device slices per annotation and assemble the
    ``_device_profile.json`` payload (see the module docstring for the
    schema's meaning; ``v`` gates readers)."""
    groups = _group_slices(slices, annotations)
    assigned, unattributed = _join(annotations, groups)
    last_slice_end = max((s["t0"] + s["dur"] for s in slices), default=0.0)

    programs: List[Dict[str, Any]] = []
    phases: Dict[str, Dict[str, Any]] = {}
    # Fused launches (annotations carrying a phase table) additionally split
    # their measured device seconds across the listed sub-phases — the
    # single multi-phase launch does NOT collapse into one opaque row, and
    # does not double-count either: the launch still appears exactly once
    # under its own program in `phases` (the --check launch-count invariant).
    fused_split: Dict[str, Dict[str, float]] = {}
    fused_split_source_s = 0.0
    for i, a in enumerate(annotations):
        window_s = max(0.0, (a["t1"] - a["t0"]) / 1e6)
        got = assigned.get(i, [])
        device_us = 0.0
        n_slices = 0
        rec_intervals: List[Tuple[float, float]] = []
        how = "unjoined"
        for g, g_how in got:
            for s in g["slices"]:
                if g_how == "window":
                    # Clip to the window: joined device time can then never
                    # exceed the host span that launched it (the --check
                    # invariant holds on the occupancy union below).
                    o0 = max(s["t0"], a["t0"])
                    o1 = min(s["t0"] + s["dur"], a["t1"])
                    if o1 <= o0:
                        continue
                    device_us += o1 - o0
                    rec_intervals.append((o0, o1))
                else:
                    device_us += s["dur"]
                    rec_intervals.append((s["t0"], s["t0"] + s["dur"]))
                n_slices += 1
        if got:
            hows = {g_how for _, g_how in got}
            how = ("window" if hows == {"window"}
                   else "fifo" if "fifo" in hows
                   else "order")
        rec = {
            "program": a["program"],
            "span_id": a["span_id"],
            "fn": a.get("fn"),
            "window_seconds": round(window_s, 6),
            # sum = device resource-seconds (parallel thunks double-count);
            # union = device occupancy — the quantity bounded by the span.
            "device_seconds": round(device_us / 1e6, 6),
            "device_union_seconds": round(_union_seconds(rec_intervals), 6),
            "slices": n_slices,
            "joined": how,
        }
        table = a.get("phases")
        if table:
            rec["phases_in_launch"] = list(table)
            total_w = sum(table.values()) or 1.0
            for pname, w in table.items():
                cell = fused_split.setdefault(
                    pname, {"device_seconds": 0.0, "launches": 0})
                cell["device_seconds"] += (device_us / 1e6) * (w / total_w)
                cell["launches"] += 1
            fused_split_source_s += device_us / 1e6
        if how == "unjoined" and a["t0"] >= last_slice_end:
            # Dispatched inside the capture window but executed after it
            # closed (an in-flight tail, e.g. the next word's pre-dispatched
            # baseline): truncated by the capture boundary, not a join miss.
            rec["truncated"] = True
        if len(programs) < _MAX_PROGRAM_RECORDS:
            programs.append(rec)
        ph = phases.setdefault(a["program"], {
            "launches": 0, "device_seconds": 0.0, "window_seconds": 0.0,
            "slices": 0, "unjoined_launches": 0})
        ph["launches"] += 1
        ph["device_seconds"] += device_us / 1e6
        ph["window_seconds"] += window_s
        ph["slices"] += n_slices
        if how == "unjoined":
            ph["unjoined_launches"] += 1
    for ph in phases.values():
        ph["device_seconds"] = round(ph["device_seconds"], 6)
        ph["window_seconds"] = round(ph["window_seconds"], 6)

    # Device-timeline totals: busy union vs the capture extent IS the
    # measured dispatch-gap share (no host inference involved).
    intervals = [(s["t0"], s["t0"] + s["dur"]) for s in slices]
    busy_union = _union_seconds(intervals)
    busy_sum = sum(s["dur"] for s in slices) / 1e6
    ts_all = ([s["t0"] for s in slices] + [a["t0"] for a in annotations])
    te_all = ([s["t0"] + s["dur"] for s in slices]
              + [a["t1"] for a in annotations])
    capture_s = ((max(te_all) - min(ts_all)) / 1e6) if ts_all else 0.0
    idle_s = max(0.0, capture_s - busy_union)

    top: Dict[str, Dict[str, Any]] = {}
    for s in slices:
        base = _base_op_name(s["name"])
        cell = top.setdefault(base, {"op": base, "seconds": 0.0, "count": 0,
                                     "class": classify_op(base)})
        cell["seconds"] += s["dur"] / 1e6
        cell["count"] += 1
    top_ops = sorted(top.values(), key=lambda c: -c["seconds"])[:15]
    for c in top_ops:
        c["seconds"] = round(c["seconds"], 6)
    op_classes: Dict[str, float] = {}
    for cell in top.values():
        op_classes[cell["class"]] = (op_classes.get(cell["class"], 0.0)
                                     + cell["seconds"])
    op_classes = {
        k: {"seconds": round(v, 6),
            "share": round(v / busy_sum, 4) if busy_sum > 0 else 0.0}
        for k, v in sorted(op_classes.items(), key=lambda kv: -kv[1])}

    if fused_split:
        for cell in fused_split.values():
            cell["device_seconds"] = round(cell["device_seconds"], 6)
        fused_section = {
            "phases": fused_split,
            "source_device_seconds": round(fused_split_source_s, 6),
            "note": "single fused launches split per sub-phase by the "
                    "in-graph phase table riding each launch's annotation "
                    "(runtime/fused.py; analytic weights at launch shapes)",
        }
    else:
        fused_section = None

    unattr_s = sum(s["dur"] for g in unattributed for s in g["slices"]) / 1e6
    capture_meta = {
        "annotations": len(annotations),
        "device_slices": len(slices),
    }
    if trace_file:
        capture_meta["trace_file"] = trace_file
    meta = dict(meta or {})
    capture_meta.update(
        {k: meta.pop(k) for k in list(meta)
         if k in ("capture_wall_seconds", "words")})
    out = {
        "v": SCHEMA_VERSION,
        "generated_by": "taboo_brittleness_tpu.obs.profile",
        **meta,
        "capture": capture_meta,
        "programs": programs,
        "phases": phases,
        "device": {
            "busy_seconds": round(busy_sum, 6),
            "busy_union_seconds": round(busy_union, 6),
            "capture_seconds": round(capture_s, 6),
            "idle_seconds": round(idle_s, 6),
            "idle_share": round(idle_s / capture_s, 4) if capture_s > 0 else 0.0,
        },
        "top_ops": top_ops,
        "op_classes": op_classes,
        "unattributed": {
            "seconds": round(unattr_s, 6),
            "groups": len(unattributed),
        },
    }
    if fused_section is not None:
        out["fused_phase_split"] = fused_section
    return out


def load_device_profile(path: str) -> Dict[str, Any]:
    """Read a ``_device_profile.json`` (raises on unreadable/newer-schema —
    callers decide whether that is fatal)."""
    with open(path, "r", encoding="utf-8") as f:
        profile = json.load(f)
    if not isinstance(profile, dict):
        raise ValueError(f"{path}: not a JSON object")
    if int(profile.get("v", 0)) > SCHEMA_VERSION:
        raise ValueError(
            f"{path}: schema v{profile.get('v')} is newer than this reader "
            f"(v{SCHEMA_VERSION})")
    return profile


# ---------------------------------------------------------------------------
# `tbx profile` drivers.
# ---------------------------------------------------------------------------

def run_launch_profile(*, phase: str = "decode", rows: Optional[int] = None,
                       prompt_len: int = 32, new_tokens: int = 50,
                       trace_dir: Optional[str] = None,
                       top: int = 20) -> Dict[str, Any]:
    """Device-profile ONE compiled sweep launch (decode / readout / nll) on
    the current backend — the flow that found the round-4 KV-stack copies
    (22% of the decode phase).  Compiles outside the capture window, then
    captures exactly one annotated launch and returns the parsed profile
    plus a rendered ``lines`` summary for the CLI to print."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from taboo_brittleness_tpu.models import gemma2
    from taboo_brittleness_tpu.ops import sae as sae_ops
    from taboo_brittleness_tpu.pipelines import interventions as iv
    from taboo_brittleness_tpu.runtime import decode

    if phase not in ("decode", "readout", "nll"):
        raise ValueError(f"unknown phase {phase!r}")
    on_accel = jax.default_backend() != "cpu"
    cfg = gemma2.PRESETS["gemma2_bench" if on_accel else "gemma2_tiny"]
    rows = rows or (330 if on_accel else 8)
    params = gemma2.init_params(jax.random.PRNGKey(0), cfg)
    sae = sae_ops.init_random(jax.random.PRNGKey(1), cfg.hidden_size,
                              16384 if on_accel else 64)
    tap = min(31, cfg.num_layers - 1)
    rng = np.random.default_rng(1)
    prompts = [list(rng.integers(1, cfg.vocab_size, size=prompt_len))
               for _ in range(rows)]
    padded, valid, positions = decode.pad_prompts(prompts)
    ins = (jnp.asarray(padded), jnp.asarray(valid), jnp.asarray(positions))
    ep = {"sae": sae,
          "latent_ids": jnp.asarray(
              rng.integers(0, sae.w_enc.shape[1], size=(rows, 32)), jnp.int32),
          "layer": tap}
    resp_start = prompt_len - 1

    def run_decode():
        with annotate("decode", fn=decode.greedy_decode, span_id=1):
            d = decode.greedy_decode(
                params, cfg, *ins, max_new_tokens=new_tokens,
                edit_fn=iv.sae_ablation_edit, edit_params=ep, stop_ids=(-1,),
                capture_residual_layer=tap, return_prefill_cache=True)
            jax.block_until_ready(d.tokens)
        return d

    dec = run_decode()                       # compile + downstream inputs
    layout = decode.response_layout_device(dec)

    def run_readout():
        with annotate("readout", fn=iv._residual_measure, span_id=2):
            out = iv._residual_measure(
                params, cfg, dec.residual, layout.sequences,
                layout.response_mask, jnp.zeros((rows,), jnp.int32),
                top_k=5, resp_start=resp_start)
            jax.block_until_ready(out["agg_ids"])

    def run_nll():
        pos2 = jnp.maximum(jnp.cumsum(dec.sequence_valid, 1) - 1, 0)
        pos2 = pos2.astype(jnp.int32)
        nm = jnp.zeros_like(dec.sequence_valid).at[:, resp_start:-1].set(True)
        with annotate("nll", fn=iv._nll_cached_jit, span_id=3):
            nll = iv._nll_cached_jit(
                params, cfg, *dec.prefill_cache,
                dec.sequences, dec.sequence_valid, pos2, nm,
                edit_fn=iv.sae_ablation_edit,
                edit_params={**ep, "chunk_positions": pos2[:, resp_start:]},
                resp_start=resp_start)
            jax.block_until_ready(nll)

    fn = {"decode": run_decode, "readout": run_readout, "nll": run_nll}[phase]
    fn()                                      # compile the chosen phase
    trace_dir = trace_dir or os.path.join("/tmp", "tbx_prof")
    capture = DeviceCapture(trace_dir)
    if not capture.start():
        raise RuntimeError(
            f"could not start a profiler capture into {trace_dir} "
            "(another capture live in this process?)")
    fn()
    profile = capture.stop()
    if profile is None:
        raise RuntimeError(f"no trace parsed from {trace_dir}")

    lines = [f"top {top} ops for ONE {phase} launch at {rows} rows:"]
    for cell in profile["top_ops"][:top]:
        lines.append(f"  {cell['seconds']:10.6f}s  x{cell['count']:5d}  "
                     f"[{cell['class']:<8}] {cell['op'][:80]}")
    dev = profile["device"]
    lines.append(
        f"device busy {dev['busy_seconds']:.4f}s "
        f"(union {dev['busy_union_seconds']:.4f}s) over a "
        f"{dev['capture_seconds']:.4f}s capture — idle share "
        f"{dev['idle_share']:.1%}")
    lines.append(f"raw trace -> {trace_dir}")
    return {"profile": profile, "phase": phase, "rows": rows, "lines": lines}


class StageTimers:
    """Nested wall-clock timers with self-time attribution (the host half of
    the profiler; previously ``tools/profile_study_host.py``).

    ``wrap(mod, name)`` monkeypatches ``mod.name`` with a timed version;
    nesting is tracked on a stack so a parent's self-time excludes its timed
    children (e.g. ``prepare_word_state`` minus its ``_residual_measure``).
    """

    def __init__(self) -> None:
        self.total: Dict[str, float] = {}
        self.self_time: Dict[str, float] = {}
        self.count: Dict[str, int] = {}
        self._stack: List[List] = []   # [name, t0, child_seconds]

    def enter(self, name: str) -> None:
        self._stack.append([name, time.perf_counter(), 0.0])

    def exit(self) -> None:
        name, t0, child = self._stack.pop()
        dt = time.perf_counter() - t0
        self.total[name] = self.total.get(name, 0.0) + dt
        self.self_time[name] = self.self_time.get(name, 0.0) + dt - child
        self.count[name] = self.count.get(name, 0) + 1
        if self._stack:
            self._stack[-1][2] += dt

    def wrap(self, mod: Any, name: str, label: Optional[str] = None) -> None:
        import functools

        label = label or name
        fn = getattr(mod, name)

        @functools.wraps(fn)
        def timed(*a, **kw):
            self.enter(label)
            try:
                return fn(*a, **kw)
            finally:
                self.exit()

        setattr(mod, name, timed)

    def reset(self) -> None:
        self.total.clear()
        self.self_time.clear()
        self.count.clear()

    def report_lines(self, wall: float, title: str) -> List[str]:
        lines = [f"== {title} (wall {wall:.2f}s) ==",
                 f"  {'stage':42s} {'total':>8s} {'self':>8s} {'calls':>6s}"]
        for name in sorted(self.self_time, key=self.self_time.get,
                           reverse=True):
            lines.append(f"  {name:42s} {self.total[name]:8.3f} "
                         f"{self.self_time[name]:8.3f} {self.count[name]:6d}")
        accounted = sum(self.total[n] for n in self.total
                        if self.count[n] and n.startswith("word:"))
        untimed = wall - accounted
        if abs(untimed) > 0.01:
            lines.append(f"  {'(outside timed stages)':42s} {untimed:8.3f}")
        return lines


def run_study_host_profile(*, words: int = 2, prompt_len: int = 32,
                           new_tokens: int = 50) -> Dict[str, Any]:
    """Host-side wall-clock breakdown of real study words (VERDICT r04 #1):
    runs the REAL ``run_intervention_studies`` driver on synthetic
    bench-shape words with every interesting stage wrapped in a nested
    timer, and returns a self-time-ranked tree per word.  Device waits show
    up inside whichever stage blocks — read next to ``_device_profile.json``
    (the device half) to separate "device busy" from "host busy".

    The first word pays all compiles; per-word reports return separately so
    the steady state is readable on its own.  ``TBX_PROFILE_NO_SPLIT=1``
    times the real overlapped ``_collect_rows`` instead of splitting it into
    device-wait + host halves."""
    import shutil
    import tempfile

    import numpy as np

    import jax

    from taboo_brittleness_tpu.runtime import jax_cache

    jax_cache.enable()

    from taboo_brittleness_tpu.config import (
        Config, ExperimentConfig, InterventionConfig, ModelConfig)
    from taboo_brittleness_tpu.models import gemma2
    from taboo_brittleness_tpu.ops import lens, projection, sae as sae_ops
    from taboo_brittleness_tpu.pipelines import interventions as iv
    from taboo_brittleness_tpu.runtime import decode
    from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

    on_accel = jax.default_backend() != "cpu"
    preset = "gemma2_bench" if on_accel else "gemma2_tiny"
    cfg = gemma2.PRESETS[preset]
    params = gemma2.init_params(jax.random.PRNGKey(0), cfg)
    sae = sae_ops.init_random(jax.random.PRNGKey(2), cfg.hidden_size,
                              16384 if on_accel else 64)
    tap = min(31, cfg.num_layers - 1)

    word_list = [f"profword{i}" for i in range(words)]
    lex = [f"w{i:02d}" for i in range(
        max(4, min(64, (cfg.vocab_size - 109) // 2 - words - 2)))]
    tok = WordTokenizer(word_list + lex, vocab_size=cfg.vocab_size)
    rng = np.random.default_rng(7)
    prompts = [" ".join(rng.choice(lex, size=max(prompt_len - 8, 2)))
               for _ in range(10)]
    config = Config(
        model=ModelConfig(layer_idx=tap, top_k=5, arch=preset,
                          dtype="bfloat16", param_dtype="bfloat16"),
        experiment=ExperimentConfig(seed=0, max_new_tokens=new_tokens,
                                    pad_to_multiple=prompt_len),
        intervention=InterventionConfig(),
        word_plurals={w: [w] for w in word_list},
        prompts=prompts,
    )

    t = StageTimers()
    # Stage wrappers, outer to inner.  _dispatch_rows is pure enqueue (host
    # trace + transfer time); _collect_rows blocks on the device queue.
    t.wrap(iv, "prepare_word_state")
    t.wrap(iv, "score_latents_for_word")
    t.wrap(iv, "plan_ablation_sweep")
    t.wrap(iv, "plan_projection_sweep")
    t.wrap(iv, "measure_arm_sets")
    t.wrap(iv, "_dispatch_rows")
    t.wrap(iv, "_residual_measure", "residual_measure(dispatch)")
    t.wrap(iv, "_decode_guess_rows")
    t.wrap(iv, "_tile_rows_ep")
    t.wrap(iv, "_atomic_json_dump", "json_dump")
    t.wrap(iv.metrics_mod, "calculate_metrics")
    t.wrap(iv.metrics_mod, "leak_rate")
    t.wrap(projection, "principal_subspace")
    t.wrap(decode, "generate", "decode.generate(dispatch)")
    t.wrap(decode, "decode_texts", "decode_texts(host work)")
    t.wrap(decode, "texts_from_tokens", "texts_from_tokens(host)")
    t.wrap(decode, "response_layout_device")
    t.wrap(lens, "spike_positions_batch", "spike_positions(dispatch)")

    # Split _collect_rows into device-wait vs host work: block on every
    # in-flight output FIRST under a wait timer, so the wrapped inner stages
    # measure pure host time.  (This serializes what the real collect
    # overlaps; per-stage attribution is exact while the word wall-clock
    # stays within ~the overlap window of the real run.)
    split = os.environ.get("TBX_PROFILE_NO_SPLIT", "0") != "1"
    real_collect = iv._collect_rows

    def collect_split(tok_, config_, state_, handle):
        t.enter("collect.device_wait")
        try:
            jax.block_until_ready((handle["dec"].tokens,
                                   handle["edited_nll"],
                                   handle["out"]["agg_ids"]))
        finally:
            t.exit()
        t.enter("collect.host")
        try:
            return real_collect(tok_, config_, state_, handle)
        finally:
            t.exit()

    if split:
        iv._collect_rows = collect_split
    else:
        t.wrap(iv, "_collect_rows")

    def model_loader(word):
        return params, cfg, tok

    out_dir = tempfile.mkdtemp(prefix="tbx_prof_study_")
    reports: List[Dict[str, Any]] = []
    try:
        for i, w in enumerate(word_list):
            t.reset()
            t.enter(f"word:{w}")
            t0 = time.perf_counter()
            iv.run_intervention_studies(
                config, model_loader=model_loader, sae=sae, words=[w],
                output_dir=out_dir)
            wall = time.perf_counter() - t0
            t.exit()
            title = f"word {i} ({'compile' if i == 0 else 'steady'})"
            reports.append({
                "word": w, "wall_seconds": round(wall, 3),
                "total": {k: round(v, 4) for k, v in t.total.items()},
                "self": {k: round(v, 4) for k, v in t.self_time.items()},
                "calls": dict(t.count),
                "lines": t.report_lines(wall, title),
            })
    finally:
        iv._collect_rows = real_collect
        shutil.rmtree(out_dir, ignore_errors=True)
    return {"preset": preset, "words": reports}
