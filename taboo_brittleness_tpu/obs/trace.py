"""Hierarchical span tracing with an append-only JSONL event sink.

The sweep is a multi-hour, 20-checkpoint grid whose only runtime signals used
to be scattered ``print()``s, end-of-run manifest stage times, and the
resilience ledger — when a TPU round stalls or regresses there was no event
stream to reconstruct *where time and HBM went*.  This module is the event
stream: thread-safe hierarchical spans (run → word → phase → program) with
monotonic timing and structured attributes, appended one JSON line at a time
to ``<output_dir>/_events.jsonl`` (the same directory as the results the
events describe, so a copied/rsynced run keeps its timeline).

Design constraints, all deliberate:

- **Host-side only.**  Nothing here runs under trace; spans wrap dispatches,
  never ops, so no new jit entry points and no graph pollution.
- **Fail-open.**  Telemetry must never take down a run: every sink error is
  swallowed and counted (``obs.events_dropped`` in the metrics registry).
  The one exception is the *deliberate* fault-injection site
  ``obs.event_write`` (runtime.resilience), which tests use to prove exactly
  this property.
- **Atomic appends.**  Each event is one ``os.write`` to an ``O_APPEND`` fd —
  concurrent writers (prefetch threads, the warm-start thread, the renderer)
  interleave whole lines, never bytes.  A torn final line from a killed run
  is skipped by the reader (``iter_events``), matching the repo's
  quarantine-not-crash stance on resume artifacts.
- **Dependency-free.**  stdlib + (lazily) jax introspection via obs.memory.

Timing: event ``t`` is seconds on the MONOTONIC clock relative to the
tracer's creation (durations survive NTP steps); the ``run_start`` event
additionally carries one wall-clock epoch so tooling can anchor the timeline
to calendar time.
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

#: Bumped whenever an event record gains/renames a REQUIRED key; readers
#: (tools/trace_report.py) accept their own version and older.
SCHEMA_VERSION = 1

EVENTS_FILENAME = "_events.jsonl"

#: Span kinds, outermost first — the hierarchy trace_report renders.
#: ``request`` spans (serve.scheduler) are per-request lifecycle intervals:
#: they parent under the run span but live OFF the per-thread stack (many
#: interleave on the one serve thread), opened via :meth:`Tracer.span_detached`.
KINDS = ("run", "word", "phase", "program", "request", "point")


def enabled() -> bool:
    """Master switch: ``TBX_OBS=0`` disables activation entirely (the bench's
    obs-off A/B arm); unset/1 enables it.  Individual samplers have their own
    ``TBX_OBS_*`` knobs and default off."""
    return os.environ.get("TBX_OBS", "1") != "0"


def _mem_sample_kinds() -> frozenset:
    """Span kinds whose END events carry an HBM/RSS watermark sample.
    Default: run+word boundaries (one procfs read + one device-stats poll
    per word — noise-level against a multi-second word).  ``TBX_OBS_MEM=0``
    turns boundary sampling off, ``phase`` adds phase ends, ``all`` adds
    program spans too (one sample per launch — noticeably chattier)."""
    v = os.environ.get("TBX_OBS_MEM", "1")
    if v == "0":
        return frozenset()
    if v == "phase":
        return frozenset({"run", "word", "phase"})
    if v == "all":
        return frozenset({"run", "word", "phase", "program"})
    return frozenset({"run", "word"})


class Span:
    """One timed interval.  Use as a context manager::

        with tracer.span("decode", kind="program", rows=40) as sp:
            sp.set(aot="hit")

    On exit the end event records ``dur`` (seconds) and ``status``
    ("ok"/"error" + the exception type).  ``event()`` emits point events
    parented to this span."""

    __slots__ = ("tracer", "name", "kind", "span_id", "parent_id",
                 "attrs", "_t0", "_done")

    def __init__(self, tracer: "Tracer", name: str, kind: str,
                 span_id: int, parent_id: Optional[int],
                 attrs: Dict[str, Any]):
        self.tracer = tracer
        self.name = name
        self.kind = kind
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self._t0 = time.monotonic()
        self._done = False

    def set(self, **attrs: Any) -> "Span":
        """Attach attributes that will ride on the span's END event (e.g.
        retry_count known only after the work ran)."""
        self.attrs.update(attrs)
        return self

    def event(self, name: str, **attrs: Any) -> None:
        self.tracer.event(name, parent=self.span_id, **attrs)

    # -- context manager ---------------------------------------------------

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.end(error=exc)

    def end(self, error: Optional[BaseException] = None) -> None:
        if self._done:      # idempotent: __exit__ after an explicit end()
            return
        self._done = True
        rec = {
            "ev": "end",
            "kind": self.kind,
            "name": self.name,
            "id": self.span_id,
            "dur": round(time.monotonic() - self._t0, 6),
            "status": "error" if error is not None else "ok",
        }
        if self.parent_id is not None:
            rec["parent"] = self.parent_id
        if error is not None:
            rec["error"] = f"{type(error).__name__}: {error}"[:500]
        if self.attrs:
            rec["attrs"] = self.attrs
        if self.kind in self.tracer.mem_kinds:
            mem = self.tracer._memory()
            if mem:
                rec["mem"] = mem
        self.tracer._pop(self)
        self.tracer._emit(rec)


#: Buffered-sink flush policy: events accumulate in memory and hit disk on
#: whichever trips first — byte cap, age, or close.  One os.write per flush
#: keeps per-event cost at ~a microsecond (a 200-event sweep word costs the
#: sink two syscalls, not 200) while the file trails live state by at most
#: _FLUSH_INTERVAL_S — the progress heartbeat flushes too, so "is it alive"
#: reads stay fresh.
_FLUSH_BYTES = 32 * 1024
_FLUSH_INTERVAL_S = 1.0


def _resume_marks(path: str) -> "tuple[int, int]":
    """(last seq, max span id) parsed from an existing sink's tail window.

    A supervised run appends several processes' event streams to ONE
    ``_events.jsonl`` (each incarnation, plus the supervisor's own point
    events between launches).  Resuming both counters from the file keeps
    the merged stream's ``seq`` strictly monotone and its span ids unique —
    the invariants ``trace_report --check`` holds the schema to — without
    any cross-process coordination beyond O_APPEND.  Torn tail lines (a
    killed incarnation) are skipped, matching ``iter_events``.  Spans older
    than the 64 KiB tail window can in principle alias an id; that degrades
    a rendered report, never a run.
    """
    try:
        size = os.path.getsize(path)
    except OSError:
        return 0, 0
    if not size:
        return 0, 0
    try:
        with open(path, "rb") as f:
            f.seek(max(0, size - 65536))
            tail = f.read().decode("utf-8", "replace")
    except OSError:
        return 0, 0
    seq = max_id = 0
    for line in tail.splitlines():
        try:
            ev = json.loads(line)
        except ValueError:
            continue
        if not isinstance(ev, dict):
            continue
        try:
            seq = max(seq, int(ev.get("seq", 0) or 0))
            max_id = max(max_id, int(ev.get("id", 0) or 0))
        except (TypeError, ValueError):
            continue
    return seq, max_id


class Tracer:
    """One run's event sink.  All methods are thread-safe; parentage is
    tracked per-thread (a span opened on a worker thread without an explicit
    ``parent=`` nests under nothing, not under another thread's span).

    Opening a sink that already has events RESUMES its seq/span-id counters
    from the file tail (:func:`_resume_marks`) — the incarnation-aware
    append contract of ``runtime.supervise``."""

    def __init__(self, path: Optional[str], *, run_id: Optional[str] = None):
        self.path = path
        self.run_id = run_id
        self.mem_kinds = _mem_sample_kinds()
        # Fleet worker identity (runtime.fleet): stamped top-level on every
        # event this process emits, so per-worker streams stay
        # self-identifying after the fleet merge folds them into one file.
        try:
            from taboo_brittleness_tpu.runtime.resilience import (
                current_worker_id)

            self._worker = current_worker_id()
        except Exception:  # noqa: BLE001 — identity is best-effort
            self._worker = None
        self._fd: Optional[int] = None
        self._lock = threading.Lock()
        self._seq = 0
        self._next_id = 1
        self._local = threading.local()
        self._t0 = time.monotonic()
        self._last_event_mono = self._t0
        self._buf: List[bytes] = []
        self._buf_bytes = 0
        self._last_flush = self._t0
        self.dropped = 0
        if path is not None:
            try:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                seq0, id0 = _resume_marks(path)
                self._seq, self._next_id = seq0, id0 + 1
                self._fd = os.open(
                    path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            except OSError:
                self._fd = None      # fail-open: spans still time, sink drops

    # -- core emit ---------------------------------------------------------

    def _memory(self) -> Optional[Dict[str, Any]]:
        try:
            from taboo_brittleness_tpu.obs import memory as memory_mod

            return memory_mod.sample(compact=True)
        except Exception:  # noqa: BLE001 — sampling is best-effort
            return None

    def _emit(self, rec: Dict[str, Any]) -> None:
        """Buffer one event line (flushed by size/age/heartbeat/close).
        NEVER raises (fail-open): a failed serialize/write increments
        ``dropped`` (and the obs.events_dropped counter) and the run
        continues untouched."""
        now = time.monotonic()
        with self._lock:
            self._seq += 1
            rec = {"v": SCHEMA_VERSION, "seq": self._seq,
                   "t": round(now - self._t0, 6), **rec}
            if self._worker is not None:
                rec.setdefault("worker", self._worker)
            self._last_event_mono = now
            if self._fd is None:
                return
            try:
                from taboo_brittleness_tpu.runtime import resilience

                resilience.fire("obs.event_write", path=self.path,
                                name=rec.get("name", ""))
                line = (json.dumps(rec, default=str) + "\n").encode("utf-8")
                self._buf.append(line)
                self._buf_bytes += len(line)
                if (self._buf_bytes >= _FLUSH_BYTES
                        or now - self._last_flush >= _FLUSH_INTERVAL_S):
                    self._flush_locked()
            except Exception:  # noqa: BLE001 — telemetry must never kill a run
                self.dropped += 1
                try:
                    from taboo_brittleness_tpu.obs import metrics

                    metrics.counter("obs.events_dropped").inc()
                except Exception:  # noqa: BLE001
                    pass

    def _flush_locked(self) -> None:
        """One os.write of every buffered line (whole lines, so concurrent
        tracers still interleave at line granularity via O_APPEND).  Caller
        holds the lock."""
        self._last_flush = time.monotonic()
        if not self._buf or self._fd is None:
            return
        buf, self._buf = self._buf, []
        n_bytes, self._buf_bytes = self._buf_bytes, 0
        try:
            os.write(self._fd, b"".join(buf))
        except Exception:  # noqa: BLE001 — fail-open: the batch is dropped
            self.dropped += len(buf)
            _ = n_bytes

    def flush(self) -> None:
        """Force buffered events to disk (heartbeat hook; tests)."""
        with self._lock:
            try:
                self._flush_locked()
            except Exception:  # noqa: BLE001
                pass

    # -- per-thread span stack --------------------------------------------

    def _stack(self) -> List[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current_span(self) -> Optional[Span]:
        st = self._stack()
        return st[-1] if st else None

    def _pop(self, span: Span) -> None:
        st = self._stack()
        if span in st:
            del st[st.index(span):]

    # -- public API --------------------------------------------------------

    def span(self, name: str, *, kind: str = "phase",
             parent: Optional[int] = None, **attrs: Any) -> Span:
        cur = self.current_span()
        parent_id = parent if parent is not None else (
            cur.span_id if cur is not None else None)
        with self._lock:
            span_id = self._next_id
            self._next_id += 1
        rec: Dict[str, Any] = {"ev": "start", "kind": kind, "name": name,
                               "id": span_id}
        if parent_id is not None:
            rec["parent"] = parent_id
        if attrs:
            rec["attrs"] = dict(attrs)
        if kind == "run":
            rec["run_id"] = self.run_id
            rec["pid"] = os.getpid()
            # Epoch anchor for the otherwise-relative monotonic timeline.
            # tbx: wallclock-ok — genuine epoch timestamp (durations use monotonic)
            rec["wall"] = time.time()
        self._emit(rec)
        sp = Span(self, name, kind, span_id, parent_id, dict(attrs))
        self._stack().append(sp)
        return sp

    def span_detached(self, name: str, *, kind: str = "request",
                      parent: Optional[int] = None, **attrs: Any) -> Span:
        """Open a span WITHOUT joining the per-thread stack.

        For intervals that overlap arbitrarily on one thread (the serve
        loop's per-request lifecycle spans: many requests in flight, none
        nesting inside another): the span still parents under the thread's
        current span (or an explicit ``parent=``), but later ``span()``
        calls on this thread do NOT nest under it, and ending it cannot
        pop unrelated spans off the stack.  End explicitly via
        ``sp.end()``."""
        cur = self.current_span()
        parent_id = parent if parent is not None else (
            cur.span_id if cur is not None else None)
        with self._lock:
            span_id = self._next_id
            self._next_id += 1
        rec: Dict[str, Any] = {"ev": "start", "kind": kind, "name": name,
                               "id": span_id}
        if parent_id is not None:
            rec["parent"] = parent_id
        if attrs:
            rec["attrs"] = dict(attrs)
        self._emit(rec)
        return Span(self, name, kind, span_id, parent_id, dict(attrs))

    def event(self, name: str, *, parent: Optional[int] = None,
              **attrs: Any) -> None:
        """A zero-duration point event (retry, quarantine, prefetch start,
        aot build record, log line...)."""
        cur = self.current_span()
        parent_id = parent if parent is not None else (
            cur.span_id if cur is not None else None)
        rec: Dict[str, Any] = {"ev": "point", "kind": "point", "name": name}
        if parent_id is not None:
            rec["parent"] = parent_id
        if attrs:
            rec["attrs"] = dict(attrs)
        self._emit(rec)

    def last_seq(self) -> int:
        """Sequence number of the most recent event — the 'event offset' the
        failure ledger records next to a quarantine so the surrounding
        timeline is one seek away."""
        with self._lock:
            return self._seq

    def last_event_age(self) -> float:
        """Seconds since the last emitted event (the progress heartbeat's
        liveness signal)."""
        with self._lock:
            return time.monotonic() - self._last_event_mono

    def close(self) -> None:
        with self._lock:
            try:
                self._flush_locked()
            except Exception:  # noqa: BLE001
                pass
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None


# ---------------------------------------------------------------------------
# Process-wide tracer stack.
#
# Shared code (decode launches, checkpoint prefetch, aot builds, resilience
# retries) emits to the INNERMOST active tracer; with none active every call
# is a cheap no-op.  A stack (not a single slot) so a sweep nested inside
# another instrumented driver (bench's study block) keeps one coherent sink.
# ---------------------------------------------------------------------------

_STACK: List[Tracer] = []
_STACK_LOCK = threading.Lock()
_LAST_PATH: Optional[str] = None


def activate(path: Optional[str], *, run_id: Optional[str] = None) -> Tracer:
    """Open a tracer writing to ``path`` (a JSONL file, or None for a
    sink-less tracer that still times spans) and make it current."""
    global _LAST_PATH
    t = Tracer(path, run_id=run_id)
    with _STACK_LOCK:
        _STACK.append(t)
        if path is not None:
            _LAST_PATH = path
    return t


def deactivate(tracer: Tracer) -> None:
    with _STACK_LOCK:
        if tracer in _STACK:
            _STACK.remove(tracer)
    tracer.close()


def get_tracer() -> Optional[Tracer]:
    with _STACK_LOCK:
        return _STACK[-1] if _STACK else None


def events_path() -> Optional[str]:
    """The innermost active tracer's sink path — falling back to the most
    recently activated one, since the manifest is saved AFTER the sweep's
    observer closes (the stamp must survive deactivation)."""
    t = get_tracer()
    if t is not None and t.path is not None:
        return t.path
    with _STACK_LOCK:
        return _LAST_PATH


# -- module-level conveniences (no-ops without an active tracer) ------------

class _NullSpan:
    """Stand-in span when no tracer is active: same surface, zero cost."""

    span_id = None
    parent_id = None

    def set(self, **attrs: Any) -> "_NullSpan":
        return self

    def event(self, name: str, **attrs: Any) -> None:
        pass

    def end(self, error: Optional[BaseException] = None) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


NULL_SPAN = _NullSpan()


def span(name: str, *, kind: str = "phase", **attrs: Any):
    t = get_tracer()
    if t is None:
        return NULL_SPAN
    try:
        return t.span(name, kind=kind, **attrs)
    except Exception:  # noqa: BLE001 — fail-open
        return NULL_SPAN


def span_detached(name: str, *, kind: str = "request", **attrs: Any):
    t = get_tracer()
    if t is None:
        return NULL_SPAN
    try:
        return t.span_detached(name, kind=kind, **attrs)
    except Exception:  # noqa: BLE001 — fail-open
        return NULL_SPAN


def event(name: str, **attrs: Any) -> None:
    t = get_tracer()
    if t is None:
        return
    try:
        t.event(name, **attrs)
    except Exception:  # noqa: BLE001 — fail-open
        pass


def last_seq() -> Optional[int]:
    t = get_tracer()
    return t.last_seq() if t is not None else None


# ---------------------------------------------------------------------------
# Reader.
# ---------------------------------------------------------------------------

def iter_events(path: str, *, strict: bool = False) -> Iterator[Dict[str, Any]]:
    """Yield events from a JSONL sink, skipping unparseable lines (a torn
    final line from a killed run is expected, not an error).  ``strict=True``
    raises on the first bad line instead (trace_report --check)."""
    with io.open(path, "r", encoding="utf-8", errors="replace") as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                if strict:
                    raise ValueError(f"{path}:{lineno}: unparseable event line")
                continue
