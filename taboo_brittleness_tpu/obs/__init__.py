"""Host-side telemetry for every pipeline: spans, metrics, watermarks, progress.

One subsystem, four surfaces (see the submodule docstrings for depth):

- :mod:`~taboo_brittleness_tpu.obs.trace` — hierarchical spans
  (run → word → phase → program) appended as JSONL to
  ``<output_dir>/_events.jsonl``; render with ``tools/trace_report.py``.
- :mod:`~taboo_brittleness_tpu.obs.metrics` — process-wide
  counters/gauges/histograms, snapshotted into the run manifest.
- :mod:`~taboo_brittleness_tpu.obs.memory` — HBM live/peak + host RSS
  watermarks at span boundaries (plus an optional background sampler).
- :mod:`~taboo_brittleness_tpu.obs.progress` — the ``_progress.json``
  heartbeat (current word/phase, EMA ETA, last-event age).

Contract, repo-wide: obs code is host-side (no new jit entry points),
fail-open (telemetry errors never take down a run), stdlib + jax
introspection only, and env-gated — ``TBX_OBS=0`` disables the sink
entirely; ``TBX_OBS_MEM`` / ``TBX_OBS_MEM_HZ`` / ``TBX_OBS_PROGRESS_S``
tune the samplers.  Package code emits events through this module instead
of printing (tbx-check rule TBX009 enforces it).

Sweep drivers wrap their word loop in :func:`sweep_observer`::

    with obs.sweep_observer(output_dir, pipeline="token_forcing",
                            words=words) as ob:
        for word in words:
            with ob.word(word):
                with ob.phase("checkpoint.load"):
                    ...
"""

from __future__ import annotations

import contextlib
import sys
import uuid
from typing import Any, Iterator, Optional, Sequence

from taboo_brittleness_tpu.obs import (
    flightrec, memory, metrics, profile, progress, reqtrace, slo, timeseries,
    trace)
from taboo_brittleness_tpu.obs.trace import (
    EVENTS_FILENAME, NULL_SPAN, SCHEMA_VERSION, Tracer, activate, deactivate,
    enabled, event, events_path, get_tracer, iter_events, last_seq, span)
from taboo_brittleness_tpu.obs.progress import (
    PROGRESS_FILENAME, ProgressReporter, read_progress)
from taboo_brittleness_tpu.obs.timeseries import (
    METRICS_FILENAME, TimeseriesRecorder)

__all__ = [
    "EVENTS_FILENAME", "METRICS_FILENAME", "PROGRESS_FILENAME",
    "SCHEMA_VERSION", "ProgressReporter", "SweepObserver",
    "TimeseriesRecorder", "Tracer",
    "activate", "deactivate", "enabled", "event", "events_path", "flightrec",
    "get_tracer", "iter_events", "last_seq", "memory", "metrics", "profile",
    "progress", "read_progress", "reqtrace", "slo", "span", "sweep_observer",
    "timeseries", "trace", "warn",
]


def warn(message: str, *, name: str = "log.warn", **attrs: Any) -> None:
    """Structured replacement for the package's stray ``print(...)``s: emits
    a point event (when a tracer is active) AND mirrors the line to stderr so
    interactive runs keep their signal.  Fail-open on both paths."""
    event(name, level="warn", message=message, **attrs)
    try:
        sys.stderr.write(message + "\n")
    except Exception:  # noqa: BLE001 — a closed stderr must not kill a run
        pass


def preempt_notice_seconds() -> float:
    """The platform's preemption notice window (``TBX_PREEMPT_NOTICE_S``,
    default 30 — the v5e notice).  Drain-at-word-boundary is only safe while
    every word finishes inside this window; the sweep observer measures the
    margin per word and warns when a word outlives it — the automated signal
    that mid-word checkpointing must be promoted to a PR."""
    import os

    try:
        return max(0.0, float(os.environ.get("TBX_PREEMPT_NOTICE_S", "30")))
    except ValueError:
        return 30.0


class SweepObserver:
    """The per-sweep bundle of tracer + run span + progress heartbeat that
    :func:`sweep_observer` yields.  A disabled observer (``active=False``)
    has the same surface with every method a no-op, so drivers never branch.
    """

    def __init__(self, *, tracer: Optional[Tracer] = None,
                 run_span=None,
                 reporter: Optional[ProgressReporter] = None,
                 owns_tracer: bool = False,
                 mem_sampler: Optional[memory.MemorySampler] = None,
                 device_capture: Optional["profile.SweepCapture"] = None,
                 ts_recorder: Optional[TimeseriesRecorder] = None):
        self.tracer = tracer
        self.run_span = run_span
        self.reporter = reporter
        self._owns_tracer = owns_tracer
        self._mem_sampler = mem_sampler
        self._device_capture = device_capture
        self.ts_recorder = ts_recorder
        self._final_status: Optional[str] = None
        self._preempt_notice = preempt_notice_seconds()
        #: Worst-case slack between the longest computed word and the
        #: preemption notice (negative = a word outlived the notice and
        #: drain-at-word-boundary is no longer preemption-safe).
        self.preempt_margin_s: Optional[float] = None

    @property
    def active(self) -> bool:
        return self.tracer is not None

    # -- span helpers ------------------------------------------------------

    @contextlib.contextmanager
    def word(self, word: str, *, resumed: bool = False) -> Iterator[Any]:
        """One word's span + progress bookkeeping.  The span is yielded so
        the driver can attach late attributes (retry counts, quarantine)."""
        if not self.active:
            yield NULL_SPAN
            return
        if self.reporter is not None:
            self.reporter.word_started(word)
        sp = self.tracer.span("word", kind="word", word=word)
        try:
            yield sp
        except BaseException as e:
            sp.end(error=e)
            if self.reporter is not None:
                self.reporter.word_quarantined(word)
            raise
        else:
            quarantined = sp.attrs.get("quarantined", False)
            sp.end()
            if self.reporter is None:
                pass
            elif quarantined:
                self.reporter.word_quarantined(word)
            elif resumed:
                self.reporter.word_skipped(word)
            else:
                self.reporter.word_done(word)
                seconds = _span_duration(sp)
                metrics.histogram("word.seconds").observe(seconds)
                self._note_preempt_margin(word, seconds)
                if self._device_capture is not None:
                    # A computed word just finished on the device profiler's
                    # clock; the bounded capture stops itself after K of them.
                    try:
                        self._device_capture.word_done()
                    except Exception:  # noqa: BLE001 — profiling is best-effort
                        pass

    @contextlib.contextmanager
    def phase(self, name: str, **attrs: Any) -> Iterator[Any]:
        if not self.active:
            yield NULL_SPAN
            return
        if self.reporter is not None:
            self.reporter.phase(name)
        sp = self.tracer.span(name, kind="phase", **attrs)
        try:
            with sp:
                yield sp
        finally:
            if self.reporter is not None:
                self.reporter.phase(None)

    def event(self, name: str, **attrs: Any) -> None:
        if self.tracer is not None:
            try:
                self.tracer.event(name, **attrs)
            except Exception:  # noqa: BLE001 — fail-open
                pass

    def _note_preempt_margin(self, word: str, seconds: float) -> None:
        """Per-word preemption-notice guard: track the worst margin between
        word wall time and ``TBX_PREEMPT_NOTICE_S`` as a gauge (and manifest
        field), and warn when a word OUTLIVES the notice — from then on a
        preemption lands mid-word and drain-at-word-boundary tears."""
        if not self._preempt_notice:
            return
        margin = round(self._preempt_notice - seconds, 3)
        if self.preempt_margin_s is None or margin < self.preempt_margin_s:
            self.preempt_margin_s = margin
            try:
                metrics.gauge("sweep.preempt_margin_s").set(margin)
            except Exception:  # noqa: BLE001 — fail-open
                pass
        if margin < 0:
            warn(f"[obs] word {word!r} ran {seconds:.1f}s — past the "
                 f"{self._preempt_notice:.0f}s preemption notice "
                 "(TBX_PREEMPT_NOTICE_S): a preemption now lands MID-word; "
                 "promote mid-word checkpointing",
                 name="sweep.preempt_notice_exceeded", word=word,
                 wall_seconds=round(seconds, 3),
                 notice_seconds=self._preempt_notice)

    def mark_drained(self) -> None:
        """The sweep is stopping BETWEEN words for a preemption drain
        (``runtime.supervise``): the progress file's final status becomes
        ``"preempted"`` (the supervisor's safe-to-resume marker) and the run
        span carries ``drained=True`` so the timeline shows the incarnation
        boundary."""
        self._final_status = "preempted"
        if self.run_span is not None:
            self.run_span.set(drained=True)
        self.event("sweep.drained")

    def close(self, error: Optional[BaseException] = None) -> None:
        if not self.active:
            return
        try:
            _publish_aot_stats()
        except Exception:  # noqa: BLE001
            pass
        if self._device_capture is not None:
            # A sweep shorter than the capture budget still lands its
            # _device_profile.json at close.
            try:
                self._device_capture.finish()
            except Exception:  # noqa: BLE001 — profiling is best-effort
                pass
        if self._mem_sampler is not None:
            self._mem_sampler.stop()
        if self.ts_recorder is not None:
            # Final window + exit snapshot: the conservation invariant
            # ``trace_report --check`` verifies (exit totals == last window).
            try:
                self.ts_recorder.stop()
            except Exception:  # noqa: BLE001 — fail-open
                pass
        if self.run_span is not None:
            if self.preempt_margin_s is not None:
                self.run_span.set(preempt_margin_s=self.preempt_margin_s)
            self.run_span.end(error=error)
        if self.reporter is not None:
            status = self._final_status or (
                "error" if error is not None else "done")
            self.reporter.stop(status=status)
        if self._owns_tracer and self.tracer is not None:
            deactivate(self.tracer)


def _span_duration(sp) -> float:
    import time

    return time.monotonic() - sp._t0


def _publish_aot_stats() -> None:
    """Fold the AOT registry's hit/miss/fallback counters into the metrics
    registry at sweep close — the cache-hit-rate snapshot the manifest keeps."""
    from taboo_brittleness_tpu.runtime import aot

    for name, st in aot.stats().items():
        for k, v in st.items():
            metrics.gauge(f"aot.{name}.{k}").set(v)


@contextlib.contextmanager
def sweep_observer(output_dir: Optional[str], *, pipeline: str,
                   words: Sequence[str] = (),
                   run_id: Optional[str] = None) -> Iterator[SweepObserver]:
    """Activate telemetry for one sweep (tracer + run span + progress
    heartbeat + optional background memory sampler), fail-open end to end.

    Inert (yields a no-op observer) when obs is disabled (``TBX_OBS=0``) or
    there is no ``output_dir`` to write next to.  When a tracer is already
    active (a sweep nested inside an instrumented driver — e.g. bench's
    study block), the nested sweep reuses it: its run span and events land
    in the OUTER sink, keeping one coherent timeline, and only the outermost
    observer owns deactivation."""
    import os

    if not enabled() or not output_dir:
        yield SweepObserver()
        return
    try:
        from taboo_brittleness_tpu.runtime.resilience import (
            current_incarnation, current_worker_id)

        # Fleet workers (runtime.fleet) write per-worker telemetry files so
        # N workers can share one output directory: each stream keeps its
        # own strictly-monotone seq, and the fleet merge folds them later.
        wid = current_worker_id()
        events_name = (EVENTS_FILENAME if wid is None
                       else f"_events.{wid}.jsonl")
        progress_name = (PROGRESS_FILENAME if wid is None
                         else f"_progress.{wid}.json")
        metrics_name = timeseries.metrics_filename(wid)
        outer = get_tracer()
        owns = outer is None
        if owns:
            os.makedirs(output_dir, exist_ok=True)
            tracer = activate(
                os.path.join(output_dir, events_name),
                run_id=run_id or uuid.uuid4().hex[:12])
        else:
            tracer = outer

        inc = current_incarnation()
        run_span = tracer.span(
            "sweep", kind="run", pipeline=pipeline, words_total=len(words),
            **({"incarnation": inc} if inc else {}),
            **({"worker": wid} if wid else {}))
        reporter = ProgressReporter(
            os.path.join(output_dir, progress_name),
            total_words=len(words), run_id=tracer.run_id,
            tracer=tracer).start()
        sampler = memory.MemorySampler(tracer).start()
        capture = None
        if owns and profile.enabled():
            # Device-timeline capture (TBX_PROFILE=1): one bounded
            # jax.profiler window over the first TBX_PROFILE_WORDS computed
            # words, parsed into <output_dir>/_device_profile.json.  Only the
            # outermost observer may own it (profiler sessions don't nest).
            capture = profile.SweepCapture(output_dir, tracer=tracer)
            if not capture.start():
                capture = None
        recorder = None
        if owns:
            # Windowed metrics spool + SLO burn engine + crash flight
            # recorder (ISSUE 15).  Only the outermost observer owns the
            # spool — a nested sweep's counters already land in the outer
            # recorder's registry sweeps.
            flightrec.configure(output_dir, worker_id=wid)
            engine = slo.SloEngine()
            recorder = TimeseriesRecorder(
                os.path.join(output_dir, metrics_name),
                slo_engine=engine,
                on_window=lambda rec, _rep=reporter, _eng=engine: (
                    _rep.set_slo(_eng.last_block())))
            recorder.start()
        ob = SweepObserver(tracer=tracer, run_span=run_span,
                           reporter=reporter, owns_tracer=owns,
                           mem_sampler=sampler, device_capture=capture,
                           ts_recorder=recorder)
    except Exception:  # noqa: BLE001 — observability must never block a sweep
        yield SweepObserver()
        return
    try:
        yield ob
    except BaseException as e:
        ob.close(error=e)
        raise
    else:
        ob.close()
