"""Live sweep progress: a heartbeat-rewritten ``_progress.json``.

A stalled remote sweep used to be diagnosable only by attaching to the host
or waiting for the run to (not) finish.  The reporter makes the current state
one ``cat`` away: a daemon thread atomically rewrites
``<output_dir>/_progress.json`` every few seconds with the current word and
phase, words done/total, an ETA from a completed-word EMA, the age of the
last telemetry event, and the heartbeat's own timestamp — so both "which word
is it on" and "is it even alive" are answerable without attaching.

Staleness has two distinct signals, deliberately:

- ``updated_at`` older than ~2 heartbeat intervals → the PROCESS is gone or
  wedged (the heartbeat thread itself stopped).
- ``last_event_age_seconds`` large while ``updated_at`` is fresh → the
  process is alive but the PIPELINE has gone quiet (a hung checkpoint read,
  a compile that never returns) — exactly the "where did the time go" case
  the span stream then answers.

Everything is fail-open and stdlib-only; the file is written via the shared
atomic tmp+rename so readers never see a torn JSON.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

from taboo_brittleness_tpu.runtime.resilience import (
    atomic_json_dump, current_incarnation, current_worker_id)

PROGRESS_FILENAME = "_progress.json"

#: EMA weight for completed-word seconds: ~last 6 words dominate, so the ETA
#: tracks drift (later checkpoints decoding longer responses) without one
#: outlier word whipsawing it.
_EMA_ALPHA = 0.3


def heartbeat_interval() -> float:
    try:
        return max(0.2, float(os.environ.get("TBX_OBS_PROGRESS_S", "5")))
    except ValueError:
        return 5.0


class ProgressReporter:
    """Heartbeat thread + thread-safe state setters.

    Use as a context manager; drivers call :meth:`word_started`,
    :meth:`word_done`, :meth:`word_skipped`, and :meth:`phase` as the sweep
    moves.  ``tracer`` (optional) supplies ``last_event_age_seconds``;
    ``clock`` is injectable so tests drive time instead of sleeping."""

    def __init__(self, path: str, *, total_words: int,
                 run_id: Optional[str] = None,
                 tracer=None,
                 interval: Optional[float] = None,
                 min_write_interval: float = 0.5,
                 clock=time.monotonic):
        self.path = path
        self.run_id = run_id
        self.tracer = tracer
        self.interval = heartbeat_interval() if interval is None else interval
        # Word/phase transitions write through only this often; faster
        # transitions (memoized words resolving in ms) just update in-memory
        # state and let the heartbeat flush — progress IO must stay
        # noise-level even when the sweep itself is fast.
        self.min_write_interval = min_write_interval
        self._clock = clock
        self._last_write: Optional[float] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._state: Dict[str, Any] = {
            "current_word": None,
            "phase": None,
            "words_done": 0,
            "words_total": total_words,
            "words_quarantined": 0,
            "status": "running",
        }
        self._word_t0: Optional[float] = None
        self._ema: Optional[float] = None
        self._serving: Optional[Dict[str, Any]] = None
        self._serving_latency: Optional[Dict[str, Any]] = None
        self._serving_slots: Optional[Dict[str, Any]] = None
        self._slo: Optional[Dict[str, Any]] = None
        self._last_step_mono: Optional[float] = None

    # -- state setters (all thread-safe, all fail-open at the write) -------

    def word_started(self, word: str) -> None:
        with self._lock:
            self._state["current_word"] = word
            self._state["phase"] = None
            self._word_t0 = self._clock()
        self._write_throttled()

    def phase(self, name: Optional[str]) -> None:
        with self._lock:
            self._state["phase"] = name

    def word_done(self, word: str, *, seconds: Optional[float] = None) -> None:
        with self._lock:
            if seconds is None and self._word_t0 is not None:
                seconds = self._clock() - self._word_t0
            self._word_t0 = None
            self._state["words_done"] += 1
            if seconds is not None:
                self._ema = (seconds if self._ema is None
                             else _EMA_ALPHA * seconds
                             + (1.0 - _EMA_ALPHA) * self._ema)
        self._write_throttled()

    def word_skipped(self, word: str) -> None:
        """A resumed word: counts toward done but not toward the EMA (a
        skip costs milliseconds and would poison the ETA)."""
        with self._lock:
            self._state["words_done"] += 1
        self._write_throttled()

    def word_quarantined(self, word: str) -> None:
        with self._lock:
            self._state["words_quarantined"] += 1
            self._word_t0 = None
        self._write_throttled()

    def serving_update(self, *, in_flight: int, completed: int,
                       queued: int = 0, stepped: bool = False,
                       latency: Optional[Dict[str, Any]] = None,
                       slo: Optional[Dict[str, Any]] = None,
                       slots: Optional[Dict[str, Any]] = None) -> None:
        """Serving-mode heartbeat state (``tbx serve``; ISSUE 6 satellite).

        The word-sweep staleness classifier assumes word-boundary progress —
        a long-lived server that is healthy but IDLE emits no events, which
        the two-signal rule would misread as "pipeline wedged".  Serving
        mode publishes what liveness actually means for a server: the
        in-flight session count, the completed-request counter, and the age
        of the last decode step (``stepped=True`` marks one).  The
        supervisor's wedge classifier (``runtime.supervise._wedge_reason``)
        keys off ``workload == "serve"``: idle-but-alive is healthy by
        heartbeat alone; only in-flight sessions with a stalled step clock
        wedge.

        ``latency`` (ISSUE 7/15 satellites) carries the per-scenario
        percentiles from ``SlotScheduler.latency_percentiles``: WINDOWED
        p50/p99 (the window-forked reservoirs, stamped with ``window_s`` and
        per-window sample counts) next to the honestly-labeled cumulative
        view.  The last non-None value persists across heartbeats (the
        scheduler only recomputes it when requests complete).

        ``slo`` (ISSUE 15) is the burn-rate block from ``obs.slo.SloEngine``
        — ``{series: {burn, fast, slow, ok}}`` — refreshed each timeseries
        window; it rides the heartbeat so a supervisor or replica router can
        admit on it without parsing the spool.

        ``slots`` (ISSUE 18) is the occupancy block — ``{width, active,
        free, verdict}``, where ``width`` is the HBM-watermark autotuner's
        solved admission cap (``serve.autotune``) and ``verdict`` how it
        was reached — so the replica router can weight placement by free
        slots and shed when every replica reports ``free == 0``.  Like
        ``latency``, the last non-None block persists across heartbeats."""
        now = self._clock()
        with self._lock:
            prev_in_flight = (int(self._serving.get("in_flight", 0))
                              if self._serving else 0)
            # The step clock restarts when work ARRIVES (0 -> >0), not just
            # when a step completes: the serve loop publishes in-flight
            # before stepping so a step that wedges is visible, and an
            # idle-for-hours server must not read as instantly wedged the
            # moment its first request lands.
            if (stepped or self._last_step_mono is None
                    or (in_flight > 0 and prev_in_flight == 0)):
                self._last_step_mono = now
            if latency is not None:
                self._serving_latency = latency
            if slo is not None:
                self._slo = slo
            if slots is not None:
                self._serving_slots = dict(slots)
            self._serving = {
                "in_flight": int(in_flight),
                "completed_requests": int(completed),
                "queued": int(queued),
            }
        self._write_throttled()

    def set_slo(self, block: Optional[Dict[str, Any]]) -> None:
        """Update the heartbeat's ``slo`` block outside a serving update
        (sweep/fleet mode, where the timeseries recorder drives it)."""
        if block is None:
            return
        with self._lock:
            self._slo = dict(block)

    def finish(self, status: str = "done") -> None:
        with self._lock:
            self._state["status"] = status
            self._state["current_word"] = None
            self._state["phase"] = None
        self.write_now()

    # -- snapshot / write --------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            state = dict(self._state)
            ema = self._ema
            word_t0 = self._word_t0
            serving = dict(self._serving) if self._serving else None
            serving_latency = (dict(self._serving_latency)
                               if self._serving_latency else None)
            serving_slots = (dict(self._serving_slots)
                             if self._serving_slots else None)
            slo = dict(self._slo) if self._slo else None
            last_step = self._last_step_mono
        remaining = max(
            0, state["words_total"] - state["words_done"]
            - state["words_quarantined"])
        eta = None
        if ema is not None:
            eta = ema * remaining
            if word_t0 is not None and remaining > 0:
                # Credit the in-flight word's elapsed time against its slot.
                eta -= min(ema, max(0.0, self._clock() - word_t0))
        out = {
            "v": 1,
            "run_id": self.run_id,
            "pid": os.getpid(),
            # Supervised-run ordinal (0 standalone): the supervisor matches
            # this + pid so a predecessor's stale file never reads as the
            # fresh child being wedged.
            "incarnation": current_incarnation(),
            # Fleet worker identity (runtime.fleet; None standalone) — the
            # per-worker supervisor watches _progress.<worker_id>.json.
            **({"worker": current_worker_id()}
               if current_worker_id() else {}),
            # Epoch timestamp: the reader computes staleness as now - this.
            # tbx: wallclock-ok — heartbeat freshness mark, not duration math
            "updated_at": time.time(),
            "heartbeat_seconds": self.interval,
            **state,
            "word_seconds_ema": round(ema, 3) if ema is not None else None,
            "eta_seconds": round(eta, 1) if eta is not None else None,
        }
        if serving is not None:
            out["workload"] = "serve"
            if last_step is not None:
                serving["last_step_age_seconds"] = round(
                    max(0.0, self._clock() - last_step), 3)
            if serving_latency:
                serving["latency"] = serving_latency
            if serving_slots:
                serving["slots"] = serving_slots
            out["serving"] = serving
        if slo:
            out["slo"] = slo
        if self.tracer is not None:
            try:
                out["last_event_age_seconds"] = round(
                    self.tracer.last_event_age(), 3)
            except Exception:  # noqa: BLE001
                pass
        return out

    def write_now(self) -> None:
        try:
            atomic_json_dump(self.snapshot(), self.path)
            # Both the heartbeat thread and the main-side setters land here;
            # the throttle mark has to be read/written under the lock.
            with self._lock:
                self._last_write = self._clock()
        except Exception:  # noqa: BLE001 — progress must never kill the sweep
            pass

    def _write_throttled(self) -> None:
        with self._lock:
            last = self._last_write
        if last is None or self._clock() - last >= self.min_write_interval:
            self.write_now()

    # -- heartbeat thread --------------------------------------------------

    def start(self) -> "ProgressReporter":
        if self._thread is None:
            self.write_now()
            self._thread = threading.Thread(
                target=self._run, name="tbx-obs-progress", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.write_now()
            # Keep the event sink at most a heartbeat stale too (the tracer
            # buffers writes): a wedged pipeline's last events reach disk
            # even though nothing is emitting.
            flush = getattr(self.tracer, "flush", None)
            if flush is not None:
                try:
                    flush()
                except Exception:  # noqa: BLE001
                    pass

    def stop(self, *, status: str = "done") -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        self.finish(status)

    def __enter__(self) -> "ProgressReporter":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop(status="error" if exc_type is not None else "done")


def read_progress(path: str, *,
                  stale_after: Optional[float] = None,
                  missing_ok: bool = False) -> Dict[str, Any]:
    """Load a progress file and derive liveness:

    - ``age_seconds``: now - updated_at (wall clock; the writer may be
      another host, so monotonic cannot apply here).
    - ``stale``: age > ``stale_after`` (default: 3x the file's own heartbeat
      interval) — the process is presumed dead or wedged.

    ``missing_ok=True`` turns a missing/unreadable file into
    ``{"status": "absent", "stale": False}`` instead of raising: before the
    first heartbeat lands there is nothing to read, and a watcher (the
    supervisor, a remote poll loop) must not need a try/except racing the
    child's startup.
    """
    import json

    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        if missing_ok:
            return {"status": "absent", "stale": False}
        raise
    # tbx: wallclock-ok — cross-process freshness check needs the epoch clock
    age = max(0.0, time.time() - float(data.get("updated_at", 0)))
    threshold = (stale_after if stale_after is not None
                 else 3.0 * float(data.get("heartbeat_seconds", 5.0)))
    data["age_seconds"] = round(age, 3)
    data["stale"] = bool(age > threshold and data.get("status") == "running")
    return data
