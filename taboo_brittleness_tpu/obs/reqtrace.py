"""End-to-end request tracing: context propagation + per-request waterfalls.

The serving stack spans six processes per request — loadgen → spool → fleet
router → replica claim → scheduler/engine step → first-writer-wins commit,
with lease-expiry re-spools to a *different* replica on death — and every
telemetry stream is process-scoped.  This module is the request-centric
join:

**Trace context** (``CTX_KEY`` in the request JSON): a compact dict minted
once at submit (``RequestSpool.put`` / ``loadgen.build_schedule``) and
carried inside the request payload through assigned-routing, claim-by-
rename, lease re-spool and speculative duplicate dispatch, then stamped
into the Response and the ``responses/`` file — one request is ONE trace
across replica death::

    {"v": 1, "trace_id": "<16 hex>", "parent": <minting span id or None>,
     "attempt": 0}
    # + "synthetic": true    when minted at claim for a pre-trace payload
    # + "dead": ["<holder>"] holders whose lease expired (re-spool chain)

Versioning: ``v`` is CTX_VERSION.  Readers accept their own version and
older; unknown versions parse as *absent* (the legacy-payload path: a
synthetic context is minted at claim with a one-shot ``obs.warn``) so a
mid-upgrade spool keeps serving.

**Lifecycle spans**: the scheduler opens one ``kind="request"`` span per
(request, attempt) — ``serve.request``, off the per-thread stack
(``Tracer.span_detached``) because in-flight requests interleave — with a
``serve.first_token`` point marking TTFT (submit → first emitted token on
the serving attempt).  A replica killed mid-decode leaves the span
dangling; the fleet merge closes it with a synthesized ``status="error"``
end, which is exactly the first-attempt closure the waterfall renders.

**Exemplars**: completions register their trace_id per SLO series (capped
at ``TBX_TRACE_EXEMPLARS``, worst-latency-first); the SLO engine drains
them into each burn window's cells so ``tbx top`` and flightrec dumps link
a burning series straight to offending traces, resolvable by ``tbx trace``.

**Assembler / CLI**::

    tbx trace <results_dir>                  # slowest-10 waterfalls
    tbx trace <results_dir> --request RID    # one request's attempt chain
    tbx trace <results_dir> --trace TID      # resolve an exemplar trace_id
    tbx trace <results_dir> --slowest N
    tbx trace --selfcheck                    # fixture gate (tools/check.sh)

stdlib-only and fail-open like the rest of obs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

from taboo_brittleness_tpu.obs import trace as trace_mod

#: Request-payload key the context rides under.
CTX_KEY = "trace"

#: Bumped whenever the context gains/renames a REQUIRED key; readers accept
#: their own version and older, and treat newer as absent (synthetic mint).
CTX_VERSION = 1

#: Span/point names the scheduler emits (the assembler + checker key off
#: these).
REQUEST_SPAN = "serve.request"
FIRST_TOKEN_POINT = "serve.first_token"


# ---------------------------------------------------------------------------
# Context mint / parse / propagation.
# ---------------------------------------------------------------------------

def mint(*, attempt: int = 0, synthetic: bool = False) -> Dict[str, Any]:
    """A fresh trace context.  ``parent`` records the minting process's
    current span id (the loadgen/bench span submitting the request) purely
    as provenance — lifecycle spans parent under the SERVING process's run
    span."""
    t = trace_mod.get_tracer()
    cur = t.current_span() if t is not None else None
    ctx: Dict[str, Any] = {
        "v": CTX_VERSION,
        "trace_id": uuid.uuid4().hex[:16],
        "parent": cur.span_id if cur is not None else None,
        "attempt": int(attempt),
    }
    if synthetic:
        ctx["synthetic"] = True
    return ctx


def parse(payload: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The validated context carried by a request payload, or None (absent,
    malformed, or minted by a NEWER writer than this reader understands)."""
    if not isinstance(payload, dict):
        return None
    ctx = payload.get(CTX_KEY)
    if not isinstance(ctx, dict):
        return None
    try:
        if int(ctx.get("v", 0)) > CTX_VERSION:
            return None
        tid = str(ctx.get("trace_id", ""))
        if not tid:
            return None
        return {
            "v": int(ctx.get("v", CTX_VERSION)),
            "trace_id": tid,
            "parent": ctx.get("parent"),
            "attempt": int(ctx.get("attempt", 0)),
            **({"synthetic": True} if ctx.get("synthetic") else {}),
            **({"dead": list(ctx.get("dead", ()))} if ctx.get("dead") else {}),
        }
    except (TypeError, ValueError):
        return None


def ensure(payload: Dict[str, Any], *,
           synthetic: bool = False) -> Tuple[Dict[str, Any], Dict[str, Any],
                                             bool]:
    """(payload-with-context, context, minted?) — attach a context when the
    payload carries none (``synthetic=True`` marks a claim-time mint for a
    pre-trace/legacy payload)."""
    ctx = parse(payload)
    if ctx is not None:
        return payload, ctx, False
    ctx = mint(attempt=0, synthetic=synthetic)
    return {**payload, CTX_KEY: ctx}, ctx, True


#: HTTP header the gateway reads/propagates the context from (ISSUE 20).
#: Format is traceparent-style: ``<2-hex version>-<16..32 hex trace id>-
#: <16 hex parent span id or zeros>-<2 hex flags>``; the W3C field layout,
#: our 16-hex trace ids.
TRACE_HEADER = "x-tbx-trace"

_HEX = frozenset("0123456789abcdef")


def parse_header(value: Optional[str]) -> Optional[Dict[str, Any]]:
    """A trace context from a traceparent-style HTTP header, or None for a
    missing/malformed header (the caller re-mints with the one-shot warn —
    ``ensure_from_header``).  Longer (W3C 32-hex) trace ids are accepted
    and truncated to this repo's 16-hex form."""
    if not value or not isinstance(value, str):
        return None
    parts = value.strip().lower().split("-")
    if len(parts) != 4:
        return None
    ver, tid, parent, _flags = parts
    if len(ver) != 2 or not set(ver) <= _HEX:
        return None
    if not (16 <= len(tid) <= 32) or not set(tid) <= _HEX:
        return None
    if set(tid) == {"0"}:
        return None
    if len(parent) != 16 or not set(parent) <= _HEX:
        return None
    return {
        "v": CTX_VERSION,
        "trace_id": tid[:16],
        "parent": None if set(parent) == {"0"} else parent,
        "attempt": 0,
    }


def format_header(ctx: Dict[str, Any]) -> str:
    """The wire form of a context — what a socket client (``tbx loadgen
    --socket``) sends so its pre-minted trace survives the HTTP hop."""
    parent = str(ctx.get("parent") or "").lower()
    if len(parent) != 16 or not set(parent) <= _HEX:
        parent = "0" * 16
    return f"00-{ctx['trace_id']}-{parent}-01"


def ensure_from_header(payload: Dict[str, Any],
                       header: Optional[str]) -> Tuple[Dict[str, Any],
                                                       Dict[str, Any], bool]:
    """(payload-with-context, context, minted?) for a request arriving over
    HTTP: a valid header's context rides into the spool payload (the
    waterfall spans the socket hop); an absent or malformed header mints a
    fresh context HERE at the gateway — the trace's birthplace moves to the
    edge.  A context already in the payload body wins over the header
    (explicit beats transport)."""
    ctx = parse(payload)
    if ctx is not None:
        return payload, ctx, False
    ctx = parse_header(header)
    if ctx is not None:
        return {**payload, CTX_KEY: ctx}, ctx, False
    ctx = mint(attempt=0)
    return {**payload, CTX_KEY: ctx}, ctx, True


def for_attempt(ctx: Dict[str, Any], attempt: int,
                *, dead_holder: Optional[str] = None) -> Dict[str, Any]:
    """The re-spool child context: SAME trace_id, bumped attempt, the dead
    holder recorded — a retry child span under the same trace, never a new
    trace."""
    nxt = dict(ctx)
    nxt["attempt"] = int(attempt)
    if dead_holder:
        nxt["dead"] = sorted(set(nxt.get("dead", ())) | {str(dead_holder)})
    return nxt


# ---------------------------------------------------------------------------
# Exemplar registry (SLO burn window → trace_id join).
# ---------------------------------------------------------------------------

_EX_LOCK = threading.Lock()
#: metric name -> [(value, trace_id)] kept worst-first, capped at the knob.
_EX_CURRENT: Dict[str, List[Tuple[float, str]]] = {}
#: metric name -> the most recently drained window's trace ids (what a
#: flightrec dump attaches when the SLO engine already consumed the window).
_EX_LAST: Dict[str, List[str]] = {}


def exemplar_cap() -> int:
    """Exemplars kept per series per window (``TBX_TRACE_EXEMPLARS``,
    default 3; 0 disables the registry)."""
    try:
        return max(0, int(os.environ.get("TBX_TRACE_EXEMPLARS", "3")))
    except ValueError:
        return 3


def note_exemplar(metric: str, trace_id: Optional[str],
                  value: float) -> None:
    """Register one observation's trace_id against a histogram series.
    Keeps the K WORST (largest) values in the current window — the traces
    an operator chasing a burning latency SLO actually wants."""
    cap = exemplar_cap()
    if not trace_id or cap <= 0:
        return
    try:
        v = float(value)
    except (TypeError, ValueError):
        return
    with _EX_LOCK:
        cur = _EX_CURRENT.setdefault(metric, [])
        cur.append((v, str(trace_id)))
        cur.sort(key=lambda p: -p[0])
        del cur[cap:]


def take_exemplars(metric: str) -> List[str]:
    """Drain the current window's exemplars for one series (the SLO engine,
    once per observe_window) — worst-first trace ids."""
    with _EX_LOCK:
        cur = _EX_CURRENT.pop(metric, None)
        if not cur:
            return []
        ids = [tid for _v, tid in cur]
        _EX_LAST[metric] = ids
        return ids


def peek_exemplars() -> Dict[str, List[str]]:
    """Non-draining snapshot across every series: the current window's
    exemplars merged over the last drained window's (flightrec dumps fire
    between windows, so either alone can be empty)."""
    with _EX_LOCK:
        out: Dict[str, List[str]] = {}
        for metric, ids in _EX_LAST.items():
            out[metric] = list(ids)
        for metric, cur in _EX_CURRENT.items():
            seen = out.setdefault(metric, [])
            for _v, tid in cur:
                if tid not in seen:
                    seen.append(tid)
        return {k: v[:max(1, exemplar_cap())] for k, v in out.items() if v}


def reset_exemplars() -> None:
    """Tests only: drop all registered exemplars."""
    with _EX_LOCK:
        _EX_CURRENT.clear()
        _EX_LAST.clear()


# ---------------------------------------------------------------------------
# Causal assembler: merged + per-worker event streams → per-request
# waterfalls.
# ---------------------------------------------------------------------------

#: Coordinator point events joined into a trace by their ``request`` attr.
#: The gateway.* points (ISSUE 20) extend the waterfall across the socket
#: hop: accept → spooled → stream start/done (or shed/cancel) bracket the
#: replica-side lifecycle.
_COORD_POINTS = ("serve_fleet.route", "serve_fleet.respool",
                 "serve_fleet.reroute", "serve_fleet.lease_expired",
                 "serve_fleet.shed", "serve.respond", "serve.claim",
                 "gateway.accept", "gateway.shed", "gateway.cancel",
                 "gateway.stream_done")


def find_event_files(path: str) -> List[str]:
    """Event streams for one results dir (or a direct ``_events.jsonl``
    path).  A merged ``_events.jsonl`` already contains every per-worker
    stream (the fleet merge folds and renumbers them), so it is preferred
    alone; otherwise the per-worker ``_events.<wid>.jsonl`` files are read
    together."""
    if os.path.isfile(path):
        return [path]
    merged = os.path.join(path, trace_mod.EVENTS_FILENAME)
    if os.path.exists(merged):
        return [merged]
    try:
        names = sorted(os.listdir(path))
    except OSError:
        return []
    return [os.path.join(path, n) for n in names
            if n.startswith("_events.") and n.endswith(".jsonl")]


class Attempt:
    """One (request, attempt) lifecycle span plus its parented points."""

    __slots__ = ("request", "number", "worker", "span_id", "t0", "dur",
                 "status", "error", "attrs", "first_token", "synthesized")

    def __init__(self, ev: Dict[str, Any]):
        attrs = ev.get("attrs") or {}
        self.request = str(attrs.get("request", ""))
        self.number = int(attrs.get("attempt", 0) or 0)
        self.worker = ev.get("worker")
        self.span_id = ev.get("id")
        self.t0 = float(ev.get("t", 0.0))
        self.dur: Optional[float] = None
        self.status: Optional[str] = None
        self.error: Optional[str] = None
        self.attrs: Dict[str, Any] = dict(attrs)
        self.first_token: Optional[Dict[str, Any]] = None
        self.synthesized = False

    @property
    def terminal(self) -> bool:
        return bool(self.attrs.get("terminal"))

    @property
    def latency(self) -> Optional[float]:
        v = self.attrs.get("latency_seconds")
        try:
            return float(v) if v is not None else None
        except (TypeError, ValueError):
            return None


class RequestTrace:
    """Every attempt + coordinator point sharing one trace_id."""

    __slots__ = ("trace_id", "request", "attempts", "coord")

    def __init__(self, trace_id: str, request: str):
        self.trace_id = trace_id
        self.request = request
        self.attempts: List[Attempt] = []
        self.coord: List[Dict[str, Any]] = []

    @property
    def terminal_attempt(self) -> Optional[Attempt]:
        done = [a for a in self.attempts if a.terminal and a.dur is not None]
        # Worst case a duplicate dispatch double-terminates; prefer the ok
        # one (the first-writer-wins winner is not knowable span-side).
        done.sort(key=lambda a: (a.status != "ok", a.number))
        return done[0] if done else None

    @property
    def latency(self) -> Optional[float]:
        a = self.terminal_attempt
        return a.latency if a is not None else None

    @property
    def ttft(self) -> Optional[float]:
        a = self.terminal_attempt
        if a is None:
            return None
        v = a.attrs.get("ttft_seconds")
        try:
            return float(v) if v is not None else None
        except (TypeError, ValueError):
            return None


def assemble(paths: Sequence[str]) -> Dict[str, RequestTrace]:
    """trace_id → :class:`RequestTrace` over one or more event streams.

    Request-kind spans carry their trace context as attrs; coordinator
    points (route / respool / lease_expired / shed / respond / claim) join
    by their ``request`` attr — via the request→trace map the spans
    establish, so a trace survives streams whose points predate the span
    (claim fires before submit)."""
    traces: Dict[str, RequestTrace] = {}
    by_request: Dict[str, str] = {}
    attempts_by_span: Dict[Tuple[str, Any], Attempt] = {}
    pending_points: List[Tuple[str, Dict[str, Any]]] = []
    for path in paths:
        stream = os.path.basename(path)
        try:
            events = list(trace_mod.iter_events(path))
        except OSError:
            continue
        for ev in events:
            kind, name = ev.get("kind"), str(ev.get("name", ""))
            if kind == "request" and name == REQUEST_SPAN:
                if ev.get("ev") == "start":
                    a = Attempt(ev)
                    tid = str(a.attrs.get("trace", "")) or a.request
                    if not a.request:
                        continue
                    tr = traces.get(tid)
                    if tr is None:
                        tr = traces[tid] = RequestTrace(tid, a.request)
                    by_request.setdefault(a.request, tid)
                    tr.attempts.append(a)
                    attempts_by_span[(stream, ev.get("id"))] = a
                elif ev.get("ev") == "end":
                    a = attempts_by_span.get((stream, ev.get("id")))
                    if a is None:
                        continue
                    a.dur = float(ev.get("dur", 0.0) or 0.0)
                    a.status = ev.get("status")
                    a.error = ev.get("error")
                    a.attrs.update(ev.get("attrs") or {})
                    a.synthesized = bool(
                        (ev.get("attrs") or {}).get("synthesized"))
            elif ev.get("ev") == "point":
                if name == FIRST_TOKEN_POINT:
                    a = attempts_by_span.get((stream, ev.get("parent")))
                    if a is not None:
                        a.first_token = ev
                elif name in _COORD_POINTS:
                    req = str((ev.get("attrs") or {}).get("request", ""))
                    if req:
                        pending_points.append((req, ev))
    for req, ev in pending_points:
        tid = by_request.get(req)
        if tid is None:
            # Routed/shed but never admitted anywhere (or the admitting
            # replica died before its span start flushed): the request is
            # still a trace, anchored by its coordinator points alone.
            tid = by_request[req] = f"(request {req})"
            traces[tid] = RequestTrace(tid, req)
        traces[tid].coord.append(ev)
    for tr in traces.values():
        tr.attempts.sort(key=lambda a: (a.number, a.t0))
        tr.coord.sort(key=lambda ev: (float(ev.get("t", 0.0)),
                                      int(ev.get("seq", 0))))
    return traces


def _fmt_s(v: Optional[float]) -> str:
    return "-" if v is None else f"{v:.3f}s"


def _critical_path(a: Attempt) -> List[Tuple[str, float]]:
    """(segment, seconds) decomposition of the terminal attempt: queue wait
    → prefill+first decode step (TTFT minus queue) → decode tail.  The
    waterfall's critical-path attribution — largest segment first."""
    try:
        queue = float(a.attrs.get("queue_seconds", 0.0) or 0.0)
        latency = float(a.attrs.get("latency_seconds", 0.0) or 0.0)
    except (TypeError, ValueError):
        return []
    segs: List[Tuple[str, float]] = []
    ttft = a.attrs.get("ttft_seconds")
    try:
        ttft = float(ttft) if ttft is not None else None
    except (TypeError, ValueError):
        ttft = None
    if ttft is not None and latency >= ttft >= queue:
        segs = [("queue", queue), ("prefill+first-token", ttft - queue),
                ("decode-tail", latency - ttft)]
    elif latency >= queue:
        segs = [("queue", queue), ("decode", latency - queue)]
    return sorted(segs, key=lambda s: -s[1])


def render(tr: RequestTrace) -> str:
    """One trace's waterfall: coordinator hops, per-attempt lifecycle with
    TTFT, and critical-path attribution.  Times are per-stream monotonic
    (each process's clock starts at its own zero) — offsets within one
    attempt are exact; cross-process rows are ordered, not aligned."""
    term = tr.terminal_attempt
    head = (f"trace {tr.trace_id}  request {tr.request}"
            f"  attempts {len(tr.attempts)}")
    if term is not None:
        head += (f"  status {term.status}"
                 f"  finish {term.attrs.get('finish', '?')}"
                 f"  latency {_fmt_s(term.latency)}"
                 f"  ttft {_fmt_s(tr.ttft)}")
    elif tr.attempts:
        head += "  status open"
    lines = [head]
    for ev in tr.coord:
        attrs = ev.get("attrs") or {}
        who = ev.get("worker") or "coord"
        brief = ", ".join(
            f"{k}={attrs[k]}" for k in ("worker", "attempt", "holder",
                                        "reason", "duplicate", "synthetic")
            if k in attrs)
        lines.append(f"  [{who}] t={float(ev.get('t', 0.0)):.3f}"
                     f"  {ev.get('name')}  {brief}")
    for a in tr.attempts:
        who = a.worker or "?"
        if a.dur is None:
            lines.append(f"  attempt {a.number} @{who}: OPEN "
                         "(span never ended — live or lost stream)")
            continue
        if a.synthesized:
            lines.append(
                f"  attempt {a.number} @{who}: DIED mid-flight after "
                f"{a.dur:.3f}s (closed by fleet merge, synthesized error)")
            continue
        seg = (f"queue {_fmt_s(a.attrs.get('queue_seconds'))}"
               if a.attrs.get("queue_seconds") is not None else "")
        ft = (f"  ttft {_fmt_s(a.attrs.get('ttft_seconds'))}"
              if a.attrs.get("ttft_seconds") is not None else "")
        err = f"  error {a.error}" if a.error else ""
        lines.append(
            f"  attempt {a.number} @{who}: {a.status}"
            f"  finish {a.attrs.get('finish', '?')}  {seg}{ft}"
            f"  total {_fmt_s(a.latency)}  steps {a.attrs.get('steps', '?')}"
            f"{err}")
        if a.terminal:
            segs = _critical_path(a)
            total = sum(s for _n, s in segs) or None
            if segs and total:
                lines.append("    critical path: " + ", ".join(
                    f"{n} {s / total:.0%} ({s:.3f}s)" for n, s in segs))
    return "\n".join(lines)


def slowest(traces: Dict[str, RequestTrace], n: int) -> List[RequestTrace]:
    done = [t for t in traces.values() if t.latency is not None]
    done.sort(key=lambda t: -(t.latency or 0.0))
    return done[:max(0, n)]


# ---------------------------------------------------------------------------
# CLI (`tbx trace`) + the fixture selfcheck tools/check.sh gates.
# ---------------------------------------------------------------------------

def default_fixture_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))),
        "tests", "fixtures", "obs", "serve_fleet")


def selfcheck(fixture_dir: Optional[str] = None) -> int:
    """Render the committed serve-fleet fixture's slowest-5 waterfalls and
    assert the request-trace invariants parse end-to-end: every terminal
    attempt chain is attempt-ordered under ONE trace_id, and every ok
    terminal attempt that emitted tokens carries a parseable TTFT."""
    d = fixture_dir or default_fixture_dir()
    paths = find_event_files(d)
    if not paths:
        print(f"tbx trace --selfcheck: no event streams under {d}",  # tbx: TBX009-ok — CLI stderr contract (selfcheck failure)
              file=sys.stderr)
        return 1
    traces = assemble(paths)
    errors: List[str] = []
    with_spans = {t.request: t for t in traces.values() if t.attempts}
    if not with_spans:
        errors.append(f"{d}: no request-kind spans in the fixture — "
                      "regenerate it via tools/make_fleet_fixture.py")
    for tr in with_spans.values():
        tids = {str(a.attrs.get("trace", "")) for a in tr.attempts}
        if len(tids) > 1:
            errors.append(f"request {tr.request}: attempts span multiple "
                          f"trace ids {sorted(tids)}")
        nums = [a.number for a in tr.attempts]
        if nums != sorted(nums):
            errors.append(f"request {tr.request}: attempt chain out of "
                          f"order: {nums}")
        term = tr.terminal_attempt
        if term is None:
            continue
        emitted = term.attrs.get("emitted", term.attrs.get("steps", 0))
        if term.status == "ok" and emitted:
            if tr.ttft is None:
                errors.append(f"request {tr.request}: completed decode "
                              "without a parseable ttft_seconds")
            elif term.first_token is None and len(paths) == 1:
                errors.append(f"request {tr.request}: ttft attr present "
                              f"but no {FIRST_TOKEN_POINT} point parented "
                              "to the terminal span")
    for tr in slowest(traces, 5):
        print(render(tr))  # tbx: TBX009-ok — CLI stdout contract (waterfall render)
        print()  # tbx: TBX009-ok — CLI stdout contract (waterfall separator)
    if errors:
        for e in errors:
            print(f"tbx trace --selfcheck: {e}", file=sys.stderr)  # tbx: TBX009-ok — CLI stderr contract (selfcheck violations)
        return 1
    n_term = sum(1 for t in traces.values()
                 if t.terminal_attempt is not None)
    print(f"tbx trace --selfcheck: OK ({len(traces)} traces, "  # tbx: TBX009-ok — CLI stdout contract (selfcheck verdict)
          f"{n_term} terminal, {len(paths)} stream(s))")
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tbx trace",
        description="Per-request waterfalls from a serve run's event "
                    "streams: attempt chains across replica death, TTFT, "
                    "critical-path attribution.")
    ap.add_argument("dir", nargs="?",
                    help="results dir (or a direct _events.jsonl path)")
    ap.add_argument("--request", default=None, metavar="RID",
                    help="render one request id's trace")
    ap.add_argument("--trace", default=None, metavar="TID",
                    help="render one trace_id (e.g. a tbx top exemplar)")
    ap.add_argument("--slowest", type=int, default=10, metavar="N",
                    help="render the N slowest completed traces (default)")
    ap.add_argument("--selfcheck", action="store_true",
                    help="gate the committed serve_fleet fixture "
                         "(tools/check.sh)")
    args = ap.parse_args(argv)
    if args.selfcheck:
        return selfcheck(args.dir)
    if not args.dir:
        ap.error("a results dir is required (or --selfcheck)")
    paths = find_event_files(args.dir)
    if not paths:
        print(f"tbx trace: no _events*.jsonl under {args.dir}",  # tbx: TBX009-ok — CLI stderr contract (missing input)
              file=sys.stderr)
        return 2
    traces = assemble(paths)
    if args.trace is not None:
        tr = traces.get(args.trace)
        if tr is None:
            print(f"tbx trace: trace {args.trace!r} not found "  # tbx: TBX009-ok — CLI stderr contract (lookup miss)
                  f"({len(traces)} traces in {len(paths)} stream(s))",
                  file=sys.stderr)
            return 1
        print(render(tr))  # tbx: TBX009-ok — CLI stdout contract (waterfall render)
        return 0
    if args.request is not None:
        hits = [t for t in traces.values() if t.request == args.request]
        if not hits:
            print(f"tbx trace: request {args.request!r} not found",  # tbx: TBX009-ok — CLI stderr contract (lookup miss)
                  file=sys.stderr)
            return 1
        for tr in hits:
            print(render(tr))  # tbx: TBX009-ok — CLI stdout contract (waterfall render)
        return 0
    picked = slowest(traces, args.slowest)
    if not picked:
        print(f"tbx trace: no completed request traces in {args.dir} "  # tbx: TBX009-ok — CLI stderr contract (empty result)
              f"({len(traces)} open/route-only)", file=sys.stderr)
        return 1
    for tr in picked:
        print(render(tr))  # tbx: TBX009-ok — CLI stdout contract (waterfall render)
        print()  # tbx: TBX009-ok — CLI stdout contract (waterfall separator)
    return 0


if __name__ == "__main__":
    sys.exit(main())
