"""``tbx top`` — a live terminal view of one output directory's telemetry.

Everything the repo's observability stack writes is a file next to the run
(``_progress*.json`` heartbeats, the ``_metrics*.jsonl`` windowed spool,
``_fleet.json``, ``_flightrec*.json``), so "what is the fleet doing right
now" should never require attaching a debugger or a dashboard.  This module
renders those files as a compact text screen:

- one lane per progress heartbeat (the coordinator plus each fleet worker):
  status, current word/phase, done/total, heartbeat age, staleness;
- the serve block when a heartbeat carries ``workload: "serve"``: in-flight
  / completed / queued plus the WINDOWED per-scenario p99 next to the
  honestly-labeled cumulative one;
- the SLO burn table from the latest spool window (``obs.slo``), the
  speculation accept rate from the window's counter deltas, and the HBM
  live/peak/headroom gauges (``obs.memory``);
- spool health: windows seen, drop counters, flight-recorder dumps.

Stdlib-only, read-only, fail-open: a torn tail line or a missing file
renders as absent, never as a crash.  ``--once`` prints one frame and exits
(the CI smoke); the live loop redraws every ``--interval`` seconds until
interrupted.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import time
from typing import Any, Dict, List, Optional

#: How much of a spool file's tail one frame parses (enough for the last
#: few windows of even a metric-heavy run, tiny against a long spool).
_TAIL_BYTES = 256 * 1024


# ---------------------------------------------------------------------------
# Collection: files → one state dict (pure, testable).
# ---------------------------------------------------------------------------


def _read_json(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as f:
            out = json.load(f)
        return out if isinstance(out, dict) else None
    except (OSError, ValueError):
        return None


def _tail_jsonl(path: str, max_bytes: int = _TAIL_BYTES) -> List[Dict[str, Any]]:
    """Parse the last ``max_bytes`` of a JSONL file, skipping the (possibly
    torn) first partial line and any torn tail — the reader's half of the
    whole-line O_APPEND contract."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - max_bytes))
            chunk = f.read()
    except OSError:
        return []
    if size > max_bytes:
        chunk = chunk.split(b"\n", 1)[-1]
    out: List[Dict[str, Any]] = []
    for line in chunk.splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def collect(output_dir: str) -> Dict[str, Any]:
    """One frame's worth of state from ``output_dir`` (see module doc)."""
    from taboo_brittleness_tpu.obs.progress import read_progress

    lanes: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(output_dir,
                                              "_progress*.json"))):
        data = read_progress(path, missing_ok=True)
        if data.get("status") == "absent":
            continue
        base = os.path.basename(path)
        lane = (base[len("_progress."):-len(".json")]
                if base != "_progress.json" else None)
        data["lane"] = data.get("worker") or lane or "main"
        lanes.append(data)

    # Latest window per (worker) lane across every spool file; the merged
    # _metrics.jsonl carries worker-stamped records, per-worker files don't.
    windows: Dict[str, Dict[str, Any]] = {}
    exits: Dict[str, Dict[str, Any]] = {}
    n_windows = 0
    for path in sorted(glob.glob(os.path.join(output_dir,
                                              "_metrics*.jsonl"))):
        base = os.path.basename(path)
        suffix = (base[len("_metrics."):-len(".jsonl")]
                  if base != "_metrics.jsonl" else None)
        for rec in _tail_jsonl(path):
            lane = str(rec.get("worker") or suffix or "main")
            if rec.get("kind") == "window":
                n_windows += 1
                windows[lane] = rec
            elif rec.get("kind") == "exit":
                exits[lane] = rec
    # The frame's headline window: the latest roll anywhere.
    latest = max(windows.values(), key=lambda r: float(r.get("wall", 0.0)),
                 default=None)

    recs = []
    for path in sorted(glob.glob(os.path.join(output_dir,
                                              "_flightrec*.json"))):
        data = _read_json(path)
        if data is not None:
            recs.append({"file": os.path.basename(path),
                         "reason": data.get("reason"),
                         "records": len(data.get("ring") or [])})

    return {
        "dir": output_dir,
        "lanes": lanes,
        "fleet": _read_json(os.path.join(output_dir, "_fleet.json")),
        "serve": _read_json(os.path.join(output_dir, "_serve.json")),
        "serve_fleet": _read_json(os.path.join(output_dir,
                                               "_serve_fleet.json")),
        "gateway": _read_json(os.path.join(output_dir, "_gateway.json")),
        "windows": windows,
        "exits": exits,
        "n_windows": n_windows,
        "latest": latest,
        "flightrec": recs,
    }


# ---------------------------------------------------------------------------
# Rendering: state dict → one text frame (pure, testable).
# ---------------------------------------------------------------------------


def _fmt_bytes(n: Optional[float]) -> str:
    if not n:
        return "-"
    for unit in ("B", "K", "M", "G", "T"):
        if abs(n) < 1024 or unit == "T":
            return (f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}")
        n /= 1024.0
    return "-"


def _fmt_s(v: Optional[float]) -> str:
    return "-" if v is None else f"{float(v):.2f}s"


def _lane_line(lane: Dict[str, Any]) -> str:
    status = str(lane.get("status", "?"))
    if lane.get("stale"):
        status += " STALE"
    bits = [f"  {str(lane.get('lane', '?')):<10} {status:<14}"]
    if lane.get("workload") == "serve":
        sv = lane.get("serving") or {}
        bits.append(f"in-flight {sv.get('in_flight', 0)}  "
                    f"completed {sv.get('completed_requests', 0)}  "
                    f"queued {sv.get('queued', 0)}  "
                    f"step-age {_fmt_s(sv.get('last_step_age_seconds'))}")
        # Occupancy vs the autotuned admission width (ISSUE 18) — the
        # second column the router steers by: active/width (+verdict when
        # the solver changed or abandoned the configured width).
        slots = sv.get("slots") or {}
        if slots:
            occ = f"slots {slots.get('active', 0)}/{slots.get('width', '?')}"
            verdict = str(slots.get("verdict", ""))
            if verdict and verdict not in ("ok", "off"):
                occ += f" ({verdict})"
            bits.append(occ)
        # The burn column the serve-fleet router steers by: the lane's
        # worst fast-window serve burn, straight off its own heartbeat.
        fast = None
        for key, cell in (lane.get("slo") or {}).items():
            if not str(key).startswith("serve"):
                continue
            try:
                val = float((cell or {}).get("fast", 0.0))
            except (TypeError, ValueError):
                continue
            fast = val if fast is None else max(fast, val)
        if fast is not None:
            bits.append(f"burn {fast:.2f}x")
    else:
        word = lane.get("current_word")
        phase = lane.get("phase")
        bits.append(f"{lane.get('words_done', 0)}/"
                    f"{lane.get('words_total', 0)} words")
        if lane.get("words_quarantined"):
            bits.append(f"quarantined {lane['words_quarantined']}")
        if word:
            bits.append(f"word={word}" + (f":{phase}" if phase else ""))
        if lane.get("eta_seconds") is not None:
            bits.append(f"eta {lane['eta_seconds']:.0f}s")
    bits.append(f"beat {lane.get('age_seconds', 0):.1f}s ago")
    return "  ".join(bits)


def _slo_lines(latest: Dict[str, Any]) -> List[str]:
    block = latest.get("slo") or {}
    if not block:
        return []
    out = ["slo burn (x over budget; fast/slow windows):"]
    for key in sorted(block):
        cell = block[key]
        flag = "ok" if cell.get("ok") else "ALERT"
        line = (f"  {key:<28} {cell.get('burn', 0):>8.2f}x  "
                f"fast {cell.get('fast', 0):.2f}  "
                f"slow {cell.get('slow', 0):.2f}  {flag}")
        exemplars = cell.get("exemplars") or []
        if exemplars:
            # Worst trace ids this window — feed them to ``tbx trace
            # <results_dir> --trace <id>`` for the full waterfall.
            line += "  traces: " + ",".join(str(t) for t in exemplars[:3])
        out.append(line)
    return out


def _latency_lines(lanes: List[Dict[str, Any]]) -> List[str]:
    for lane in lanes:
        lat = (lane.get("serving") or {}).get("latency") or {}
        scenarios = lat.get("scenarios") or {}
        if not scenarios:
            continue
        out = [f"serve latency (window {lat.get('window_s', '?')}s | "
               "cumulative):"]
        for name in sorted(scenarios):
            w = scenarios[name].get("window") or {}
            c = scenarios[name].get("cumulative") or {}
            out.append(f"  {name:<20} p99 {_fmt_s(w.get('p99_s')):>8} "
                       f"(n={w.get('n', 0)})  |  "
                       f"p99 {_fmt_s(c.get('p99_s')):>8} "
                       f"(n={c.get('n', 0)})")
        return out
    return []


def _window_extras(latest: Dict[str, Any]) -> List[str]:
    out = []
    counters = latest.get("counters") or {}
    drafted = (counters.get("serve.spec.drafted") or {}).get("delta", 0)
    accepted = (counters.get("serve.spec.accepted") or {}).get("delta", 0)
    if drafted:
        out.append(f"spec accept: {accepted / drafted:.2f} "
                   f"({int(accepted)}/{int(drafted)} this window)")
    gauges = latest.get("gauges") or {}
    live = gauges.get("mem.hbm.live_bytes")
    if live is not None:
        line = f"hbm: live {_fmt_bytes(live)}"
        if gauges.get("mem.hbm.peak_bytes") is not None:
            line += f"  peak {_fmt_bytes(gauges['mem.hbm.peak_bytes'])}"
        if gauges.get("mem.hbm.headroom_frac") is not None:
            line += f"  headroom {100 * gauges['mem.hbm.headroom_frac']:.1f}%"
        out.append(line)
    if gauges.get("mem.host.rss_bytes") is not None:
        out.append(f"rss: {_fmt_bytes(gauges['mem.host.rss_bytes'])}")
    return out


def render(state: Dict[str, Any]) -> str:
    lines = [f"tbx top — {state['dir']}",
             "=" * max(20, len(state["dir"]) + 10)]
    fleet = state.get("fleet")
    if fleet:
        lines.append(
            f"fleet: {fleet.get('status', '?')}  "
            f"committed {fleet.get('committed', 0)}/"
            f"{fleet.get('units_total', 0)}  "
            f"reissued {fleet.get('reissued', 0)}  "
            f"lease-expiries {fleet.get('lease_expiries', 0)}"
            + (f"  recovery {fleet['recovery_seconds']:.1f}s"
               if fleet.get("recovery_seconds") is not None else ""))
    sf = state.get("serve_fleet")
    if sf:
        lines.append(
            f"serve-fleet: {sf.get('status', '?')}  "
            f"answered {sf.get('completed', 0)}/"
            f"{sf.get('requests_total', 0)}  "
            f"shed {sf.get('shed', 0)}  "
            f"respooled {sf.get('respooled', 0)}  "
            f"lease-expiries {sf.get('lease_expiries', 0)}  "
            f"dupes {sf.get('duplicate_commits', 0)}"
            + (f"  recovery {sf['recovery_seconds']:.1f}s"
               if sf.get("recovery_seconds") is not None else ""))
    gw = state.get("gateway")
    if gw:
        win = gw.get("window") or {}
        shed = gw.get("shed") or {}
        line = (f"gateway: {'draining' if gw.get('draining') else 'up'}  "
                f"port {gw.get('port', '?')}  "
                f"streams {gw.get('open_streams', 0)}/"
                f"{win.get('limit', '?')}  "
                f"accepted {gw.get('accepted', 0)}  "
                f"done {gw.get('completed', 0)}  "
                f"canceled {gw.get('canceled', 0)}")
        if shed:
            line += "  shed " + ",".join(
                f"{k}={shed[k]}" for k in sorted(shed))
        lines.append(line)
        tenants = gw.get("tenants") or {}
        tenant_shed = {t: c.get("shed", 0) for t, c in tenants.items()
                       if c.get("shed", 0)}
        if tenant_shed:
            lines.append("  tenant shed: " + "  ".join(
                f"{t}={tenant_shed[t]}" for t in sorted(tenant_shed)))
    lanes = state.get("lanes") or []
    if lanes:
        lines.append("lanes:")
        lines.extend(_lane_line(ln) for ln in lanes)
    else:
        lines.append("lanes: (no _progress*.json yet)")
    lines.extend(_latency_lines(lanes))
    latest = state.get("latest")
    if latest is not None:
        lines.extend(_slo_lines(latest))
        lines.extend(_window_extras(latest))
        counters = latest.get("counters") or {}
        dropped = (counters.get("obs.metrics_dropped") or {}).get("total", 0)
        lines.append(
            f"spool: {state.get('n_windows', 0)} windows in tail "
            f"({len(state.get('windows') or {})} lane(s)); "
            f"dropped {int(dropped)}")
    else:
        lines.append("spool: (no _metrics*.jsonl windows yet)")
    for rec in state.get("flightrec") or []:
        lines.append(f"flightrec: {rec['file']}  reason={rec['reason']}  "
                     f"{rec['records']} records")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Entry points.
# ---------------------------------------------------------------------------


def run(output_dir: str, *, once: bool = False,
        interval: float = 2.0) -> int:
    while True:
        frame = render(collect(output_dir))
        if once:
            print(frame)  # tbx: TBX009-ok — CLI stdout contract (top frame)
            return 0
        # tbx: TBX009-ok — CLI stdout contract (live screen redraw)
        print("\x1b[2J\x1b[H" + frame, flush=True)
        try:
            time.sleep(max(0.2, interval))
        except KeyboardInterrupt:
            return 0


def default_fixture_dir() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "tests", "fixtures", "obs", "fleet")


def default_serve_fleet_fixture_dir() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "tests", "fixtures", "obs", "serve_fleet")


def main_selfcheck(fixture_dir: Optional[str] = None) -> int:
    """CI smoke (``tbx top --once --selfcheck``): render the committed fleet
    fixture and assert the frame carries the load-bearing sections — worker
    lanes and spool windows — so a silent collection regression fails the
    gate instead of rendering an empty screen forever.  When the serve-fleet
    fixture is committed too, render it and assert replica lanes plus the
    serve-fleet summary line."""
    fixture_dir = fixture_dir or default_fixture_dir()
    state = collect(fixture_dir)
    frame = render(state)
    print(frame)  # tbx: TBX009-ok — CLI stdout contract (selfcheck frame)
    problems = []
    if not state["lanes"]:
        problems.append("no progress lanes in fixture")
    if state["latest"] is None:
        problems.append("no metrics windows in fixture")
    if not state["flightrec"]:
        problems.append("no flight-recorder dump in fixture")
    sf_dir = default_serve_fleet_fixture_dir()
    if fixture_dir == default_fixture_dir() and os.path.isdir(sf_dir):
        sf_state = collect(sf_dir)
        sf_frame = render(sf_state)
        # tbx: TBX009-ok — CLI stdout contract (selfcheck frame)
        print(sf_frame)
        replica_lanes = [ln for ln in sf_state["lanes"]
                         if ln.get("workload") == "serve"]
        if len(replica_lanes) < 2:
            problems.append("serve_fleet fixture: fewer than 2 replica "
                            "serve lanes")
        if not sf_state.get("serve_fleet"):
            problems.append("serve_fleet fixture: no _serve_fleet.json "
                            "summary")
        elif "serve-fleet:" not in sf_frame:
            problems.append("serve_fleet fixture: summary line not "
                            "rendered")
        if not sf_state.get("gateway"):
            problems.append("serve_fleet fixture: no _gateway.json "
                            "heartbeat")
        elif "gateway:" not in sf_frame or "tenant shed:" not in sf_frame:
            problems.append("serve_fleet fixture: gateway lane not "
                            "rendered")
    if problems:
        # tbx: TBX009-ok — CLI stdout contract (selfcheck verdict)
        print("top selfcheck FAILED: " + "; ".join(problems))
        return 1
    print("top selfcheck OK")  # tbx: TBX009-ok — CLI stdout contract
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="tbx top", description=__doc__)
    p.add_argument("--dir", default=".", help="run output directory to watch")
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit (CI / piping)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="live-refresh period in seconds")
    p.add_argument("--selfcheck", action="store_true",
                   help="render the committed fleet fixture and verify the "
                        "frame (CI smoke)")
    args = p.parse_args(argv)
    if args.selfcheck:
        return main_selfcheck()
    return run(args.dir, once=args.once, interval=args.interval)


__all__ = ["collect", "render", "run", "main", "main_selfcheck"]
