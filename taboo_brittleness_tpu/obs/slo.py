"""Declarative SLOs evaluated as multi-window burn rates.

An SLO here is "at most ``budget`` of observations may violate
``value <op> threshold``".  Each timeseries window (``obs.timeseries``)
contributes a (bad, total) pair per target; the burn rate over a span of
windows is::

    burn = (bad / total) / budget

i.e. how many times faster than sustainable the error budget is being
consumed (1.0 = exactly on budget).  Alerting is SRE-style multi-window: a
target alerts only when BOTH a fast span (default 1 window — catches the
regression quickly) and a slow span (default 6 windows — suppresses
one-window blips) burn at or above ``alert_burn``.  The published
``slo.burn.<name>`` gauge is ``min(fast, slow)`` — the admission signal the
replica router will consume (ROADMAP: "a router that admits by per-replica
SLO burn"): it rises only when a regression is both current and sustained.

Three target sources cover the repo's signals:

- ``histogram`` — per-sample violation counting over the window's forked
  reservoir (``serve.latency.<scenario>`` ≤ threshold; a ``*`` in the
  metric name fans one target out per matching histogram).
- ``ratio`` — a per-window counter-delta quotient held to a floor/ceiling
  (speculation ``accept_rate`` = accepted/drafted ≥ threshold; serve
  goodput = completed/admitted).
- ``gauge`` — an instantaneous value held to a bound (HBM headroom
  fraction ≥ threshold, ``obs.memory``).

Evaluation is in-process at window-roll time (raw reservoir samples never
leave the process); outputs are ``slo.burn.*`` gauges (which then ride the
next window of the spool), one ``obs.warn`` alert per sustained episode,
and the ``slo`` block the serve heartbeat carries.  Everything is
fail-open, stdlib-only, host-side.
"""

from __future__ import annotations

import collections
import dataclasses
import fnmatch
import json
import os
from typing import Any, Deque, Dict, List, Optional, Tuple

from taboo_brittleness_tpu.obs import metrics as obs_metrics

_SOURCES = ("histogram", "gauge", "ratio")
_OPS = ("le", "ge")

#: Floor for the error budget so a zero-budget target ("never violate")
#: yields a large finite burn instead of a division by zero.
_MIN_BUDGET = 1e-6


@dataclasses.dataclass(frozen=True)
class SloTarget:
    """One declarative objective.  ``metric`` may contain ``*`` (fnmatch)
    for histogram/gauge sources — the target fans out per matching
    instrument, suffixing the series name with the matched tail."""

    name: str
    source: str                 # histogram | gauge | ratio
    metric: str                 # instrument name/pattern (ratio: numerator)
    threshold: float
    op: str = "le"              # good when  value <op> threshold
    budget: float = 0.01        # tolerated bad fraction
    metric_b: str = ""          # ratio denominator counter
    fast_windows: int = 1
    slow_windows: int = 6
    alert_burn: float = 1.0

    def __post_init__(self):
        if self.source not in _SOURCES:
            raise ValueError(f"SLO {self.name!r}: unknown source "
                             f"{self.source!r} (one of {_SOURCES})")
        if self.op not in _OPS:
            raise ValueError(f"SLO {self.name!r}: unknown op {self.op!r}")
        if self.source == "ratio" and not self.metric_b:
            raise ValueError(f"SLO {self.name!r}: ratio needs metric_b")

    def good(self, value: float) -> bool:
        return (value <= self.threshold if self.op == "le"
                else value >= self.threshold)


def default_targets() -> List[SloTarget]:
    """The shipped objectives (overridable wholesale via ``TBX_SLO`` —
    inline JSON or a path to a JSON file with a list of target dicts)."""
    spec = os.environ.get("TBX_SLO")
    if spec:
        return load_targets(spec)
    try:
        latency_s = max(0.001, float(os.environ.get("TBX_SLO_LATENCY_S",
                                                    "2.5")))
    except ValueError:
        latency_s = 2.5
    try:
        ttft_s = max(0.001, float(os.environ.get("TBX_SLO_TTFT_S", "1.0")))
    except ValueError:
        ttft_s = 1.0
    return [
        # Per-scenario end-to-end serve latency: ≤ latency_s for all but 5%.
        SloTarget(name="serve_latency", source="histogram",
                  metric="serve.latency.*", threshold=latency_s,
                  op="le", budget=0.05),
        # Per-scenario time-to-first-token (submit → first emitted token,
        # re-timed on the surviving attempt after a re-spool): ≤ ttft_s for
        # all but 5% — the interactivity half of the latency story.
        SloTarget(name="serve_ttft", source="histogram",
                  metric="serve.ttft.*", threshold=ttft_s,
                  op="le", budget=0.05),
        # Goodput: ≥ 99% of admitted requests complete (per window).
        SloTarget(name="serve_goodput", source="ratio",
                  metric="serve.completed", metric_b="serve.admitted",
                  threshold=0.99, op="ge", budget=0.01),
        # Speculation health: accept_rate ≥ 0.2 — below it the (k, G)
        # calibration is stale and verify launches are mostly waste.
        SloTarget(name="spec_accept", source="ratio",
                  metric="serve.spec.accepted", metric_b="serve.spec.drafted",
                  threshold=0.2, op="ge", budget=0.05),
        # Fleet re-issue latency: a dropped unit back under lease ≤ 60 s.
        SloTarget(name="fleet_recovery", source="histogram",
                  metric="fleet.recovery_seconds", threshold=60.0,
                  op="le", budget=0.01),
        # HBM headroom: ≥ 5% of the device limit stays free.
        SloTarget(name="hbm_headroom", source="gauge",
                  metric="mem.hbm.headroom_frac", threshold=0.05,
                  op="ge", budget=0.01),
    ]


def load_targets(spec: str) -> List[SloTarget]:
    """Parse targets from inline JSON or a JSON file (a list of dicts with
    :class:`SloTarget`'s field names).  A malformed spec raises — a typo'd
    SLO config must fail loudly at startup, not silently guard nothing."""
    text = spec
    if not spec.lstrip().startswith("["):
        with open(spec) as f:
            text = f.read()
    raw = json.loads(text)
    if not isinstance(raw, list):
        raise ValueError("TBX_SLO must be a JSON list of target objects")
    return [SloTarget(**item) for item in raw]


def _series_key(target: SloTarget, metric_name: str) -> str:
    """`serve_latency` + pattern `serve.latency.*` matching
    `serve.latency.chat` → `serve_latency.chat` (the literal prefix/suffix
    around the ``*`` is stripped; an exact metric keeps the bare name)."""
    if "*" not in target.metric:
        return target.name
    head, _, tail = target.metric.partition("*")
    core = metric_name
    if head and core.startswith(head):
        core = core[len(head):]
    if tail and core.endswith(tail):
        core = core[:-len(tail)]
    return f"{target.name}.{core}" if core else target.name


class SloEngine:
    """Per-target sliding windows of (bad, total) pairs + burn/alert state.
    One engine per process surface (the serve loop, the sweep observer);
    feed it from ``TimeseriesRecorder(slo_engine=...)``."""

    def __init__(self, targets: Optional[List[SloTarget]] = None, *,
                 registry: Optional[obs_metrics.MetricsRegistry] = None,
                 emit_alerts: bool = True):
        self.targets = default_targets() if targets is None else list(targets)
        self.registry = registry or obs_metrics.registry()
        self.emit_alerts = emit_alerts
        self._series: Dict[str, Deque[Tuple[float, float]]] = {}
        self._alerting: Dict[str, bool] = {}
        self._last_block: Dict[str, Dict[str, Any]] = {}

    # -- per-window observation --------------------------------------------

    def _observations(self, target: SloTarget, hists, counter_deltas,
                      gauges) -> List[Tuple[str, float, float, str]]:
        """(series key, bad, total, metric name) contributions of one
        window.  A series with nothing to say this window contributes
        (0, 0) implicitly by not appearing — idle windows age old badness
        out of the spans."""
        out: List[Tuple[str, float, float, str]] = []
        if target.source == "histogram":
            for name, win in hists.items():
                if not fnmatch.fnmatchcase(name, target.metric):
                    continue
                samples = win.get("samples") or []
                if not samples:
                    continue
                bad = sum(1 for v in samples if not target.good(v))
                out.append((_series_key(target, name), float(bad),
                            float(len(samples)), name))
        elif target.source == "gauge":
            for name, value in gauges.items():
                if not fnmatch.fnmatchcase(name, target.metric):
                    continue
                out.append((_series_key(target, name),
                            0.0 if target.good(value) else 1.0, 1.0, name))
        elif target.source == "ratio":
            den = counter_deltas.get(target.metric_b, 0.0)
            if den > 0:
                num = counter_deltas.get(target.metric, 0.0)
                out.append((target.name,
                            0.0 if target.good(num / den) else 1.0, 1.0,
                            target.metric))
        return out

    def observe_window(self, *, dur: float, hists: Dict[str, Any],
                       counter_deltas: Dict[str, float],
                       gauges: Dict[str, float]) -> Dict[str, Dict[str, Any]]:
        """Fold one rolled window into every target's spans; set the
        ``slo.burn.<series>`` gauges; emit at most one ``obs.warn`` per
        newly-sustained alert episode.  Returns the heartbeat block
        ``{series: {burn, fast, slow, ok}}``."""
        contributions: Dict[str, Tuple[SloTarget, float, float, str]] = {}
        for target in self.targets:
            for key, bad, total, metric in self._observations(
                    target, hists, counter_deltas, gauges):
                contributions[key] = (target, bad, total, metric)
        block: Dict[str, Dict[str, Any]] = {}
        # Every KNOWN series advances each window — absent = (0, 0) — so a
        # regression that stops the traffic entirely still ages out.
        keys = set(self._series) | set(contributions)
        for key in sorted(keys):
            target, bad, total, metric = contributions.get(
                key, (None, 0.0, 0.0, ""))
            series = self._series.get(key)
            if series is None:
                if target is None:
                    continue
                series = self._series[key] = collections.deque(
                    maxlen=max(1, target.slow_windows))
            series.append((bad, total))
            target = target or self._target_for(key)
            if target is None:
                continue
            fast = self._burn(series, target, target.fast_windows)
            slow = self._burn(series, target, target.slow_windows)
            burn = round(min(fast, slow), 4)
            ok = burn < target.alert_burn
            block[key] = {"burn": burn, "fast": round(fast, 4),
                          "slow": round(slow, 4), "ok": ok}
            if metric and target.source == "histogram":
                # Burn → trace exemplars: the window's worst trace ids for
                # this series ride the heartbeat block, so an operator can
                # jump straight from a burning row to ``tbx trace``.
                try:
                    from taboo_brittleness_tpu.obs import reqtrace
                    exemplars = reqtrace.take_exemplars(metric)
                    if exemplars:
                        block[key]["exemplars"] = exemplars
                except Exception:  # noqa: BLE001 — fail-open
                    pass
            try:
                self.registry.gauge(f"slo.burn.{key}").set(burn)
            except Exception:  # noqa: BLE001 — fail-open
                pass
            self._maybe_alert(key, target, burn, ok)
        self._last_block = block
        return block

    def _target_for(self, key: str) -> Optional[SloTarget]:
        for target in self.targets:
            if key == target.name or key.startswith(target.name + "."):
                return target
        return None

    @staticmethod
    def _burn(series, target: SloTarget, span: int) -> float:
        recent = list(series)[-max(1, span):]
        total = sum(t for _, t in recent)
        if total <= 0:
            return 0.0
        frac = sum(b for b, _ in recent) / total
        return frac / max(target.budget, _MIN_BUDGET)

    def _maybe_alert(self, key: str, target: SloTarget, burn: float,
                     ok: bool) -> None:
        was = self._alerting.get(key, False)
        self._alerting[key] = not ok
        if ok or was or not self.emit_alerts:
            return
        try:
            from taboo_brittleness_tpu import obs

            obs.warn(
                f"[slo] {key}: burn {burn:.2f}x over budget "
                f"(target {target.metric} {target.op} {target.threshold}, "
                f"budget {target.budget:.2%})",
                name="slo.alert", slo=key, burn=burn,
                threshold=target.threshold, budget=target.budget)
        except Exception:  # noqa: BLE001 — alerting must not kill the roll
            pass

    def last_block(self) -> Dict[str, Dict[str, Any]]:
        return dict(self._last_block)


__all__ = [
    "SloEngine", "SloTarget", "default_targets", "load_targets",
]
