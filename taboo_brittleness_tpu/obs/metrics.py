"""Process-wide metrics registry: counters, gauges, histograms.

The run manifest snapshots this registry at save time (``RunManifest.to_dict``
→ ``obs.metrics``), so every pipeline run carries its own decode-launch
counts, retry/quarantine totals, AOT hit rates, and word-time distributions
without any pipeline threading a registry object around.  Everything is
host-side, thread-safe, and bounded: a histogram keeps running stats plus a
fixed-size reservoir for quantiles, so a million observations cost the same
memory as a hundred.

Names are dotted lowercase (``decode.launches``, ``sweep.retries``,
``word.seconds``); the snapshot groups by type, not by name prefix.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

_RESERVOIR_CAP = 512


def quantile_of(samples: List[float], q: float) -> Optional[float]:
    """Nearest-rank quantile over a raw sample list (the shared rule every
    reservoir consumer uses, so windowed and cumulative percentiles can never
    disagree about rounding)."""
    if not samples:
        return None
    s = sorted(samples)
    return s[min(len(s) - 1, max(0, int(q * (len(s) - 1) + 0.5)))]


class Counter:
    """Monotonic non-negative counter."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value: Optional[float] = None
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> Optional[float]:
        with self._lock:
            return self._value


class Histogram:
    """Running count/sum/min/max plus a bounded reservoir for quantiles.

    The reservoir keeps the FIRST ``_RESERVOIR_CAP`` observations and then
    overwrites deterministically (index ``n % cap``): sweeps observe at most
    a few thousand values, so this stays representative without RNG (obs code
    must not perturb seeded randomness anywhere).

    Alongside the cumulative reservoir, each observation also lands in a
    WINDOW-forked reservoir: :meth:`roll_window` (called by the timeseries
    recorder, ``obs.timeseries``) snapshots and resets it, so per-window
    p50/p99 describe only the samples of that window — the live signal an
    SLO burn rate needs, which a since-process-start reservoir arithmetically
    masks.  The last rolled window is kept so :meth:`windowed` can report
    "recent" stats (last rolled + in-progress window) between rolls."""

    __slots__ = ("name", "count", "total", "min", "max", "_sample",
                 "_w_count", "_w_total", "_w_min", "_w_max", "_w_sample",
                 "_last_window", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._sample: List[float] = []
        self._w_count = 0
        self._w_total = 0.0
        self._w_min: Optional[float] = None
        self._w_max: Optional[float] = None
        self._w_sample: List[float] = []
        self._last_window: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            if self.count < _RESERVOIR_CAP:
                self._sample.append(value)
            else:
                self._sample[self.count % _RESERVOIR_CAP] = value
            self.count += 1
            self.total += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)
            if self._w_count < _RESERVOIR_CAP:
                self._w_sample.append(value)
            else:
                self._w_sample[self._w_count % _RESERVOIR_CAP] = value
            self._w_count += 1
            self._w_total += value
            self._w_min = (value if self._w_min is None
                           else min(self._w_min, value))
            self._w_max = (value if self._w_max is None
                           else max(self._w_max, value))

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            if not self._sample:
                return None
            s = list(self._sample)
        return quantile_of(s, q)

    def roll_window(self) -> Dict[str, Any]:
        """Fork off the current window: return ``{n, sum, min, max, samples}``
        for everything observed since the last roll, reset the window
        accumulators, and remember the result as the "last rolled window".
        ``samples`` is the raw (bounded) reservoir — the timeseries recorder
        computes per-window quantiles from it and the SLO engine counts
        per-sample threshold violations; neither leaves the process."""
        with self._lock:
            win = {
                "n": self._w_count,
                "sum": self._w_total,
                "min": self._w_min,
                "max": self._w_max,
                "samples": self._w_sample,
            }
            self._w_count = 0
            self._w_total = 0.0
            self._w_min = None
            self._w_max = None
            self._w_sample = []
            self._last_window = win
        return win

    def windowed(self) -> Dict[str, Any]:
        """Stats over the RECENT samples: the last rolled window plus the
        in-progress one (so the view is never empty right after a roll).
        Before any roll this is simply "everything so far" — identical to
        cumulative, which is correct for a process younger than one window."""
        with self._lock:
            samples = list(self._w_sample)
            n = self._w_count
            w_max = self._w_max
            last = self._last_window
        if last is not None:
            samples = list(last["samples"]) + samples
            n += last["n"]
            if last["max"] is not None:
                w_max = (last["max"] if w_max is None
                         else max(w_max, last["max"]))
        return {"n": n, "max": w_max,
                "p50": quantile_of(samples, 0.50),
                "p99": quantile_of(samples, 0.99)}

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            if not self.count:
                return {"count": 0}
            mean = self.total / self.count
            s = sorted(self._sample)

        def q(frac: float) -> float:
            return s[min(len(s) - 1, max(0, int(frac * (len(s) - 1) + 0.5)))]

        return {
            "count": self.count,
            "sum": round(self.total, 6),
            "mean": round(mean, 6),
            "min": round(self.min, 6),
            "max": round(self.max, 6),
            "p50": round(q(0.50), 6),
            "p90": round(q(0.90), 6),
        }


class MetricsRegistry:
    """Named metric instruments, created on first touch (so call sites never
    pre-register).  A name is permanently one type: asking for an existing
    name with a different type raises — that is a bug at the call site, not
    a runtime condition, so it is NOT fail-open."""

    def __init__(self) -> None:
        self._metrics: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} is a {type(m).__name__}, "
                    f"not a {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def instruments(self) -> Dict[str, Any]:
        """A point-in-time copy of the name → instrument map (the timeseries
        recorder iterates this to roll histogram windows and diff counters
        without holding the registry lock across IO)."""
        with self._lock:
            return dict(self._metrics)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """{"counters": {...}, "gauges": {...}, "histograms": {...}} with
        names sorted — the manifest-stable form."""
        with self._lock:
            items = sorted(self._metrics.items())
        out: Dict[str, Dict[str, Any]] = {
            "counters": {}, "gauges": {}, "histograms": {}}
        for name, m in items:
            if isinstance(m, Counter):
                out["counters"][name] = m.value
            elif isinstance(m, Gauge):
                if m.value is not None:
                    out["gauges"][name] = m.value
            elif isinstance(m, Histogram):
                out["histograms"][name] = m.to_dict()
        return {k: v for k, v in out.items() if v}

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


# Process-wide default registry (the one the manifest snapshots).
_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str) -> Histogram:
    return _REGISTRY.histogram(name)


def snapshot() -> Dict[str, Dict[str, Any]]:
    return _REGISTRY.snapshot()


def reset() -> None:
    """Clear the process registry (tests; bench A/B arms)."""
    _REGISTRY.reset()


__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "quantile_of", "registry", "counter", "gauge", "histogram", "snapshot",
    "reset",
]
