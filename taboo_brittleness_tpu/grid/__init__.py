"""Gemma-Scope grid sweeps + closed-loop attack search (ISSUE 14).

The paper reads ONE SAE (16k width, layer 31) per word; Gemma Scope
(arXiv:2408.05147) ships SAEs at every layer and several widths, turning
the brittleness question into a depth x width grid — the workload the
fleet layer (PR 10) and multi-word serving (PR 12) were built for.

- :mod:`~taboo_brittleness_tpu.grid.spec` — the grid schema: which
  (layer, width) readout cells exist, where their converted SAE
  artifacts live, and which residual tap layers one decode must capture.
- :mod:`~taboo_brittleness_tpu.grid.runner` — capture-once execution:
  decode each word ONE time tapping every grid layer in a single
  launched program, then fan encode -> top-latents -> ablate -> decode
  -> score per cell as fleet ``(word, readout_config)`` units.
- :mod:`~taboo_brittleness_tpu.grid.search` — the seeded evolutionary
  attack driver riding ``serve/loadgen.run_inprocess`` against a running
  engine; emits the breakage matrix (which (layer, width, attack) cells
  elicit each secret).
"""

from taboo_brittleness_tpu.grid.spec import (  # noqa: F401
    GRID_ARTIFACT_VERSION, CellSpec, GridSpec)
