"""Closed-loop attack search: evolve elicitation attacks against a
running engine.

The paper hand-lists its attacks (ten token-forcing prefills, two prompt
families); this driver *generates* them.  A candidate :class:`Attack` is
(forcing prefix, prompt template, optional grid-cell latent ablation);
each generation every candidate is scored by driving the served engine
through ``serve.loadgen.run_inprocess`` with the candidate as a
per-request :class:`~taboo_brittleness_tpu.serve.scheduler.Scenario`
(``prefill`` = the evolved prefix, ``ablate_latents`` drawn from the
grid's per-(layer, width) top latents, ``lens_readout=True`` for the
dense per-step P(secret) signal), then mutated/crossed over under a
seeded rng.

Determinism contract (tier-1 gated): token streams and lens probabilities
from the scheduler are deterministic — only host latencies vary — and the
search excludes latencies from every scored quantity, so the SAME seed
yields a byte-identical trajectory and breakage matrix
(``json.dumps(..., sort_keys=True)`` equality, not just approximate
scores).  Fitness = mean token-forcing success over words (the paper's
``metrics.forcing_success``) + a small lens-probability bonus that breaks
ties continuously, which is what lets evolution climb even while every
seed attack scores zero forcing.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import random
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

#: Weight of the dense lens-probability bonus relative to forcing success
#: (forcing is in [0, 1]; the bonus must never dominate a real leak).
LENS_BONUS = 1e-3


@dataclasses.dataclass(frozen=True)
class Attack:
    """One candidate: forced prefix + prompt template + optional ablation
    targets drawn from a grid cell's latent pool."""

    prefix: str
    template: str
    cell: Optional[str] = None       # grid cell key the latents came from
    latents: Tuple[int, ...] = ()

    @property
    def name(self) -> str:
        """Stable content-derived id (NOT Python ``hash`` — that's salted
        per process and would break byte-identical trajectories)."""
        blob = json.dumps([self.prefix, self.template, self.cell,
                           list(self.latents)], sort_keys=True)
        return "a" + hashlib.sha1(blob.encode("utf-8")).hexdigest()[:10]

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "prefix": self.prefix,
                "template": self.template, "cell": self.cell,
                "latents": list(self.latents)}


def default_valid_forms(words: Sequence[str]) -> Dict[str, Set[str]]:
    from taboo_brittleness_tpu.config import WORD_PLURALS

    return {w: {w.lower(),
                *(p.lower() for p in WORD_PLURALS.get(w, []))}
            for w in words}


def evaluate_attack(engine, lens_target_id: int, attack: Attack,
                    words: Sequence[str], *,
                    valid_forms: Dict[str, Set[str]],
                    n_requests: int = 6, seed: int = 0,
                    max_new_tokens: int = 6,
                    ) -> Tuple[float, Dict[str, Any]]:
    """Score one attack against the engine: one ``run_inprocess`` burst of
    ``n_requests`` mixed-word requests, all rendered from this attack's
    scenario.  Returns (fitness, per_word) built ONLY from deterministic
    response fields (texts, tokens, lens probs — never latencies)."""
    from taboo_brittleness_tpu import metrics
    from taboo_brittleness_tpu.serve import loadgen
    from taboo_brittleness_tpu.serve.scheduler import Scenario

    scen = Scenario(name="attack", prefill=attack.prefix or None,
                    lens_readout=True,
                    ablate_latents=tuple(attack.latents),
                    max_new_tokens=max_new_tokens)
    responses: List[Any] = []
    loadgen.run_inprocess(
        engine, n_requests=n_requests, seed=seed,
        # Instant arrivals + concurrency >= n: admission order depends only
        # on the seeded schedule, never on host timing.
        rate=1e6, concurrency=max(n_requests, 1),
        queue_limit=max(n_requests, 64),
        mix={"attack": 1.0}, scenarios={"attack": scen},
        prompts=(attack.template,), words=list(words),
        lens_target_id=lens_target_id,
        on_complete=responses.append)

    per_word: Dict[str, Any] = {}
    forcing_sum = lens_sum = 0.0
    for w in words:
        rs = sorted((r for r in responses if r.word == w and r.ok),
                    key=lambda r: r.id)
        texts = [r.text for r in rs]
        forcing = metrics.forcing_success(texts, valid_forms[w])
        peaks = [max(r.lens_probs) for r in rs if r.lens_probs]
        lens = sum(peaks) / len(peaks) if peaks else 0.0
        per_word[w] = {"forcing": round(forcing, 6),
                       "lens": round(lens, 9), "n": len(rs)}
        forcing_sum += forcing
        lens_sum += lens
    n = max(len(words), 1)
    fitness = round(forcing_sum / n + LENS_BONUS * (lens_sum / n), 12)
    return fitness, per_word


# ---------------------------------------------------------------------------
# Mutation / crossover (seeded; pure host-side string and tuple surgery).
# ---------------------------------------------------------------------------


def _mutate(rng: random.Random, parent: Attack, mates: Sequence[Attack], *,
            templates: Sequence[str], mutation_words: Sequence[str],
            latent_pools: Dict[str, Sequence[int]]) -> Attack:
    ops = ["append", "drop", "template", "crossover"]
    if latent_pools:
        ops += ["latents", "clear_latents"]
    op = rng.choice(ops)
    prefix, template = parent.prefix, parent.template
    cell, latents = parent.cell, parent.latents
    if op == "append":
        prefix = (prefix + " " + rng.choice(list(mutation_words))).strip()
    elif op == "drop":
        parts = prefix.split()
        if len(parts) > 1:
            del parts[rng.randrange(len(parts))]
        prefix = " ".join(parts)
    elif op == "template":
        template = rng.choice(list(templates))
    elif op == "crossover" and mates:
        mate = rng.choice(list(mates))
        a, b = prefix.split(), mate.prefix.split()
        if a and b:
            prefix = " ".join(a[: max(1, len(a) // 2)]
                              + b[len(b) // 2:])
    elif op == "latents":
        cell = rng.choice(sorted(latent_pools))
        pool = list(latent_pools[cell])
        k = min(len(pool), rng.randrange(1, 4))
        latents = tuple(sorted(rng.sample(pool, k)))
    elif op == "clear_latents":
        cell, latents = None, ()
    return Attack(prefix=prefix, template=template, cell=cell,
                  latents=latents)


# ---------------------------------------------------------------------------
# The search driver.
# ---------------------------------------------------------------------------


def run_search(engine, lens_target_id: int, *,
               words: Sequence[str],
               seed: int = 0,
               generations: int = 4,
               population: int = 6,
               elite: int = 2,
               n_requests: int = 6,
               max_new_tokens: int = 6,
               seed_prefixes: Optional[Sequence[str]] = None,
               seed_templates: Optional[Sequence[str]] = None,
               mutation_words: Optional[Sequence[str]] = None,
               latent_pools: Optional[Dict[str, Sequence[int]]] = None,
               valid_forms: Optional[Dict[str, Set[str]]] = None,
               matrix_attacks: int = 2,
               ) -> Dict[str, Any]:
    """Seeded evolutionary search.  Returns the full artifact dict:

    - ``trajectory``: per-generation evaluated candidates (fitness +
      per-word forcing/lens), sorted best-first — byte-identical across
      runs with the same seed;
    - ``matrix``: the breakage matrix — for each grid cell in
      ``latent_pools`` and each of the ``matrix_attacks`` best evolved
      attacks, which words that (layer, width, attack) combination
      elicits;
    - ``best``/``seed_best_fitness``/``improved``: the acceptance hook —
      ``improved`` is True iff some evolved candidate scored strictly
      higher than the whole seed population.
    """
    from taboo_brittleness_tpu import config as cfg_mod

    rng = random.Random(f"attack-search:{seed}")
    prefixes = list(seed_prefixes or cfg_mod.TOKEN_FORCING_PREFILLS[:4])
    templates = list(seed_templates or cfg_mod.NAIVE_PROMPTS[:3])
    mutation_words = list(mutation_words or (
        list(words) + ["secret", "word", "is", "the", "answer", "My",
                       "hint", "say", "now"]))
    latent_pools = dict(latent_pools or {})
    valid_forms = valid_forms or default_valid_forms(words)

    seeds = [Attack(prefix=p, template=templates[i % len(templates)])
             for i, p in enumerate(prefixes)][:population]
    cache: Dict[str, Tuple[float, Dict[str, Any]]] = {}

    def score(attack: Attack) -> Tuple[float, Dict[str, Any]]:
        if attack.name not in cache:
            cache[attack.name] = evaluate_attack(
                engine, lens_target_id, attack, words,
                valid_forms=valid_forms, n_requests=n_requests, seed=seed,
                max_new_tokens=max_new_tokens)
        return cache[attack.name]

    trajectory: List[Dict[str, Any]] = []
    pop = list(seeds)
    seed_best = None
    best: Tuple[float, Attack] = (-1.0, seeds[0])
    for gen in range(generations):
        scored = []
        for a in pop:
            fitness, per_word = score(a)
            scored.append((fitness, a, per_word))
        scored.sort(key=lambda t: (-t[0], t[1].name))
        if gen == 0:
            seed_best = scored[0][0]
        if scored[0][0] > best[0]:
            best = (scored[0][0], scored[0][1])
        trajectory.append({
            "gen": gen,
            "evaluated": [dict(a.to_dict(), fitness=f, per_word=pw)
                          for f, a, pw in scored],
        })
        if gen == generations - 1:
            break
        parents = [a for _f, a, _pw in scored]
        nxt = parents[:elite]
        seen = {a.name for a in nxt}
        while len(nxt) < population:
            parent = parents[min(rng.randrange(max(elite, 1)),
                                 len(parents) - 1)]
            child = _mutate(rng, parent, parents, templates=templates,
                            mutation_words=mutation_words,
                            latent_pools=latent_pools)
            if child.name in seen:
                # Deterministic de-dup: nudge with another mutation round.
                child = _mutate(rng, child, parents, templates=templates,
                                mutation_words=mutation_words,
                                latent_pools=latent_pools)
            seen.add(child.name)
            nxt.append(child)
        pop = nxt

    # Breakage matrix: top evolved attacks x grid cells.  Each evaluation
    # swaps the attack's ablation targets for the cell's pool (first 3,
    # deterministic), asking "does THIS (layer, width, attack) cell elicit
    # the secret?".
    evaluated_best = sorted(
        {a.name: (f, a) for gen in trajectory
         for f, a in [(e["fitness"], Attack(
             prefix=e["prefix"], template=e["template"], cell=e["cell"],
             latents=tuple(e["latents"]))) for e in gen["evaluated"]]
         }.values(), key=lambda t: (-t[0], t[1].name))
    top = [a for _f, a in evaluated_best[:max(matrix_attacks, 1)]]
    cells = sorted(latent_pools) or [None]
    matrix: Dict[str, Dict[str, Any]] = {w: {} for w in words}
    for cell in cells:
        ckey = cell or "none"
        for a in top:
            latents = (tuple(sorted(latent_pools[cell])[:3])
                       if cell else ())
            probe = Attack(prefix=a.prefix, template=a.template,
                           cell=cell, latents=latents)
            _f, per_word = score(probe)
            for w in words:
                matrix[w].setdefault(ckey, {})[a.name] = {
                    "forcing": per_word[w]["forcing"],
                    "lens": per_word[w]["lens"],
                    "broke": per_word[w]["forcing"] > 0.0,
                }

    break_cells = sum(
        1 for w in words for ckey in matrix[w]
        for rec in matrix[w][ckey].values() if rec["broke"])
    total_cells = sum(len(matrix[w][ckey]) for w in words
                      for ckey in matrix[w])
    return {
        "version": 1,
        "seed": seed,
        "words": list(words),
        "generations": generations,
        "population": population,
        "trajectory": trajectory,
        "matrix": {"cells": [c or "none" for c in cells],
                   "attacks": [a.to_dict() for a in top],
                   "by_word": matrix},
        "best": dict(best[1].to_dict(), fitness=best[0]),
        "seed_best_fitness": seed_best,
        "improved": bool(seed_best is not None and best[0] > seed_best),
        "break_rate": round(break_cells / total_cells, 6)
        if total_cells else 0.0,
    }
