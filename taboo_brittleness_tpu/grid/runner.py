"""Capture-once grid execution: one decode per word, one fleet unit per cell.

The grid's whole point is the cost split: decoding a word is the expensive
half (a full generation pass), while a readout cell only needs that
decode's residual stream at ITS layer.  So the coordinator decodes each
word ONCE with ``capture_residual_layer=spec.tap_layers`` — the multi-tap
carry (``ops.lens.residual_multi_tap``) captures every grid layer in the
same launched program for ~0.5 MB/prompt/layer, nothing like the 1.16 GB
all-probs hazard — and persists the [K, B, T, D] stack as a shared
artifact.  Fleet workers then fan out ``(word, cell)`` units that LOAD the
artifact instead of re-decoding: encode -> top-latents -> ablate -> decode
-> score per cell, under the standard lease/retry/quarantine machinery
(``grid.cell`` is a named fault site riding the worker's ``run_guarded``).
"""

from __future__ import annotations

import functools
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax

from taboo_brittleness_tpu.grid.spec import (
    GRID_ARTIFACT_VERSION, CellSpec, GridSpec, cell_sae)

RESID_DIRNAME = "residuals"


def residual_path(resid_dir: str, word: str) -> str:
    return os.path.join(resid_dir, f"{word}.npz")


def probe_prompts(word: str) -> List[str]:
    """The word's grid probe prompt (one decode shared by every cell)."""
    return [f"Give me a hint about the {word}"]


def capture_word_residuals(params, cfg, tok, word: str, spec: GridSpec, *,
                           max_new_tokens: int, resid_dir: str,
                           prompts: Optional[Sequence[str]] = None) -> str:
    """Decode ``word`` once, tapping every grid layer, and persist the
    shared residual artifact the cell units consume.  Returns the path."""
    from taboo_brittleness_tpu.runtime import decode

    prompts = list(prompts) if prompts else probe_prompts(word)
    result, _texts, _ids = decode.generate(
        params, cfg, tok, prompts, max_new_tokens=max_new_tokens,
        capture_residual_layer=spec.tap_layers, return_texts=False)
    residual = np.asarray(jax.device_get(result.residual),
                          np.float32)                     # [K, B, T, D]
    tokens = np.asarray(jax.device_get(result.tokens))    # [B, N]
    lengths = np.asarray(jax.device_get(result.lengths))  # [B]
    K, B, T, _D = residual.shape
    N = tokens.shape[1]
    prompt_cols = T - N
    # mask[b, Tp+i] = step i emitted a real token: the response positions
    # every cell's mean-activation readout pools over.
    mask = np.zeros((B, T), bool)
    for b in range(B):
        mask[b, prompt_cols:prompt_cols + int(lengths[b])] = True
    os.makedirs(resid_dir, exist_ok=True)
    path = residual_path(resid_dir, word)
    # Keep the tmp name .npz-suffixed: np.savez appends .npz to any other
    # name and the atomic rename would miss the real file.
    tmp = f"{path}.tmp-{os.getpid()}.npz"
    np.savez(tmp, residual=residual, mask=mask, tokens=tokens,
             lengths=lengths, prompt_cols=np.int64(prompt_cols),
             tap_layers=np.asarray(spec.tap_layers, np.int64),
             __grid_version__=np.int64(GRID_ARTIFACT_VERSION))
    os.replace(tmp, path)
    return path


def load_word_residuals(path: str) -> Dict[str, np.ndarray]:
    """Load + validate a shared residual artifact (version-stamped, like
    every grid artifact — a stale schema must fail loudly)."""
    with np.load(path) as data:
        art = {k: np.asarray(data[k]) for k in data.files}
    ver = int(art.get("__grid_version__", -1))
    if ver != GRID_ARTIFACT_VERSION:
        raise ValueError(f"{path}: residual artifact version {ver} != "
                         f"{GRID_ARTIFACT_VERSION}")
    return art


# ---------------------------------------------------------------------------
# The per-cell readout program (jitted, AOT-registered).
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("top_k",))
def _cell_readout(sae, resid, mask, *, top_k: int):
    """Pooled JumpReLU readout for one cell: mean SAE activation over the
    response positions of every prompt row, then top-k latents.
    resid [B, T, D], mask [B, T] -> (ids [k], acts [k])."""
    from taboo_brittleness_tpu.ops import sae as sae_ops

    B, T, D = resid.shape
    mean_acts = sae_ops.mean_response_acts(
        sae, resid.reshape(B * T, D), mask.reshape(B * T))
    return sae_ops.top_latents(mean_acts, top_k)


def cell_readout(sae, resid, mask, *, top_k: int = 8):
    """:func:`_cell_readout` through the AOT program registry, under a
    ``grid.encode`` program span + device-profiler annotation (the same
    dispatch idiom as the study's readout/nll programs)."""
    from taboo_brittleness_tpu import obs
    from taboo_brittleness_tpu.runtime import aot

    with obs.span("grid.encode", kind="program",
                  rows=int(getattr(resid, "shape", (0,))[0]),
                  width=int(sae.w_enc.shape[1]), fn="_cell_readout") as sp:
        with obs.profile.annotate("grid.encode", fn=_cell_readout,
                                  span_id=getattr(sp, "span_id", None)):
            return aot.dispatch(
                "grid.encode", _cell_readout,
                dynamic=dict(sae=sae, resid=resid, mask=mask),
                static=dict(top_k=top_k),
                route=True)


# ---------------------------------------------------------------------------
# The fleet unit: one (word, cell) computation.
# ---------------------------------------------------------------------------


def _leak(texts: Sequence[str], word: str) -> float:
    from taboo_brittleness_tpu import metrics
    from taboo_brittleness_tpu.config import WORD_PLURALS

    forms = {word.lower(), *(p.lower() for p in WORD_PLURALS.get(word, []))}
    return metrics.leak_rate(list(texts), forms)


def run_cell(unit: Dict[str, Any], *, spec: GridSpec, resid_dir: str,
             model: Optional[Tuple[Any, Any, Any]] = None, seed: int = 7,
             top_k: int = 8, max_new_tokens: int = 8) -> Dict[str, Any]:
    """One grid cell: load the word's shared residual artifact, encode at
    the cell's (layer, width) SAE, take top-k latents, then (with a model
    in hand) re-decode the probe with those latents ablated and score the
    leak shift.  Raises on any inconsistency — the fleet worker's
    retry -> quarantine guard owns failures (``grid.cell`` fault site)."""
    import jax.numpy as jnp

    from taboo_brittleness_tpu import obs
    from taboo_brittleness_tpu.runtime import decode, resilience

    word = str(unit["word"])
    key = str((unit.get("readout") or {}).get("key") or "")
    cell = spec.cell(key)
    # ``unit`` context = "<word>@<cell>": lets a fault plan target exactly
    # one grid cell by substring match (the selfcheck's injection).
    resilience.fire("grid.cell", word=word, cell=cell.key,
                    unit=f"{word}@{cell.key}",
                    layer=cell.layer, width=cell.width)

    with obs.span("grid.cell", word=word, cell=cell.key):
        art = load_word_residuals(residual_path(resid_dir, word))
        taps = tuple(int(t) for t in art["tap_layers"])
        if cell.layer not in taps:
            raise ValueError(f"cell {cell.key}: layer {cell.layer} not in "
                             f"captured taps {taps} for word {word!r}")
        resid = art["residual"][taps.index(cell.layer)]      # [B, T, D]
        mask = art["mask"]
        sae = cell_sae(cell, resid.shape[-1], seed=seed)
        ids, vals = cell_readout(sae, jnp.asarray(resid), jnp.asarray(mask),
                                 top_k=top_k)
        ids = np.asarray(jax.device_get(ids))
        vals = np.asarray(jax.device_get(vals))
        out: Dict[str, Any] = {
            "word": word, "cell": cell.key,
            "layer": cell.layer, "width": cell.width,
            "top_latents": [int(i) for i in ids],
            "top_acts": [round(float(v), 6) for v in vals],
        }
        if model is not None:
            params, cfg, tok = model
            tokens, lengths = art["tokens"], art["lengths"]
            base_texts = [tok.decode(tokens[b][: int(lengths[b])].tolist())
                          for b in range(tokens.shape[0])]
            from taboo_brittleness_tpu.pipelines.interventions import (
                sae_ablation_edit)

            ep = {"sae": sae, "latent_ids": jnp.asarray(ids),
                  "layer": cell.layer}
            _res, abl_texts, _ = decode.generate(
                params, cfg, tok, probe_prompts(word),
                max_new_tokens=max_new_tokens,
                edit_fn=sae_ablation_edit, edit_params=ep)
            leak_base = _leak(base_texts, word)
            leak_abl = _leak(abl_texts or [], word)
            out.update({
                "leak_base": round(leak_base, 6),
                "leak_ablated": round(leak_abl, 6),
                # "broke" = the cell's latents carry the secret: ablating
                # them changes whether the word leaks.
                "broke": bool(leak_abl < leak_base),
                "ablated_text": (abl_texts or [""])[0],
            })
        return out


def make_unit_fn(spec: GridSpec, *, resid_dir: str, model=None, seed: int = 7,
                 top_k: int = 8, max_new_tokens: int = 8):
    """The fleet worker's ``unit_fn`` for grid spools."""
    def unit_fn(unit: Dict[str, Any]) -> Dict[str, Any]:
        return run_cell(unit, spec=spec, resid_dir=resid_dir, model=model,
                        seed=seed, top_k=top_k, max_new_tokens=max_new_tokens)
    return unit_fn


def grid_units(spec: GridSpec, words: Sequence[str]) -> List[Dict[str, Any]]:
    """One fleet unit per (word, cell); ``fleet.unit_id`` keys on the
    cell key, so uids read ``<word>@L<layer>-W<tag>``."""
    from taboo_brittleness_tpu.runtime import fleet

    units = []
    for w in words:
        for c in spec.cells:
            readout = {"layer": c.layer, "width": c.width, "key": c.key}
            units.append({"uid": fleet.unit_id(w, readout), "word": w,
                          "readout": readout})
    return units


# ---------------------------------------------------------------------------
# Matrix assembly (coordinator, after the fleet returns).
# ---------------------------------------------------------------------------


def assemble_matrix(fleet_dir: str, spec: GridSpec,
                    words: Sequence[str]) -> Dict[str, Any]:
    """Fold the spool's committed/quarantined cell results into the grid
    matrix artifact: ``matrix[word][cell]`` is the cell's result dict, or
    ``{"status": "quarantined"}`` for cells the fleet gave up on."""
    from taboo_brittleness_tpu.runtime import fleet

    spool = fleet.FleetSpool(os.path.join(fleet_dir, fleet.SPOOL_DIRNAME))
    matrix: Dict[str, Dict[str, Any]] = {w: {} for w in words}

    def _scan(dirname: str, status: str):
        try:
            names = sorted(os.listdir(dirname))
        except OSError:
            return
        for name in names:
            if not name.endswith(".json"):
                continue
            rec = spool._parse(os.path.join(dirname, name)) or {}
            unit = rec.get("unit") or {}
            w = unit.get("word")
            key = (unit.get("readout") or {}).get("key")
            if w in matrix and key:
                if status == "done":
                    matrix[w][key] = dict(rec.get("result") or {},
                                          status="done")
                else:
                    matrix[w].setdefault(key, {"status": "quarantined"})

    _scan(spool.done_dir, "done")
    _scan(spool.quarantined_dir, "quarantined")
    complete = all(k in matrix[w] for w in words for k in spec.keys)
    return {"version": GRID_ARTIFACT_VERSION, "release": spec.release,
            "words": list(words), "cells": list(spec.keys),
            "complete": complete, "matrix": matrix}


def latent_pools(matrix: Dict[str, Any]) -> Dict[str, List[int]]:
    """Per-cell latent pool for the attack search: the union (sorted) of
    every word's top latents at that cell."""
    pools: Dict[str, List[int]] = {}
    for _w, cells in sorted(matrix.get("matrix", {}).items()):
        for key, res in sorted(cells.items()):
            ids = res.get("top_latents") if isinstance(res, dict) else None
            if ids:
                pools.setdefault(key, [])
                pools[key] = sorted(set(pools[key]) | set(int(i) for i in ids))
    return pools


# ---------------------------------------------------------------------------
# Selfcheck: the CI smoke (tools/check.sh) — tiny model, 2x2 synthetic
# grid, one injected grid.cell fault, asserts exactly-once + ledger.
# ---------------------------------------------------------------------------


def selfcheck(out_dir: Optional[str] = None) -> Dict[str, Any]:
    """Grid chaos smoke: 2 words x 2x2 synthetic cells through 2 fleet
    workers with ONE transient ``grid.cell`` fault injected into a named
    cell.  Asserts every cell committed exactly once, the matrix is
    complete, and the merged failure ledger records the retried unit.
    Raises AssertionError on violation; returns a summary dict."""
    import sys
    import tempfile

    from taboo_brittleness_tpu.models import gemma2
    from taboo_brittleness_tpu.runtime import fleet
    from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

    root = out_dir or tempfile.mkdtemp(prefix="tbx_grid_selfcheck_")
    words = ["ship", "moon"]
    spec = GridSpec.build([1, 2], [32, 64], release="synthetic")
    seed, max_new = 7, 4

    cfg = gemma2.PRESETS["gemma2_tiny"]
    params = gemma2.init_params(jax.random.PRNGKey(seed), cfg)
    tok = WordTokenizer(
        words + ["Give", "me", "a", "hint", "about", "the", "word"],
        vocab_size=cfg.vocab_size)
    resid_dir = os.path.join(root, RESID_DIRNAME)
    for w in words:
        capture_word_residuals(params, cfg, tok, w, spec,
                               max_new_tokens=max_new, resid_dir=resid_dir)

    units = grid_units(spec, words)
    faulted_uid = units[0]["uid"]
    # Match the full "<word>@<cell>" context value: exactly ONE cell ever
    # fires, whichever worker claims it.
    plan = {"grid.cell": [{"mode": "fail", "times": 1, "kind": "transient",
                           "match": f"{words[0]}@{spec.cells[0].key}"}]}
    env = {"JAX_PLATFORMS": "cpu", "TABOO_FAULT_PLAN": json.dumps(plan),
           "TBX_OBS_PROGRESS_S": "0.2", "TBX_SUPERVISE_BACKOFF_S": "0"}

    def argv(wid: str) -> List[str]:
        return [sys.executable, "-m", "taboo_brittleness_tpu", "worker",
                "--fleet-dir", root, "--worker-id", wid]

    res = fleet.run_fleet(
        units, root, n_workers=2, worker_argv=argv, worker_env=env,
        spool_config={"mode": "grid", "words": words,
                      "grid": spec.to_dict(), "resid_dir": resid_dir,
                      "seed": seed, "top_k": 4, "max_new_tokens": max_new},
        lease_s=3.0, poll_s=0.2, supervise_poll=0.2, grace=2.0,
        wedge_after=30.0, max_incarnations=4, spec_factor=0.0,
        policy=fleet.RetryPolicy(max_retries=6, base_delay=0.0),
        max_wall_s=600.0)

    spool = fleet.FleetSpool(os.path.join(root, fleet.SPOOL_DIRNAME))
    done = spool.done_uids()
    assert res.status == "done" and res.exit_code == 0, res.to_dict()
    assert sorted(done) == sorted(u["uid"] for u in units), (
        f"exactly-once violated: {sorted(done)}")
    matrix = assemble_matrix(root, spec, words)
    assert matrix["complete"], matrix
    # The injected fault must show up as a RETRY in the merged ledger (the
    # cell still committed — transient), never as a quarantine.
    with open(os.path.join(root, "_failures.json")) as f:
        ledger = json.load(f)
    retried = set(ledger.get("retried", {}))
    assert faulted_uid in retried, (
        f"injected grid.cell fault not in ledger retried={sorted(retried)}")
    assert not ledger.get("quarantined"), ledger
    return {"selfcheck": "ok", "units": res.units_total,
            "committed": res.committed, "retried": sorted(retried),
            "complete": matrix["complete"],
            "faulted": faulted_uid}


def main_selfcheck() -> int:
    out = selfcheck()
    # tbx: TBX009-ok — CLI stdout contract (selfcheck verdict JSON)
    print(json.dumps(out))
    return 0
