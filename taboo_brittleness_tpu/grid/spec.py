"""Grid schema: which (layer, width) SAE readout cells exist.

A :class:`GridSpec` is the static shape of one sweep: the Gemma-Scope
release it reads, the :class:`CellSpec` cells (one per (layer, width)
pair), and — derived — the tuple of residual tap layers ONE decode pass
must capture (``runtime.decode.generate(capture_residual_layer=taps)``).

Cell SAE parameters arrive by one of two routes:

- **converted artifacts** (real runs): ``tools/convert_gemma_scope.py
  --cells`` writes one ``.npz`` per cell carrying a versioned header
  (``__grid_version__``/``__sae_id__``/``__layer__``/``__width__``)
  next to the canonical W_enc/b_enc/W_dec/b_dec/threshold arrays;
  :func:`load_cell_sae` validates the header against the cell before
  trusting the weights (a stale or mislabeled artifact must fail loudly,
  not silently score the wrong layer).
- **synthetic** (tests, selfcheck, bench): :func:`synthetic_cell_sae`
  derives a deterministic random JumpReLU SAE from (seed, layer, width)
  — identical across processes, so fleet workers agree on what cell
  ``L1-W32`` means without shipping arrays (the same contract as
  ``serve.loadgen.synthetic_word_params``).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

#: Version stamp written into every per-cell artifact and the residual
#: capture npz; loaders reject anything else (schema drift must not be
#: silently reinterpreted).
GRID_ARTIFACT_VERSION = 1

#: Header keys riding in each converted cell npz, next to the SAE arrays
#: (``ops.sae.from_numpy_state`` ignores unknown keys, so the header and
#: the weights share one file).
HEADER_KEYS = ("__grid_version__", "__sae_id__", "__layer__", "__width__")


def width_tag(width: int) -> str:
    """Gemma-Scope width folder tag: 16384 -> ``16k``, 131072 -> ``128k``."""
    w = int(width)
    if w >= 1024 and w % 1024 == 0:
        return f"{w // 1024}k"
    return str(w)


def default_sae_id(layer: int, width: int) -> str:
    """Release subfolder for a cell when none is given explicitly.  The
    official release names leaves ``average_l0_<x>`` with per-cell x; the
    converter resolves ``canonical`` to whatever single leaf exists under
    ``layer_<L>/width_<tag>/``."""
    return f"layer_{int(layer)}/width_{width_tag(width)}/canonical"


@dataclasses.dataclass(frozen=True)
class CellSpec:
    """One (layer, width) readout cell of the grid."""

    layer: int
    width: int
    sae_id: str = ""
    path: Optional[str] = None   # converted npz artifact; None = synthetic

    @property
    def key(self) -> str:
        """Filesystem/unit-id-safe cell key (``fleet.unit_id`` readout key)."""
        return f"L{self.layer}-W{width_tag(self.width)}"

    def to_dict(self) -> Dict[str, Any]:
        return {"layer": self.layer, "width": self.width,
                "sae_id": self.sae_id, "path": self.path, "key": self.key}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "CellSpec":
        return cls(layer=int(d["layer"]), width=int(d["width"]),
                   sae_id=str(d.get("sae_id") or ""),
                   path=d.get("path") or None)


@dataclasses.dataclass(frozen=True)
class GridSpec:
    """The static shape of one grid sweep."""

    release: str
    cells: Tuple[CellSpec, ...]

    @property
    def tap_layers(self) -> Tuple[int, ...]:
        """Sorted unique residual tap layers — the static tuple one decode
        pass captures (``capture_residual_layer=spec.tap_layers``)."""
        return tuple(sorted({c.layer for c in self.cells}))

    @property
    def keys(self) -> Tuple[str, ...]:
        return tuple(c.key for c in self.cells)

    def cell(self, key: str) -> CellSpec:
        for c in self.cells:
            if c.key == key:
                return c
        raise KeyError(f"no grid cell {key!r}; have {list(self.keys)}")

    def slot_of(self, cell: CellSpec) -> int:
        """Index of ``cell``'s layer in the captured [K, B, T, D] stack."""
        return self.tap_layers.index(cell.layer)

    def to_dict(self) -> Dict[str, Any]:
        return {"version": GRID_ARTIFACT_VERSION, "release": self.release,
                "cells": [c.to_dict() for c in self.cells]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "GridSpec":
        ver = int(d.get("version", GRID_ARTIFACT_VERSION))
        if ver != GRID_ARTIFACT_VERSION:
            raise ValueError(
                f"grid spec version {ver} != {GRID_ARTIFACT_VERSION}")
        return cls(release=str(d.get("release") or ""),
                   cells=tuple(CellSpec.from_dict(c) for c in d["cells"]))

    @classmethod
    def build(cls, layers: Sequence[int], widths: Sequence[int], *,
              release: str = "", artifact_dir: Optional[str] = None,
              sae_ids: Optional[Dict[Tuple[int, int], str]] = None,
              ) -> "GridSpec":
        """The layer x width cross product.  With ``artifact_dir``, each
        cell points at ``<dir>/<key>.npz`` (the converter's layout); without
        it cells are synthetic."""
        ids = sae_ids or {}
        cells: List[CellSpec] = []
        for la in layers:
            for w in widths:
                sid = ids.get((int(la), int(w))) or default_sae_id(la, w)
                path = None
                if artifact_dir:
                    path = os.path.join(
                        artifact_dir, f"L{int(la)}-W{width_tag(w)}.npz")
                cells.append(CellSpec(layer=int(la), width=int(w),
                                      sae_id=sid, path=path))
        if not cells:
            raise ValueError("empty grid (no layers x widths)")
        return cls(release=release, cells=tuple(cells))

    @classmethod
    def from_config(cls, config, *, layers: Optional[Sequence[int]] = None,
                    widths: Optional[Sequence[int]] = None,
                    artifact_dir: Optional[str] = None) -> "GridSpec":
        """Default grid from the run config: the paper's single
        (layer_idx, sae.width) cell unless ``layers``/``widths`` widen it."""
        layers = list(layers) if layers else [config.model.layer_idx]
        widths = list(widths) if widths else [config.sae.width]
        ids = {}
        if (len(layers), len(widths)) == (1, 1):
            ids[(int(layers[0]), int(widths[0]))] = config.sae.sae_id
        return cls.build(layers, widths, release=config.sae.release,
                         artifact_dir=artifact_dir, sae_ids=ids)


# ---------------------------------------------------------------------------
# Cell SAE loading.
# ---------------------------------------------------------------------------


def validate_cell_header(state: Dict[str, np.ndarray], cell: CellSpec,
                         *, path: str = "<npz>") -> None:
    """Reject a cell artifact whose header doesn't match the cell.  Raises
    ValueError with the precise mismatch (the converter wrote the header,
    so any mismatch means the file is stale or misplaced)."""
    missing = [k for k in HEADER_KEYS if k not in state]
    if missing:
        raise ValueError(
            f"{path}: not a grid cell artifact (missing header {missing}; "
            "re-run tools/convert_gemma_scope.py --cells)")
    ver = int(np.asarray(state["__grid_version__"]))
    if ver != GRID_ARTIFACT_VERSION:
        raise ValueError(f"{path}: grid artifact version {ver} != "
                         f"{GRID_ARTIFACT_VERSION}")
    layer = int(np.asarray(state["__layer__"]))
    width = int(np.asarray(state["__width__"]))
    if (layer, width) != (cell.layer, cell.width):
        raise ValueError(
            f"{path}: header says layer={layer} width={width}, cell wants "
            f"layer={cell.layer} width={cell.width}")


def load_cell_sae(cell: CellSpec, dtype=None):
    """Load a converted cell artifact, validating its versioned header
    against the cell before trusting the weights."""
    from taboo_brittleness_tpu.ops import sae as sae_ops

    if not cell.path:
        raise ValueError(f"cell {cell.key} has no artifact path "
                         "(synthetic cells use synthetic_cell_sae)")
    with np.load(cell.path) as data:
        state = {k: np.asarray(data[k]) for k in data.files}
    validate_cell_header(state, cell, path=cell.path)
    kwargs = {} if dtype is None else {"dtype": dtype}
    sae = sae_ops.from_numpy_state(state, **kwargs)
    if sae.d_sae != cell.width:
        raise ValueError(f"{cell.path}: d_sae={sae.d_sae} != cell width "
                         f"{cell.width}")
    return sae


def synthetic_cell_sae(cell: CellSpec, d_model: int, *, seed: int = 7):
    """Deterministic random SAE for a synthetic cell, seeded from the CELL
    ITSELF so every fleet worker derives identical weights."""
    import jax

    from taboo_brittleness_tpu.ops import sae as sae_ops

    key = jax.random.PRNGKey(
        (int(seed) * 1_000_003 + cell.layer * 1009 + cell.width)
        & 0x7FFFFFFF)
    return sae_ops.init_random(key, d_model, cell.width)


def cell_sae(cell: CellSpec, d_model: int, *, seed: int = 7):
    """Route: converted artifact when the cell has a path, synthetic
    otherwise — the single entry the runner/worker uses."""
    if cell.path:
        return load_cell_sae(cell)
    return synthetic_cell_sae(cell, d_model, seed=seed)
