"""tbx-check core: findings, suppression pragmas, per-module AST context.

Everything here is stdlib-only (``ast`` + ``re``): the static pass must cost
milliseconds and run before jax is even importable (e.g. in a container that
only has the checker).  The jaxpr-level pass lives in ``deep.py``.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a file line (or a deep-mode entry)."""

    path: str        # repo-relative posix path, or "<deep:entry>" for jaxpr findings
    line: int        # 1-based; 0 for deep-mode findings
    col: int
    code: str        # "TBX001"
    alias: str       # "host-sync" — usable in pragmas interchangeably with code
    message: str
    snippet: str = ""  # stripped source line: the line-number-free fingerprint basis
    scope: str = ""    # module-relative qualname of the enclosing def/class
    #                    ("TimeseriesRecorder.stop"); "" at module level.  The
    #                    path-free half of the baseline fingerprint, so a pure
    #                    file move does not churn the ratchet.

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} [{self.alias}] {self.message}"


# ---------------------------------------------------------------------------
# Suppression pragmas.
# ---------------------------------------------------------------------------

# ``# tbx: f32-ok — reason`` / ``# tbx: TBX002-ok, TBX001-ok: reason``.
# Tokens are <code-or-alias>-ok; anything after them is the (recommended)
# one-line justification.  A trailing pragma suppresses its own line; a
# pragma inside a comment block suppresses the first code line after the
# block (so multi-line justifications work wherever the tbx line sits).
_PRAGMA_LINE_RE = re.compile(r"#\s*tbx:\s*(?P<body>.+)$")
_PRAGMA_TOKEN_RE = re.compile(r"([A-Za-z0-9]+(?:-[A-Za-z0-9]+)*)-ok\b")


def parse_pragmas(lines: Sequence[str]) -> Dict[int, Set[str]]:
    """Map 1-based line number -> set of suppressed rule tokens (codes or
    aliases, lowercased; the literal token ``all`` suppresses every rule)."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(lines, start=1):
        m = _PRAGMA_LINE_RE.search(line)
        if not m:
            continue
        tokens = {t.lower() for t in _PRAGMA_TOKEN_RE.findall(m.group("body"))}
        if not tokens:
            continue
        out.setdefault(i, set()).update(tokens)
        if line.strip().startswith("#"):
            # Comment-only pragma: walk past the rest of the comment block so
            # it covers the statement the block documents.
            j = i
            while j < len(lines) and lines[j].strip().startswith("#"):
                j += 1
            out.setdefault(j + 1, set()).update(tokens)
    return out


def is_suppressed(finding: Finding, pragmas: Dict[int, Set[str]]) -> bool:
    tokens = pragmas.get(finding.line, ())
    return ("all" in tokens or finding.code.lower() in tokens
            or finding.alias.lower() in tokens)


# ---------------------------------------------------------------------------
# Import alias resolution + dotted names.
# ---------------------------------------------------------------------------

def import_aliases(tree: ast.AST) -> Dict[str, str]:
    """Local name -> fully dotted origin (``jnp`` -> ``jax.numpy``, ``P`` ->
    ``jax.sharding.PartitionSpec``, ``partial`` -> ``functools.partial``)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def dotted(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Dotted origin of a Name/Attribute chain, alias-expanded; None for
    anything that is not a plain chain (calls, subscripts, ...)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(aliases.get(node.id, node.id))
    return ".".join(reversed(parts))


# ---------------------------------------------------------------------------
# Jit bindings (how a function became a trace root).
# ---------------------------------------------------------------------------

JIT_WRAPPERS = {
    "jax.jit", "jax.pjit", "jax.pmap",
    "jax.experimental.pjit.pjit",
}
PARTIAL_NAMES = {"functools.partial"}


@dataclasses.dataclass
class JitBinding:
    """One ``fn`` <- jit association: a decorator (``@jax.jit``,
    ``@partial(jax.jit, ...)``) or a module-level ``g = jax.jit(fn, ...)``."""

    fn: ast.FunctionDef
    call: Optional[ast.Call]   # None for the bare @jax.jit decorator form
    line: int
    col: int

    def keyword(self, name: str) -> Optional[ast.expr]:
        if self.call is None:
            return None
        for kw in self.call.keywords:
            if kw.arg == name:
                return kw.value
        return None

    def has_keyword(self, *names: str) -> bool:
        return any(self.keyword(n) is not None for n in names)


class ModuleContext:
    """Parsed module + everything the rules need: alias map, jit bindings,
    and the set of functions reachable from a trace root (module-local call
    graph by name; nested defs inherit their parent's reachability)."""

    def __init__(self, path: str, source: str, rel: Optional[str] = None):
        self.path = path
        self.rel = rel or path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.aliases = import_aliases(self.tree)
        self.pragmas = parse_pragmas(self.lines)

        self.functions: List[ast.FunctionDef] = []
        self.parents: Dict[ast.AST, Optional[ast.FunctionDef]] = {}
        self.module_funcs: Dict[str, ast.FunctionDef] = {}
        self._index_functions()
        self._scopes: List[Tuple[int, int, str]] = []
        self._index_scopes()

        self.jit_bindings: List[JitBinding] = []
        self._collect_jit_bindings()
        self.traced: Set[ast.FunctionDef] = self._traced_closure()

    # -- indexing ----------------------------------------------------------

    def _index_functions(self) -> None:
        def visit(node: ast.AST, parent: Optional[ast.FunctionDef]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.functions.append(child)
                    self.parents[child] = parent
                    if parent is None:
                        self.module_funcs[child.name] = child
                    visit(child, child)
                else:
                    visit(child, parent)

        visit(self.tree, None)

    def _index_scopes(self) -> None:
        """Source spans of every def/class, with module-relative qualnames
        (``Cls.method``, ``outer.<locals>-free: just dotted names).  Used to
        stamp findings with a path-free anchor for baseline fingerprints."""
        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    qual = f"{prefix}.{child.name}" if prefix else child.name
                    end = getattr(child, "end_lineno", child.lineno)
                    self._scopes.append((child.lineno, end, qual))
                    visit(child, qual)
                else:
                    visit(child, prefix)

        visit(self.tree, "")

    def scope_of(self, lineno: int) -> str:
        """Qualname of the innermost def/class containing ``lineno`` ("" at
        module level)."""
        best = ""
        best_start = 0
        for start, end, qual in self._scopes:
            if start <= lineno <= end and start >= best_start:
                best, best_start = qual, start
        return best

    def dotted(self, node: ast.AST) -> Optional[str]:
        return dotted(node, self.aliases)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, node: ast.AST, code: str, alias: str, message: str) -> Finding:
        line = getattr(node, "lineno", 0)
        return Finding(path=self.rel, line=line,
                       col=getattr(node, "col_offset", 0) + 1,
                       code=code, alias=alias, message=message,
                       snippet=self.line_text(line), scope=self.scope_of(line))

    # -- jit bindings ------------------------------------------------------

    def _jit_call(self, node: ast.expr) -> Optional[ast.Call]:
        """The jit Call carrying the kwargs, if ``node`` is a jit wrapper
        expression: ``jax.jit(...)`` or ``partial(jax.jit, ...)``."""
        if not isinstance(node, ast.Call):
            return None
        fn = self.dotted(node.func)
        if fn in JIT_WRAPPERS:
            return node
        if fn in PARTIAL_NAMES and node.args:
            if self.dotted(node.args[0]) in JIT_WRAPPERS:
                return node
        return None

    def _collect_jit_bindings(self) -> None:
        for fn in self.functions:
            for deco in fn.decorator_list:
                if self.dotted(deco) in JIT_WRAPPERS:
                    self.jit_bindings.append(JitBinding(
                        fn=fn, call=None, line=deco.lineno,
                        col=deco.col_offset + 1))
                    continue
                call = self._jit_call(deco)
                if call is not None:
                    self.jit_bindings.append(JitBinding(
                        fn=fn, call=call, line=deco.lineno,
                        col=deco.col_offset + 1))
        # g = jax.jit(fn, ...) form (module level or inside functions).
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if self.dotted(node.func) in JIT_WRAPPERS and node.args:
                target = node.args[0]
                if isinstance(target, ast.Name):
                    fn = self.module_funcs.get(target.id)
                    if fn is not None:
                        self.jit_bindings.append(JitBinding(
                            fn=fn, call=node, line=node.lineno,
                            col=node.col_offset + 1))

    # -- traced reachability ----------------------------------------------

    def _loaded_names(self, fn: ast.FunctionDef) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                names.add(node.id)
        return names

    def _traced_closure(self) -> Set[ast.FunctionDef]:
        """Trace roots + the module-local by-name call-graph closure, plus
        every function *defined inside* a traced function (its body runs
        under the trace)."""
        roots = {b.fn for b in self.jit_bindings}
        traced: Set[ast.FunctionDef] = set()
        frontier = list(roots)
        while frontier:
            fn = frontier.pop()
            if fn in traced:
                continue
            traced.add(fn)
            # Nested defs run under the same trace.
            for other in self.functions:
                if self.parents.get(other) is fn:
                    frontier.append(other)
            # Module-level functions referenced by name (called or passed to
            # lax.scan / vmap / ...) are traced too.
            for name in self._loaded_names(fn):
                callee = self.module_funcs.get(name)
                if callee is not None and callee not in traced:
                    frontier.append(callee)
        return traced

    def enclosing_traced(self, node: ast.AST) -> Optional[ast.FunctionDef]:
        """The innermost traced function whose source span contains ``node``
        (AST nodes don't carry parent pointers; spans are cheap and exact
        here because functions nest strictly)."""
        line = getattr(node, "lineno", None)
        if line is None:
            return None
        best: Optional[ast.FunctionDef] = None
        for fn in self.traced:
            end = getattr(fn, "end_lineno", None)
            if end is None:
                continue
            if fn.lineno <= line <= end:
                if best is None or fn.lineno >= best.lineno:
                    best = fn
        return best


def analyze_file(path: str, rel: Optional[str] = None,
                 rules: Optional[Iterable] = None,
                 repo=None) -> Tuple[List[Finding], List[Finding]]:
    """Run the AST rules over one file.  Returns (active, suppressed)."""
    from taboo_brittleness_tpu.analysis.rules import RULES, RepoContext

    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    try:
        ctx = ModuleContext(path, source, rel=rel)
    except SyntaxError as e:
        f_err = Finding(path=rel or path, line=e.lineno or 0, col=e.offset or 0,
                        code="TBX000", alias="syntax",
                        message=f"file does not parse: {e.msg}")
        return [f_err], []
    repo = repo if repo is not None else RepoContext.discover([path])
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for rule in (rules if rules is not None else RULES):
        for finding in rule.check(ctx, repo):
            (suppressed if is_suppressed(finding, ctx.pragmas)
             else active).append(finding)
    active.sort(key=lambda f: (f.line, f.col, f.code))
    suppressed.sort(key=lambda f: (f.line, f.col, f.code))
    return active, suppressed
