"""Baseline (ratchet) engine for tbx-check findings.

A baseline is a JSON file of finding *fingerprints*: line-number-free hashes
of (path, rule, source snippet), so unrelated edits above a known finding do
not churn the file.  Workflow:

    python -m taboo_brittleness_tpu.analysis --write-baseline tools/tbx_baseline.json ...
    python -m taboo_brittleness_tpu.analysis --baseline tools/tbx_baseline.json ...

``--baseline`` filters known findings out of the gate; anything NEW still
fails.  Deep-mode (jaxpr) findings baseline the same way — their "path" is
the entry-point name and their snippet the conversion description, both
stable across line edits.
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, Iterable, List, Set, Tuple

from taboo_brittleness_tpu.analysis.core import Finding


def fingerprint(finding: Finding) -> str:
    basis = f"{finding.path}::{finding.code}::{finding.snippet or finding.message}"
    return hashlib.sha1(basis.encode("utf-8")).hexdigest()[:16]


def save(findings: Iterable[Finding], path: str) -> int:
    entries = {}
    for f in findings:
        fp = fingerprint(f)
        # Keep one human-readable locator per fingerprint (the hash alone
        # would make the committed file unreviewable).
        entries.setdefault(fp, {
            "rule": f.code, "path": f.path, "summary": f.message[:120]})
    doc = {"version": 1, "findings": entries}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return len(entries)


def load(path: str) -> Set[str]:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or "findings" not in doc:
        raise ValueError(f"{path}: not a tbx-check baseline file")
    return set(doc["findings"])


def split(findings: List[Finding],
          known: Set[str]) -> Tuple[List[Finding], List[Finding]]:
    """(new, baselined) partition of ``findings`` against a baseline set."""
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        (old if fingerprint(f) in known else new).append(f)
    return new, old
