"""Baseline (ratchet) engine for tbx-check findings.

A baseline is a JSON file of finding *fingerprints*: hashes of
``(rule, module-relative qualname, normalized snippet)``.  Line numbers AND
directory paths are both excluded, so neither unrelated edits above a known
finding nor a pure file move churn the committed file.  Workflow:

    python -m taboo_brittleness_tpu.analysis --write-baseline tools/tbx_baseline.json ...
    python -m taboo_brittleness_tpu.analysis --baseline tools/tbx_baseline.json ...

``--baseline`` filters known findings out of the gate; anything NEW still
fails.  Deep-mode (jaxpr) findings baseline the same way — they carry no
scope, so their synthetic ``<deep:entry>`` path anchors the hash instead,
and their snippet is the conversion description: both stable across edits.
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, Iterable, List, Set, Tuple

from taboo_brittleness_tpu.analysis.core import Finding

VERSION = 2


def fingerprint(finding: Finding) -> str:
    # Anchor on the in-module qualname when we have one; synthetic paths
    # ("<deep:...>") are already location-free and stay as-is.  Real-file
    # module-level findings anchor on "" — the normalized snippet + rule is
    # identity enough, and it is what makes a pure rename a no-op.
    if finding.path.startswith("<"):
        anchor = finding.path
    else:
        anchor = finding.scope
    snippet = " ".join((finding.snippet or finding.message).split())
    basis = f"{finding.code}::{anchor}::{snippet}"
    return hashlib.sha1(basis.encode("utf-8")).hexdigest()[:16]


def save(findings: Iterable[Finding], path: str) -> int:
    entries = {}
    for f in findings:
        fp = fingerprint(f)
        # Keep one human-readable locator per fingerprint (the hash alone
        # would make the committed file unreviewable).  ``path`` is advisory
        # only — it is NOT part of the hash.
        entries.setdefault(fp, {
            "rule": f.code, "path": f.path, "scope": f.scope,
            "summary": f.message[:120]})
    doc = {"version": VERSION, "findings": entries}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return len(entries)


def load(path: str) -> Set[str]:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or "findings" not in doc:
        raise ValueError(f"{path}: not a tbx-check baseline file")
    if doc.get("version", 1) != VERSION:
        raise ValueError(
            f"{path}: baseline version {doc.get('version')} != {VERSION}; "
            "regenerate with --write-baseline (v2 keys on rule+scope+snippet "
            "so file moves do not churn the ratchet)")
    return set(doc["findings"])


def split(findings: List[Finding],
          known: Set[str]) -> Tuple[List[Finding], List[Finding]]:
    """(new, baselined) partition of ``findings`` against a baseline set."""
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        (old if fingerprint(f) in known else new).append(f)
    return new, old
