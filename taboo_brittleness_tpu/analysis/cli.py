"""tbx-check CLI.

    python -m taboo_brittleness_tpu.analysis [--deep] [--baseline FILE]
        [--write-baseline FILE] [--list-rules] [paths...]

Exit codes: 0 clean (every finding fixed, pragma-suppressed, or baselined),
1 unsuppressed findings, 2 usage/IO error.  The default path set is the
package itself; CI runs it over ``taboo_brittleness_tpu/ tools/ tests/``
(see tools/check.sh).
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
from typing import List, Optional, Sequence

from taboo_brittleness_tpu.analysis import baseline as baseline_mod
from taboo_brittleness_tpu.analysis.core import Finding, analyze_file
from taboo_brittleness_tpu.analysis.rules import RULES, RepoContext

# The checker's own violation corpus: every file seeds exactly the hazard its
# rule must catch, so scanning it would fail the gate by design.
DEFAULT_EXCLUDES = ("tests/fixtures/analysis",)


@dataclasses.dataclass
class Report:
    findings: List[Finding]        # active (unsuppressed, unbaselined)
    suppressed: List[Finding]      # pragma'd out
    baselined: List[Finding]       # filtered by --baseline
    files_checked: int

    @property
    def clean(self) -> bool:
        return not self.findings


def _norm(path: str) -> str:
    return os.path.relpath(path).replace(os.sep, "/")


def iter_python_files(paths: Sequence[str],
                      default_excludes: bool = True) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs.sort()
                for name in sorted(names):
                    if name.endswith(".py"):
                        files.append(os.path.join(root, name))
        elif os.path.isfile(p):
            files.append(p)
        else:
            raise FileNotFoundError(p)
    out = []
    for f in files:
        rel = _norm(f)
        if default_excludes and any(ex in rel for ex in DEFAULT_EXCLUDES):
            continue
        if rel not in out:
            out.append(rel)
    return out


def run_check(paths: Sequence[str], *, deep: bool = False,
              conc: bool = True,
              baseline: Optional[str] = None,
              default_excludes: bool = True,
              rules=None) -> Report:
    """Programmatic entry point (tests/test_analysis.py uses this)."""
    files = iter_python_files(paths, default_excludes=default_excludes)
    repo = RepoContext.discover(files)
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for f in files:
        a, s = analyze_file(f, rel=_norm(f), rules=rules, repo=repo)
        active.extend(a)
        suppressed.extend(s)
    if conc:
        from taboo_brittleness_tpu.analysis.conc import run_conc

        a, s = run_conc(files)
        active.extend(a)
        suppressed.extend(s)
    if deep:
        from taboo_brittleness_tpu.analysis.deep import run_deep

        active.extend(run_deep())
    baselined: List[Finding] = []
    if baseline is not None:
        known = baseline_mod.load(baseline)
        active, baselined = baseline_mod.split(active, known)
    active.sort(key=lambda x: (x.path, x.line, x.col, x.code))
    return Report(findings=active, suppressed=suppressed,
                  baselined=baselined, files_checked=len(files))


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m taboo_brittleness_tpu.analysis",
        description="tbx-check: JAX/TPU-aware static analysis gate "
                    "(rules TBX001..TBX010 plus the whole-program "
                    "host-concurrency pass TBX201..TBX206; --deep adds "
                    "the jaxpr pass).")
    ap.add_argument("paths", nargs="*", default=["taboo_brittleness_tpu"],
                    help="files or directories (default: the package)")
    ap.add_argument("--deep", action="store_true",
                    help="also trace the registered jit entry points and "
                         "audit their jaxprs for vocab-dim f32 "
                         "materialization (imports jax)")
    ap.add_argument("--conc", dest="conc", action="store_true", default=True,
                    help="run the whole-program host-concurrency pass "
                         "(TBX201..TBX206); on by default")
    ap.add_argument("--no-conc", dest="conc", action="store_false",
                    help="skip the concurrency pass (static AST rules only)")
    ap.add_argument("--baseline", metavar="FILE",
                    help="filter findings already recorded in FILE")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="record current findings to FILE and exit 0")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("--no-default-excludes", action="store_true",
                    help="also scan the checker's own violation corpus "
                         f"(default excludes: {', '.join(DEFAULT_EXCLUDES)})")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="print only the summary line")
    args = ap.parse_args(argv)

    if args.list_rules:
        from taboo_brittleness_tpu.analysis.conc import CONC_RULES

        for rule in RULES:
            print(f"{rule.code}  {rule.alias:<14} {rule.summary}")
        for rule in CONC_RULES:
            print(f"{rule.code}  {rule.alias:<14} [--conc] {rule.summary}")
        print("TBX100  deep-entry     [--deep] entry point failed to trace")
        print("TBX101  deep-f32       [--deep] jaxpr f32 materialization on "
              "a vocab-dim operand")
        return 0

    try:
        report = run_check(
            args.paths, deep=args.deep, conc=args.conc,
            baseline=args.baseline,
            default_excludes=not args.no_default_excludes)
    except (FileNotFoundError, ValueError) as e:
        print(f"tbx-check: error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        n = baseline_mod.save(report.findings, args.write_baseline)
        print(f"tbx-check: wrote {n} fingerprint(s) to {args.write_baseline}")
        return 0

    if not args.quiet:
        for f in report.findings:
            print(f.format())
    print(f"tbx-check: {report.files_checked} file(s), "
          f"{len(report.findings)} finding(s) "
          f"({len(report.suppressed)} suppressed, "
          f"{len(report.baselined)} baselined)")
    if report.findings and not args.quiet:
        print("  fix, suppress with `# tbx: <rule>-ok — <reason>`, or ratchet "
              "with --write-baseline/--baseline", file=sys.stderr)
    return 0 if report.clean else 1
