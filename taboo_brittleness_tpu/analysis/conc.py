"""tbx-check conc: whole-program host-concurrency + runtime-contract pass.

The device side of the repo is covered by the per-module AST rules
(TBX001–TBX010) and the jaxpr deep pass (TBX10x).  This module covers the
*host* side: the threads, locks, signal handlers, durable-artifact writers,
and the ``FAULT_SITES`` registry that grew across the resilience / fleet /
telemetry PRs.  Unlike ``rules.py`` it is whole-program: it parses every
package module into one :class:`ConcModel` and checks cross-module
invariants against it.

Rule family (pragmas ``# tbx: <code-or-alias>-ok — reason`` and baseline
fingerprints work exactly like TBX001–TBX010):

TBX201  thread-shared   attribute written on one side of a thread boundary
                        and read on the other with no common lock
TBX202  signal-handler  handler's reachable call graph acquires a lock,
                        performs I/O, or emits telemetry (handlers may only
                        set latches/Events — the PR-5 self-deadlock class)
TBX203  lock-order      cycle in the lock acquisition-order graph
TBX204  thread-leak     thread started with no reachable join path (the
                        PR-2 skipped-word prefetch leak class)
TBX205  atomic-write    durable artifact written via bare ``open(.., "w")``
                        instead of the tmp+``os.replace`` protocol
TBX206  fault-site      FAULT_SITES contract drift: fired-but-unregistered,
                        registered-but-never-fired, or never armed in tests

Model scope and limits (deliberate, documented in README):

* Only ``taboo_brittleness_tpu/`` modules participate; ``analysis/`` itself
  is exempt (the checker's CLI is its own I/O surface, like TBX009/TBX010).
* The call graph is module-local by name (plus ``self.X()`` within a
  class); threads spawned through executors (``ThreadPoolExecutor``) own
  their lifecycle and are out of TBX204's scope.
* TBX201 reasons per class: the "thread side" is the closure of
  ``threading.Thread(target=...)`` targets over ``self`` calls, the "main
  side" the closure of every other public entry.  Attributes that are
  threading primitives, or never written outside ``__init__``, are exempt.
  A private method whose every intra-class call site holds a lock is
  treated as lock-protected (how ``roll()``-style daemons factor helpers).
* TBX204 join evidence is token-based with aliasing: ``t, self._thread =
  self._thread, None; t.join()``, ``threads.append(t)`` + loop-join, and
  ``self._pending.pop(word).join()`` all count.
* TBX205 covers the builtin ``open``; ``os.open(..., O_APPEND)`` whole-line
  spool writes are a sanctioned protocol and not flagged.  A write is
  exempt when its enclosing function also calls ``os.replace``/``os.rename``
  or the path expression mentions ``tmp`` (the atomic idiom itself).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import (Callable, Dict, Iterable, Iterator, List, Optional,
                    Sequence, Set, Tuple)

from taboo_brittleness_tpu.analysis.core import (
    Finding, ModuleContext, is_suppressed)

_PKG_MARKER = "taboo_brittleness_tpu/"
_EXEMPT_MARKER = "taboo_brittleness_tpu/analysis/"

_THREAD_CTOR = "threading.Thread"
_SYNC_CTORS = {
    "threading.Thread", "threading.Lock", "threading.RLock",
    "threading.Event", "threading.Condition", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Barrier", "threading.local",
    "concurrent.futures.ThreadPoolExecutor",
}
_LOCK_NAME_RE = re.compile(r"(^|_)(lock|mutex|rlock)s?$", re.IGNORECASE)
_MUTATORS = {"append", "add", "extend", "insert", "update", "setdefault",
             "pop", "popleft", "remove", "discard", "clear", "appendleft"}
_IO_CALLS = {
    "open", "print", "os.write", "os.remove", "os.unlink", "os.replace",
    "os.rename", "os.makedirs", "os.rmdir", "os.truncate", "shutil.rmtree",
    "shutil.copy", "shutil.move", "json.dump", "sys.stdout.write",
    "sys.stderr.write", "sys.stdout.flush", "sys.stderr.flush",
}
_TELEMETRY_ATTRS = {"event", "warn", "emit", "record", "dump", "observe",
                    "inc", "set_gauge"}
_TELEMETRY_RECV_RE = re.compile(r"obs|trace|metric|flight|telemetry",
                                re.IGNORECASE)


def _in_scope(rel: str) -> bool:
    rel = rel.replace(os.sep, "/")
    if _EXEMPT_MARKER in rel:
        return False
    return _PKG_MARKER in rel or rel.startswith("taboo_brittleness_tpu")


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _root_token(node: ast.AST) -> Optional[Tuple[str, str]]:
    """Peel calls/subscripts/attribute chains down to a stable token:
    ``("a", attr)`` for a ``self.attr`` root, ``("n", name)`` for a local
    name.  ``self._pending.pop(w)`` -> ("a", "_pending")."""
    while True:
        if isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Attribute):
            a = _self_attr(node)
            if a is not None:
                return ("a", a)
            node = node.value
        elif isinstance(node, ast.Name):
            return ("n", node.id)
        else:
            return None


def _expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


# ---------------------------------------------------------------------------
# Lock-aware walking.
# ---------------------------------------------------------------------------

def _walk_held(fn: ast.AST,
               lock_of: Callable[[ast.AST], Optional[str]],
               on_node: Callable[[ast.AST, Tuple[str, ...]], None],
               on_nested: Callable[[ast.AST, Tuple[str, ...]], None],
               on_acquire: Optional[
                   Callable[[Tuple[str, ...], str, ast.AST], None]] = None,
               ) -> None:
    """Visit ``fn``'s body tracking the stack of held locks through ``with``
    blocks.  Nested function/lambda definitions are reported via
    ``on_nested`` and not descended into (they do not run where they are
    defined).  ``on_acquire(held, lock, site)`` fires when a ``with`` block
    acquires ``lock`` — the hook TBX203 builds its order graph from."""

    def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
        on_node(node, held)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            on_nested(node, held)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in node.items:
                visit(item.context_expr, held)
                if item.optional_vars is not None:
                    visit(item.optional_vars, held)
                lock = lock_of(item.context_expr)
                if lock is not None:
                    if on_acquire is not None:
                        on_acquire(tuple(inner), lock, node)
                    inner.append(lock)
            for stmt in node.body:
                visit(stmt, tuple(inner))
            return
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        body: List[ast.AST] = list(fn.body)
    elif isinstance(fn, ast.Lambda):
        body = [fn.body]
    else:
        body = [fn]
    for stmt in body:
        visit(stmt, ())


# ---------------------------------------------------------------------------
# Per-class concurrency model (TBX201).
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Access:
    attr: str
    write: bool
    locked: bool
    node: ast.AST


@dataclasses.dataclass
class _Call:
    name: str          # self-method name
    locked: bool


class _Unit:
    """One body that can run: a method, or a nested thread-target function
    defined inside a method (which runs on the spawned thread)."""

    def __init__(self, name: str, node: ast.AST, is_target_fn: bool = False):
        self.name = name
        self.node = node
        self.is_target_fn = is_target_fn
        self.accesses: List[_Access] = []
        self.calls: List[_Call] = []


class _ClassModel:
    def __init__(self, mod: "_Module", cls: ast.ClassDef):
        self.mod = mod
        self.cls = cls
        self.name = cls.name
        self.methods: Dict[str, ast.FunctionDef] = {
            s.name: s for s in cls.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.sync_attrs = self._sync_attrs()
        self.target_methods: Set[str] = set()
        self.target_fns: List[Tuple[ast.AST, str]] = []  # (fn node, owner)
        self._find_targets()
        self.units: Dict[str, _Unit] = {}
        self._build_units()
        self._propagate_private_locks()

    # -- attribute classification -----------------------------------------

    def _sync_attrs(self) -> Set[str]:
        """Attributes holding threading primitives (exempt from TBX201):
        assigned from a threading ctor, or annotated as one."""
        out: Set[str] = set()
        for node in ast.walk(self.cls):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                d = self.mod.ctx.dotted(node.value.func)
                if d in _SYNC_CTORS:
                    for t in node.targets:
                        a = _self_attr(t)
                        if a:
                            out.add(a)
            elif isinstance(node, ast.AnnAssign):
                a = _self_attr(node.target)
                if a and any(isinstance(n, (ast.Name, ast.Attribute))
                             and getattr(n, "attr", getattr(n, "id", "")) in
                             ("Thread", "Lock", "RLock", "Event", "Condition")
                             for n in ast.walk(node.annotation)):
                    out.add(a)
        return out

    def _find_targets(self) -> None:
        for name, fn in self.methods.items():
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and self.mod.ctx.dotted(node.func) == _THREAD_CTOR):
                    continue
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    a = _self_attr(kw.value)
                    if a is not None:
                        self.target_methods.add(a)
                    elif isinstance(kw.value, ast.Name):
                        nested = self._nested_def(fn, kw.value.id)
                        if nested is not None:
                            self.target_fns.append((nested, name))

    def _nested_def(self, fn: ast.AST, name: str) -> Optional[ast.AST]:
        for node in ast.walk(fn):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node is not fn and node.name == name):
                return node
        return None

    # -- unit construction -------------------------------------------------

    def _lock_of(self, node: ast.AST) -> Optional[str]:
        a = _self_attr(node)
        if a is not None and (a in self.sync_attrs or _LOCK_NAME_RE.search(a)):
            return f"self.{a}"
        if isinstance(node, ast.Name) and (
                node.id in self.mod.module_locks
                or _LOCK_NAME_RE.search(node.id)):
            return node.id
        return None

    def _collect(self, unit: _Unit, fn: ast.AST) -> None:
        target_nodes = {n for n, _ in self.target_fns}

        def on_node(node: ast.AST, held: Tuple[str, ...]) -> None:
            locked = bool(held)
            if isinstance(node, ast.Attribute):
                a = _self_attr(node)
                if a is None or a in self.methods:
                    return
                write = isinstance(node.ctx, (ast.Store, ast.Del))
                unit.accesses.append(_Access(a, write, locked, node))
            elif isinstance(node, ast.Subscript):
                a = _self_attr(node.value)
                if a is not None and isinstance(node.ctx, (ast.Store, ast.Del)):
                    unit.accesses.append(_Access(a, True, locked, node))
            elif isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute):
                    a = _self_attr(node.func.value)
                    if a is not None and node.func.attr in _MUTATORS:
                        unit.accesses.append(
                            _Access(a, True, locked, node))
                a = _self_attr(node.func)
                if a is not None and a in self.methods:
                    unit.calls.append(_Call(a, locked))

        def on_nested(node: ast.AST, held: Tuple[str, ...]) -> None:
            # Thread-target nested defs get their own unit; other nested
            # defs (callbacks, key fns) are folded into the enclosing unit
            # with a fresh (empty) lock stack — they do not run where they
            # are defined.
            if node in target_nodes:
                return
            _walk_held(node, self._lock_of,
                       on_node, on_nested)

        _walk_held(fn, self._lock_of, on_node, on_nested)

    def _build_units(self) -> None:
        for name, fn in self.methods.items():
            if name == "__init__":
                continue
            unit = _Unit(name, fn)
            self._collect(unit, fn)
            self.units[name] = unit
        for fn, owner in self.target_fns:
            key = f"{owner}.<{fn.name}>"
            unit = _Unit(fn.name, fn, is_target_fn=True)
            self._collect(unit, fn)
            self.units[key] = unit

    def _propagate_private_locks(self) -> None:
        """A method whose every intra-class call site holds a lock is
        lock-protected by convention (``roll()`` factoring ``_collect`` /
        ``_write`` helpers).  Iterate to cover one level of chaining."""
        for _ in range(2):
            for name, fn in self.methods.items():
                unit = self.units.get(name)
                if unit is None or name in self.target_methods:
                    continue
                sites = [c for u in self.units.values()
                         for c in u.calls if c.name == name]
                if sites and all(c.locked for c in sites):
                    for acc in unit.accesses:
                        acc.locked = True
                    for c in unit.calls:
                        c.locked = True

    # -- side closures -----------------------------------------------------

    def _closure(self, roots: Iterable[str]) -> Set[str]:
        seen: Set[str] = set()
        frontier = [r for r in roots if r in self.units]
        while frontier:
            key = frontier.pop()
            if key in seen:
                continue
            seen.add(key)
            for call in self.units[key].calls:
                if call.name in self.units and call.name not in seen:
                    frontier.append(call.name)
        return seen

    def shared_attr_findings(self) -> Iterator[Tuple[str, str, ast.AST, str]]:
        """Yield (attr, unit_name, node, other_side_desc) for each attribute
        accessed without a common lock on both sides of the thread boundary."""
        if not self.target_methods and not self.target_fns:
            return
        thread_units = self._closure(self.target_methods)
        thread_units |= {k for k, u in self.units.items() if u.is_target_fn}
        for key in list(thread_units):
            u = self.units.get(key)
            if u is not None and u.is_target_fn:
                thread_units |= self._closure(c.name for c in u.calls)
        main_roots = [n for n in self.methods
                      if n != "__init__" and n not in self.target_methods]
        main_units = self._closure(main_roots)
        if not thread_units or not main_units:
            return

        def unlocked(units: Set[str], attr: str, write: bool) -> List[
                Tuple[str, _Access]]:
            out = []
            for key in units:
                for acc in self.units[key].accesses:
                    if (acc.attr == attr and not acc.locked
                            and (acc.write or not write)):
                        out.append((key, acc))
            return out

        attrs = {a.attr for u in self.units.values() for a in u.accesses}
        written_outside_init = {
            a.attr for u in self.units.values() for a in u.accesses if a.write}
        for attr in sorted(attrs):
            if attr in self.sync_attrs or attr not in written_outside_init:
                continue
            t_writes = unlocked(thread_units, attr, write=True)
            m_writes = unlocked(main_units, attr, write=True)
            t_any = unlocked(thread_units, attr, write=False)
            m_any = unlocked(main_units, attr, write=False)
            if t_writes and m_any:
                key, acc = t_writes[0]
                other = m_any[0][0]
                yield attr, self.units[key].name, acc.node, other
            elif m_writes and t_any:
                key, acc = t_any[0]
                other = m_writes[0][0]
                yield attr, self.units[key].name, acc.node, other


# ---------------------------------------------------------------------------
# Per-module model.
# ---------------------------------------------------------------------------

class _Module:
    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.rel = ctx.rel.replace(os.sep, "/")
        i = self.rel.find(_PKG_MARKER)
        base = self.rel[i:] if i >= 0 else self.rel
        self.modname = base[:-3].replace("/", ".") if base.endswith(
            ".py") else base.replace("/", ".")
        self.module_locks: Set[str] = set()
        for node in self.ctx.tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                d = self.ctx.dotted(node.value.func)
                if d in ("threading.Lock", "threading.RLock"):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.module_locks.add(t.id)
        self.classes = [
            _ClassModel(self, n) for n in ast.walk(self.ctx.tree)
            if isinstance(n, ast.ClassDef)]
        self.class_spans = [
            (n.lineno, getattr(n, "end_lineno", n.lineno), cm)
            for n, cm in ((c.cls, c) for c in self.classes)]
        self.stmt_parent: Dict[ast.AST, ast.stmt] = {}
        self._index_statements()

    def _index_statements(self) -> None:
        def visit(node: ast.AST, stmt: Optional[ast.stmt]) -> None:
            for child in ast.iter_child_nodes(node):
                s = child if isinstance(child, ast.stmt) else stmt
                if s is not None:
                    self.stmt_parent[child] = s
                visit(child, s)

        visit(self.ctx.tree, None)

    def enclosing_class(self, lineno: int) -> Optional[_ClassModel]:
        best = None
        for start, end, cm in self.class_spans:
            if start <= lineno <= end and (
                    best is None or start >= best[0]):
                best = (start, cm)
        return best[1] if best else None

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        line = getattr(node, "lineno", None)
        if line is None:
            return None
        best = None
        for fn in self.ctx.functions:
            end = getattr(fn, "end_lineno", None)
            if end is not None and fn.lineno <= line <= end:
                if best is None or fn.lineno >= best.lineno:
                    best = fn
        return best

    def lock_id(self, node: ast.AST) -> Optional[str]:
        """Global identity for a lock expression: imported module-level locks
        resolve to their dotted origin (shared across modules); ``self``
        attribute locks are qualified by module+class."""
        a = _self_attr(node)
        if a is not None and _LOCK_NAME_RE.search(a):
            cm = self.enclosing_class(getattr(node, "lineno", 0))
            cls = cm.name if cm else "?"
            return f"{self.modname}.{cls}.{a}"
        if isinstance(node, ast.Name):
            if node.id in self.module_locks:
                return f"{self.modname}.{node.id}"
            if _LOCK_NAME_RE.search(node.id):
                origin = self.ctx.aliases.get(node.id)
                return origin if origin else f"{self.modname}.{node.id}"
        if isinstance(node, ast.Attribute):
            d = self.ctx.dotted(node)
            if d is not None and _LOCK_NAME_RE.search(d.rsplit(".", 1)[-1]):
                return d
        return None

    def finding(self, node_or_line, code: str, alias: str,
                message: str) -> Finding:
        if isinstance(node_or_line, int):
            line = node_or_line
            col = 1
        else:
            line = getattr(node_or_line, "lineno", 0)
            col = getattr(node_or_line, "col_offset", 0) + 1
        return Finding(path=self.ctx.rel, line=line, col=col, code=code,
                       alias=alias, message=message,
                       snippet=self.ctx.line_text(line),
                       scope=self.ctx.scope_of(line))


class ConcModel:
    """The whole-program model: every in-scope package module, plus the
    location of the repo's ``tests/`` dir for the TBX206 arming scan."""

    def __init__(self, modules: List[_Module],
                 tests_dir: Optional[str]):
        self.modules = modules
        self.tests_dir = tests_dir
        self.by_rel = {m.ctx.rel: m for m in modules}

    @classmethod
    def build(cls, files: Sequence[str],
              rels: Optional[Dict[str, str]] = None,
              tests_dir: Optional[str] = "auto") -> "ConcModel":
        modules: List[_Module] = []
        for path in files:
            rel = (rels or {}).get(path, path)
            if not _in_scope(rel):
                continue
            try:
                with open(path, "r", encoding="utf-8") as f:
                    source = f.read()
                ctx = ModuleContext(path, source, rel=rel)
            except (OSError, SyntaxError):
                continue  # TBX000 comes from the static pass
            modules.append(_Module(ctx))
        if tests_dir == "auto":
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            cand = os.path.join(os.path.dirname(pkg_root), "tests")
            tests_dir = cand if os.path.isdir(cand) else None
        return cls(modules, tests_dir)

    def tests_source(self) -> str:
        if not self.tests_dir or not os.path.isdir(self.tests_dir):
            return ""
        chunks: List[str] = []
        for root, dirs, names in os.walk(self.tests_dir):
            dirs[:] = sorted(d for d in dirs if d != "fixtures")
            for name in sorted(names):
                if name.endswith(".py"):
                    try:
                        with open(os.path.join(root, name), "r",
                                  encoding="utf-8") as f:
                            chunks.append(f.read())
                    except OSError:
                        continue
        return "\n".join(chunks)


# ---------------------------------------------------------------------------
# TBX201 — shared attribute across the thread boundary.
# ---------------------------------------------------------------------------

class SharedAttrRule:
    code = "TBX201"
    alias = "thread-shared"
    summary = ("attribute crosses a thread boundary with no common lock "
               "on both paths")

    def check(self, model: ConcModel) -> Iterator[Finding]:
        for mod in model.modules:
            for cm in mod.classes:
                for attr, unit, node, other in cm.shared_attr_findings():
                    yield mod.finding(
                        node, self.code, self.alias,
                        f"`{cm.name}.{attr}` is accessed from thread-side "
                        f"`{unit}` and from `{other}` with no common lock "
                        "on both paths — hold one lock on every access, or "
                        "serialize via join/Event and pragma with the "
                        "happens-before argument")


# ---------------------------------------------------------------------------
# TBX202 — signal handlers must only set latches.
# ---------------------------------------------------------------------------

class SignalHandlerRule:
    code = "TBX202"
    alias = "signal-handler"
    summary = ("signal handler call graph acquires a lock / performs I/O / "
               "emits telemetry")

    def _handlers(self, mod: _Module) -> List[Tuple[ast.AST,
                                                    Optional[_ClassModel],
                                                    str]]:
        out = []
        for node in ast.walk(mod.ctx.tree):
            if not (isinstance(node, ast.Call)
                    and mod.ctx.dotted(node.func) == "signal.signal"
                    and len(node.args) >= 2):
                continue
            h = node.args[1]
            a = _self_attr(h)
            if a is not None:
                cm = mod.enclosing_class(node.lineno)
                if cm is not None and a in cm.methods:
                    out.append((cm.methods[a], cm, a))
            elif isinstance(h, ast.Name):
                fn = mod.ctx.module_funcs.get(h.id)
                if fn is not None:
                    out.append((fn, None, h.id))
            elif isinstance(h, ast.Lambda):
                out.append((h, mod.enclosing_class(node.lineno), "<lambda>"))
        return out

    def _hazard(self, mod: _Module, node: ast.Call) -> Optional[str]:
        d = mod.ctx.dotted(node.func)
        if d is not None:
            if d in _IO_CALLS:
                return f"performs I/O (`{d}`)"
            parts = d.split(".")
            if ("obs" in parts or "flightrec" in parts
                    or d.startswith("taboo_brittleness_tpu.obs")):
                return f"emits telemetry (`{d}`)"
            if d.endswith(".acquire"):
                return f"acquires a lock (`{d}`)"
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in ("write", "flush"):
                recv = _expr_text(node.func.value)
                if re.search(r"stderr|stdout|file|fh|fd|sock", recv):
                    return f"performs I/O (`{recv}.{node.func.attr}`)"
            if node.func.attr in _TELEMETRY_ATTRS:
                recv = node.func.value
                rname = (recv.attr if isinstance(recv, ast.Attribute)
                         else recv.id if isinstance(recv, ast.Name) else "")
                if _TELEMETRY_RECV_RE.search(rname):
                    return (f"emits telemetry "
                            f"(`{rname}.{node.func.attr}`)")
            if node.func.attr == "acquire":
                return "acquires a lock (`.acquire()`)"
        return None

    def check(self, model: ConcModel) -> Iterator[Finding]:
        for mod in model.modules:
            for handler, cm, hname in self._handlers(mod):
                yield from self._scan(mod, cm, hname, handler)

    def _scan(self, mod: _Module, cm: Optional[_ClassModel], hname: str,
              root: ast.AST) -> Iterator[Finding]:
        seen: Set[int] = set()
        frontier: List[ast.AST] = [root]
        flagged: Set[int] = set()
        depth = 0
        while frontier and depth < 10:
            depth += 1
            next_frontier: List[ast.AST] = []
            for fn in frontier:
                if id(fn) in seen:
                    continue
                seen.add(id(fn))
                lock_of = (cm._lock_of if cm is not None
                           else lambda n: mod.lock_id(n))

                def on_node(node, held, _fn=fn):
                    if isinstance(node, ast.Call):
                        if id(node) in flagged:
                            return
                        hz = self._hazard(mod, node)
                        if hz is not None:
                            flagged.add(id(node))
                            findings.append(mod.finding(
                                node, self.code, self.alias,
                                f"signal handler `{hname}` reachably "
                                f"{hz} — handlers may only set "
                                "latches/Events (self-deadlock class: a "
                                "signal can land while the lock is held); "
                                "move the work to the poll side or pragma "
                                "with the reason this call is "
                                "async-signal-safe"))
                            return
                        # expand: self.X() and module-level f()
                        a = _self_attr(node.func)
                        if (a is not None and cm is not None
                                and a in cm.methods):
                            next_frontier.append(cm.methods[a])
                        elif isinstance(node.func, ast.Name):
                            callee = mod.ctx.module_funcs.get(node.func.id)
                            if callee is not None:
                                next_frontier.append(callee)

                def on_acquire(held, lock, site):
                    if id(site) not in flagged:
                        flagged.add(id(site))
                        findings.append(mod.finding(
                            site, self.code, self.alias,
                            f"signal handler `{hname}` reachably acquires "
                            f"lock `{lock}` — a signal delivered while the "
                            "main thread holds it self-deadlocks (the PR-5 "
                            "tracer-lock incident); handlers may only set "
                            "latches/Events"))

                findings: List[Finding] = []
                _walk_held(fn, lock_of, on_node,
                           lambda n, h: None, on_acquire)
                yield from findings
            frontier = next_frontier


# ---------------------------------------------------------------------------
# TBX203 — lock-order cycles.
# ---------------------------------------------------------------------------

class LockOrderRule:
    code = "TBX203"
    alias = "lock-order"
    summary = "cycle in the lock acquisition-order graph"

    def check(self, model: ConcModel) -> Iterator[Finding]:
        edges: Dict[Tuple[str, str], Tuple[_Module, ast.AST]] = {}
        for mod in model.modules:
            for fn in mod.ctx.functions:
                def on_acquire(held, lock, site, _mod=mod):
                    for h in held:
                        if h != lock:
                            edges.setdefault((h, lock), (_mod, site))
                _walk_held(fn, mod.lock_id, lambda n, h: None,
                           lambda n, h: None, on_acquire)

        adj: Dict[str, Set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)

        seen_cycles: Set[frozenset] = set()
        for start in sorted(adj):
            path: List[str] = []
            on_path: Set[str] = set()

            def dfs(node: str) -> Optional[List[str]]:
                if node in on_path:
                    return path[path.index(node):] + [node]
                if node not in adj:
                    return None
                path.append(node)
                on_path.add(node)
                for nxt in sorted(adj[node]):
                    cyc = dfs(nxt)
                    if cyc is not None:
                        return cyc
                path.pop()
                on_path.discard(node)
                return None

            cyc = dfs(start)
            if cyc is None:
                continue
            key = frozenset(cyc)
            if key in seen_cycles:
                continue
            seen_cycles.add(key)
            # Anchor at the first edge of the cycle that we have a site for.
            for a, b in zip(cyc, cyc[1:]):
                if (a, b) in edges:
                    mod, site = edges[(a, b)]
                    yield mod.finding(
                        site, self.code, self.alias,
                        "lock-order cycle: " + " -> ".join(cyc) +
                        " — two threads taking these locks in opposite "
                        "order deadlock; pick one global order (or collapse "
                        "to a single lock)")
                    break


# ---------------------------------------------------------------------------
# TBX204 — threads with no reachable join path.
# ---------------------------------------------------------------------------

class ThreadLeakRule:
    code = "TBX204"
    alias = "thread-leak"
    summary = "thread started with no reachable join/stop path"

    def _tokens_of_targets(self, targets: Sequence[ast.AST]) -> Set[Tuple]:
        toks: Set[Tuple] = set()
        for t in targets:
            if isinstance(t, ast.Tuple):
                toks |= self._tokens_of_targets(t.elts)
                continue
            tok = _root_token(t)
            if tok is not None:
                toks.add(tok)
        return toks

    def _alias_edges(self, mod: _Module) -> List[Tuple[Tuple, Tuple]]:
        edges: List[Tuple[Tuple, Tuple]] = []
        for node in ast.walk(mod.ctx.tree):
            if isinstance(node, ast.Assign):
                if (len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Tuple)
                        and isinstance(node.value, ast.Tuple)
                        and len(node.targets[0].elts) == len(
                            node.value.elts)):
                    pairs = zip(node.targets[0].elts, node.value.elts)
                else:
                    pairs = ((t, node.value) for t in node.targets)
                for tgt, val in pairs:
                    a = _root_token(tgt)
                    b = _root_token(val)
                    if a is not None and b is not None and a != b:
                        edges.append((a, b))
            elif isinstance(node, ast.For):
                a = _root_token(node.target)
                b = _root_token(node.iter)
                if a is not None and b is not None and a != b:
                    edges.append((a, b))
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr in _MUTATORS):
                coll = _root_token(node.func.value)
                if coll is not None:
                    for arg in node.args:
                        tok = _root_token(arg)
                        if tok is not None and tok != coll:
                            edges.append((tok, coll))
        return edges

    def check(self, model: ConcModel) -> Iterator[Finding]:
        for mod in model.modules:
            creations: List[Tuple[ast.Call, Set[Tuple], bool]] = []
            join_roots: Set[Tuple] = set()
            for node in ast.walk(mod.ctx.tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "join"):
                    tok = _root_token(node.func.value)
                    if tok is not None:
                        join_roots.add(tok)
                if (isinstance(node, ast.Call)
                        and mod.ctx.dotted(node.func) == _THREAD_CTOR):
                    stmt = mod.stmt_parent.get(node)
                    toks: Set[Tuple] = set()
                    escapes = False
                    if isinstance(stmt, (ast.Assign,)):
                        toks = self._tokens_of_targets(stmt.targets)
                    elif isinstance(stmt, ast.AnnAssign) and stmt.target is not None:
                        toks = self._tokens_of_targets([stmt.target])
                    elif isinstance(stmt, ast.Return):
                        escapes = True   # factory: the caller owns it
                    elif isinstance(stmt, ast.Expr):
                        pass             # Thread(...).start() — no handle
                    else:
                        # Ctor in argument position etc: conservatively
                        # treat as escaping to avoid false positives.
                        escapes = True
                    creations.append((node, toks, escapes))
            if not creations:
                continue

            # Token connectivity: a creation is joined if any of its handle
            # tokens reaches a `.join()` root through the alias graph.
            adj: Dict[Tuple, Set[Tuple]] = {}
            for a, b in self._alias_edges(mod):
                adj.setdefault(a, set()).add(b)
                adj.setdefault(b, set()).add(a)
            joined: Set[Tuple] = set()
            frontier = list(join_roots)
            while frontier:
                tok = frontier.pop()
                if tok in joined:
                    continue
                joined.add(tok)
                frontier.extend(adj.get(tok, ()))

            for node, toks, escapes in creations:
                if escapes or (toks and toks & joined):
                    continue
                handle = (", ".join(sorted(
                    ("self." if k == "a" else "") + v
                    for k, v in toks)) or "<none>")
                yield mod.finding(
                    node, self.code, self.alias,
                    f"thread started here is never joined (handle: "
                    f"{handle}) — keep the handle and join it on the stop "
                    "path (the PR-2 prefetch-leak class), or pragma with "
                    "the reason it may outlive its owner")


# ---------------------------------------------------------------------------
# TBX205 — durable artifacts must use the atomic tmp+rename protocol.
# ---------------------------------------------------------------------------

class AtomicWriteRule:
    code = "TBX205"
    alias = "atomic-write"
    summary = ("durable artifact written via bare open(..,'w') instead of "
               "tmp+os.replace")

    def _write_mode(self, node: ast.Call) -> Optional[str]:
        mode = None
        if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant) \
                and isinstance(node.args[1].value, str):
            mode = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                mode = kw.value.value
        # Only truncate-write modes: append-only logs ("a") are a sanctioned
        # protocol (crash leaves the prefix intact; readers quarantine a
        # torn tail), and "x" is exclusive-create used by claim protocols.
        if mode and mode[:1] == "w":
            return mode
        return None

    def check(self, model: ConcModel) -> Iterator[Finding]:
        for mod in model.modules:
            for node in ast.walk(mod.ctx.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "open" and node.args):
                    continue
                mode = self._write_mode(node)
                if mode is None:
                    continue
                path_text = _expr_text(node.args[0])
                if "tmp" in path_text.lower():
                    continue  # the atomic idiom's own tmp-file open
                fn = mod.enclosing_function(node)
                if fn is not None and any(
                        isinstance(n, ast.Call)
                        and mod.ctx.dotted(n.func) in ("os.replace",
                                                       "os.rename")
                        for n in ast.walk(fn)):
                    continue  # writes tmp then renames: atomic protocol
                yield mod.finding(
                    node, self.code, self.alias,
                    f"durable artifact `{path_text or '?'}` written via "
                    f"bare open(.., {mode!r}) — a crash mid-write leaves a "
                    "torn file for readers/resume; write a tmp sibling and "
                    "os.replace() it (see resilience.atomic_json_dump), or "
                    "pragma with the reason torn output is acceptable")


# ---------------------------------------------------------------------------
# TBX206 — FAULT_SITES contract drift.
# ---------------------------------------------------------------------------

class FaultSiteRule:
    code = "TBX206"
    alias = "fault-site"
    summary = ("FAULT_SITES drift: fired-unregistered / never-fired / "
               "never-armed-in-tests")

    def check(self, model: ConcModel) -> Iterator[Finding]:
        registry: Dict[str, Tuple[_Module, int]] = {}
        reg_mod: Optional[_Module] = None
        fires: Dict[str, Tuple[_Module, ast.Call]] = {}
        for mod in model.modules:
            for node in mod.ctx.tree.body:
                if (isinstance(node, ast.Assign)
                        and any(isinstance(t, ast.Name)
                                and t.id == "FAULT_SITES"
                                for t in node.targets)
                        and isinstance(node.value, (ast.Tuple, ast.List))):
                    reg_mod = mod
                    for elt in node.value.elts:
                        if isinstance(elt, ast.Constant) and isinstance(
                                elt.value, str):
                            registry[elt.value] = (mod, elt.lineno)
            for node in ast.walk(mod.ctx.tree):
                if not (isinstance(node, ast.Call) and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                d = mod.ctx.dotted(node.func)
                if d is not None and (d == "fire" or d.endswith(".fire")):
                    fires.setdefault(node.args[0].value, (mod, node))
        if reg_mod is None:
            return  # no registry in the analyzed set (partial run)

        for site, (mod, node) in sorted(fires.items()):
            if site not in registry:
                yield mod.finding(
                    node, self.code, self.alias,
                    f"fault site '{site}' is fired here but absent from "
                    "FAULT_SITES — register it so TABOO_FAULT_PLAN "
                    "schedules can arm it (unregistered sites are "
                    "untestable dead protocol)")

        tests_src = model.tests_source()
        for site, (mod, lineno) in sorted(registry.items()):
            if site not in fires:
                yield mod.finding(
                    lineno, self.code, self.alias,
                    f"fault site '{site}' is registered in FAULT_SITES but "
                    "never fired anywhere in the package — wire "
                    f"resilience.fire('{site}', ...) at the site or drop "
                    "the registry entry")
            elif tests_src and site not in tests_src:
                yield mod.finding(
                    lineno, self.code, self.alias,
                    f"fault site '{site}' is never armed by any test "
                    "(no TABOO_FAULT_PLAN / arm reference in tests/) — add "
                    "schedule coverage so the site's failure path is "
                    "exercised, or pragma with the reason")


CONC_RULES = [SharedAttrRule(), SignalHandlerRule(), LockOrderRule(),
              ThreadLeakRule(), AtomicWriteRule(), FaultSiteRule()]
CONC_RULES_BY_CODE = {r.code: r for r in CONC_RULES}


def run_conc(files: Sequence[str], *,
             rels: Optional[Dict[str, str]] = None,
             tests_dir: Optional[str] = "auto",
             rules: Optional[Iterable] = None,
             ) -> Tuple[List[Finding], List[Finding]]:
    """Build the whole-program model over the package subset of ``files``
    and run the TBX2xx rules.  Returns (active, suppressed) with the same
    pragma semantics as the per-module pass."""
    model = ConcModel.build(files, rels=rels, tests_dir=tests_dir)
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for rule in (rules if rules is not None else CONC_RULES):
        for finding in rule.check(model):
            mod = model.by_rel.get(finding.path)
            pragmas = mod.ctx.pragmas if mod is not None else {}
            (suppressed if is_suppressed(finding, pragmas)
             else active).append(finding)
    active.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    suppressed.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return active, suppressed
