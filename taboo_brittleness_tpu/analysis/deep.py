"""Deep mode: jaxpr-level vocab-dtype audit of the public jit entry points.

The AST rules see *source*; this pass sees what JAX will actually stage.  It
traces a small registry of public entry points (ops/lens.py, ops/sae.py,
runtime/decode.py) with ABSTRACT shapes — a tiny Gemma-2 config whose vocab
size is a distinctive marker dim — and walks the resulting jaxprs (through
pjit/scan/while/cond sub-jaxprs) for ``convert_element_type`` to f32 applied
to a vocab-carrying operand.  That is exactly the [L, S, V] f32
materialization hazard (~1.16 GB/prompt at the real 256k vocab) surfacing
*after* tracing, where an AST rule cannot follow it.

Complements ``tools/hlo_collectives.py``, which audits the compiled HLO's
collectives but not its dtypes.  Nothing compiles here — ``jax.make_jaxpr``
only traces, so deep mode stays a few seconds on CPU.

Known-intentional conversions (the lens softmax must be f32; the tensor is
transient inside one scan step) are kept out of the gate via the committed
baseline (``tools/tbx_baseline.json``), not pragmas — jaxpr findings have no
source line to pragma.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Set, Tuple

from taboo_brittleness_tpu.analysis.core import Finding

# Distinctive vocab size: prime, and far from every other tiny-config dim,
# so "the marker appears in an operand shape" identifies vocab-carrying
# tensors with no false hits.
VOCAB_MARKER = 641


def _tiny_cfg():
    from taboo_brittleness_tpu.models import gemma2

    # bf16 compute so widening conversions actually appear in the jaxpr (the
    # f32-compute test config would make astype(float32) a no-op).
    return gemma2.PRESETS["gemma2_tiny"].replace(
        vocab_size=VOCAB_MARKER, dtype="bfloat16", param_dtype="bfloat16")


def _abstract_params(cfg):
    import jax

    from taboo_brittleness_tpu.models import gemma2

    return jax.eval_shape(
        lambda key: gemma2.init_params(key, cfg), jax.random.PRNGKey(0))


# ---------------------------------------------------------------------------
# Entry registry: name -> () -> (callable, abstract args).
# Add new public jit entry points here as the repo grows.
# ---------------------------------------------------------------------------

def _entry_lens_aggregate():
    import jax
    import jax.numpy as jnp

    from taboo_brittleness_tpu.ops import lens

    cfg = _tiny_cfg()
    params = _abstract_params(cfg)
    B, T = 2, 5
    residual = jax.ShapeDtypeStruct((B, T, cfg.hidden_size), jnp.float32)
    token_ids = jax.ShapeDtypeStruct((B, T), jnp.int32)
    mask = jax.ShapeDtypeStruct((B, T), jnp.bool_)

    def fn(p, r, ids, m):
        return lens.aggregate_from_residual(p, cfg, r, ids, m, top_k=3)

    return fn, (params, residual, token_ids, mask)


def _entry_sae_correlation_stream():
    import jax
    import jax.numpy as jnp

    from taboo_brittleness_tpu.ops import sae as sae_ops

    D, S, N = 16, 37, 8
    sae = sae_ops.SAEParams(
        w_enc=jax.ShapeDtypeStruct((D, S), jnp.float32),
        b_enc=jax.ShapeDtypeStruct((S,), jnp.float32),
        w_dec=jax.ShapeDtypeStruct((S, D), jnp.float32),
        b_dec=jax.ShapeDtypeStruct((D,), jnp.float32),
        threshold=jax.ShapeDtypeStruct((S,), jnp.float32),
    )
    x = jax.ShapeDtypeStruct((N, D), jnp.bfloat16)
    y = jax.ShapeDtypeStruct((N,), jnp.float32)
    w = jax.ShapeDtypeStruct((N,), jnp.float32)

    def fn(s, xx, yy, ww):
        return sae_ops.latent_secret_correlation_stream(s, xx, yy, ww, chunk=4)

    return fn, (sae, x, y, w)


def _entry_greedy_decode():
    import jax
    import jax.numpy as jnp

    from taboo_brittleness_tpu.runtime import decode

    cfg = _tiny_cfg()
    params = _abstract_params(cfg)
    B, T = 2, 5
    ids = jax.ShapeDtypeStruct((B, T), jnp.int32)
    valid = jax.ShapeDtypeStruct((B, T), jnp.bool_)
    pos = jax.ShapeDtypeStruct((B, T), jnp.int32)

    def fn(p, i, v, q):
        return decode.greedy_decode(p, cfg, i, v, q, max_new_tokens=3)

    return fn, (params, ids, valid, pos)


def _entry_greedy_decode_multi_tap():
    # The grid capture program (grid/runner.py capture_word_residuals): ONE
    # decode tapping a static TUPLE of residual layers.  Each tap slot is an
    # f32 accumulator by the single-tap contract; the [K, B, T, D] stack
    # must never widen a vocab-carrying tensor.
    import jax
    import jax.numpy as jnp

    from taboo_brittleness_tpu.runtime import decode

    cfg = _tiny_cfg()
    params = _abstract_params(cfg)
    B, T = 2, 5
    ids = jax.ShapeDtypeStruct((B, T), jnp.int32)
    valid = jax.ShapeDtypeStruct((B, T), jnp.bool_)
    pos = jax.ShapeDtypeStruct((B, T), jnp.int32)

    def fn(p, i, v, q):
        return decode.greedy_decode(p, cfg, i, v, q, max_new_tokens=3,
                                    capture_residual_layer=(1, 2))

    return fn, (params, ids, valid, pos)


def _entry_grid_cell_readout():
    # The grid per-cell encode program (grid/runner.py _cell_readout):
    # pooled JumpReLU readout + top-k at one cell's width, dispatched once
    # per (word, cell) fleet unit.
    import jax
    import jax.numpy as jnp

    from taboo_brittleness_tpu.grid import runner as grid_runner
    from taboo_brittleness_tpu.ops import sae as sae_ops

    D, S, B, T = 16, 37, 2, 6
    sae = sae_ops.SAEParams(
        w_enc=jax.ShapeDtypeStruct((D, S), jnp.float32),
        b_enc=jax.ShapeDtypeStruct((S,), jnp.float32),
        w_dec=jax.ShapeDtypeStruct((S, D), jnp.float32),
        b_dec=jax.ShapeDtypeStruct((D,), jnp.float32),
        threshold=jax.ShapeDtypeStruct((S,), jnp.float32),
    )
    resid = jax.ShapeDtypeStruct((B, T, D), jnp.float32)
    mask = jax.ShapeDtypeStruct((B, T), jnp.bool_)

    def fn(s, r, m):
        return grid_runner._cell_readout(s, r, m, top_k=3)

    return fn, (sae, resid, mask)


def _entry_residual_measure():
    # The sweep's readout program — PR-3's AOT-warm-started hot path (one
    # vocab-width lens readout per row; the f32 probability slab must stay
    # transient inside each lax.map chunk).
    import jax
    import jax.numpy as jnp

    from taboo_brittleness_tpu.pipelines import interventions as iv

    cfg = _tiny_cfg()
    params = _abstract_params(cfg)
    B, T = 2, 6
    residual = jax.ShapeDtypeStruct((B, T, cfg.hidden_size), jnp.float32)
    seqs = jax.ShapeDtypeStruct((B, T), jnp.int32)
    mask = jax.ShapeDtypeStruct((B, T), jnp.bool_)
    tgt = jax.ShapeDtypeStruct((B,), jnp.int32)

    def fn(p, r, s, m, t):
        return iv._residual_measure(p, cfg, r, s, m, t, top_k=3, resp_start=1)

    return fn, (params, residual, seqs, mask, tgt)


def _entry_nll_cached():
    # The sweep's ΔNLL program (prefill-KV continuation) — the third
    # AOT-warm-started production program.
    import jax
    import jax.numpy as jnp

    from taboo_brittleness_tpu.pipelines import interventions as iv

    cfg = _tiny_cfg()
    params = _abstract_params(cfg)
    B, T, s = 2, 6, 2
    kv = jax.ShapeDtypeStruct(
        (cfg.num_layers, B, s, cfg.num_kv_heads, cfg.head_dim),
        jnp.bfloat16)
    cache_valid = jax.ShapeDtypeStruct((B, s), jnp.bool_)
    seqs = jax.ShapeDtypeStruct((B, T), jnp.int32)
    valid = jax.ShapeDtypeStruct((B, T), jnp.bool_)
    pos = jax.ShapeDtypeStruct((B, T), jnp.int32)
    nmask = jax.ShapeDtypeStruct((B, T), jnp.bool_)

    def fn(p, ck, cv, cval, sq, vl, ps, nm):
        return iv._nll_cached_jit(p, cfg, ck, cv, cval, sq, vl, ps, nm,
                                  resp_start=s)

    return fn, (params, kv, kv, cache_valid, seqs, valid, pos, nmask)


def _serve_tp_mesh():
    """The dp×tp serve mesh for the mesh-mode entries (ISSUE 18); None when
    this process has fewer than two (or an odd number of) devices — the
    builders then fall back to the unsharded trace so the registry stays
    traceable everywhere, while the check.sh gate forces 8 host devices so
    the tp programs ARE audited there."""
    import jax

    try:
        if jax.device_count() < 2 or jax.device_count() % 2:
            return None
        from taboo_brittleness_tpu.serve.engine import serve_mesh

        return serve_mesh(2)
    except Exception:  # noqa: BLE001 — no backend: unsharded fallback
        return None


def _mesh_dims(mesh) -> dict:
    """Abstract-shape overrides for a mesh-mode trace: vocab doubles to
    2×VOCAB_MARKER so each tp shard keeps the marker dim (the audit follows
    the LOCAL vocab tensors inside the sharded readout), and slots become a
    dp multiple so the row sharding divides evenly."""
    return {"vocab": 2 * VOCAB_MARKER,
            "slots": 2 * int(mesh.shape["dp"])}


def _serve_abstract(vocab: int = None, slots: int = None):
    """Shared abstract serving state (cfg, params, sae, cache, state) for
    the serve-step entries.  ``vocab``/``slots`` override the defaults for
    the mesh-mode variants (:func:`_mesh_dims`)."""
    import jax
    import jax.numpy as jnp

    from taboo_brittleness_tpu.ops import sae as sae_ops
    from taboo_brittleness_tpu.serve import engine as serve_engine

    cfg = _tiny_cfg()
    if vocab is not None:
        cfg = cfg.replace(vocab_size=vocab)
    params = _abstract_params(cfg)
    S, C, P, m, r = (2 if slots is None else int(slots)), 8, 4, 2, 2
    D = cfg.hidden_size
    sds = jax.ShapeDtypeStruct
    sae = sae_ops.SAEParams(
        w_enc=sds((D, 16), jnp.float32),
        b_enc=sds((16,), jnp.float32),
        w_dec=sds((16, D), jnp.float32),
        b_dec=sds((D,), jnp.float32),
        threshold=sds((16,), jnp.float32),
    )
    cache = serve_engine.KVCache(
        k=sds((cfg.num_layers, S, C, cfg.num_kv_heads, cfg.head_dim),
              jnp.bfloat16),
        v=sds((cfg.num_layers, S, C, cfg.num_kv_heads, cfg.head_dim),
              jnp.bfloat16),
        valid=sds((S, C), jnp.bool_),
        length=sds((), jnp.int32),
    )
    state = serve_engine.SlotState(
        input_tok=sds((S,), jnp.int32),
        pos=sds((S,), jnp.int32),
        active=sds((S,), jnp.bool_),
        done=sds((S,), jnp.bool_),
        prompt_buf=sds((S, P), jnp.int32),
        prompt_len=sds((S,), jnp.int32),
        gen_count=sds((S,), jnp.int32),
        max_gen=sds((S,), jnp.int32),
        latent_ids=sds((S, m), jnp.int32),
        basis=sds((S, D, r), jnp.float32),
        lens_target=sds((S,), jnp.int32),
        word_id=sds((S,), jnp.int32),
    )
    return cfg, params, sae, cache, state


def _entry_serve_step(mesh=None):
    # The serving subsystem's resident step program (one compiled step for
    # every scenario; serve/engine.py).  Its per-step unembed + optional
    # lens readout each materialize a transient [S, 1, V] f32 row — reviewed
    # and baselined like the decode/NLL readouts.  With ``mesh`` this is the
    # tensor-parallel variant (ISSUE 18): the same program under the dp×tp
    # mesh, its readout a shard_map over local vocab shards.
    from taboo_brittleness_tpu.serve import engine as serve_engine

    cfg, params, sae, cache, state = _serve_abstract(
        **(_mesh_dims(mesh) if mesh is not None else {}))

    def fn(p, s, c, st):
        return serve_engine.serve_step(p, cfg, s, c, st, sae_layer=1,
                                       proj_layer=1, tap_layer=2, mesh=mesh)

    return fn, (params, sae, cache, state)


def _entry_serve_step_tp():
    return _entry_serve_step(mesh=_serve_tp_mesh())


def _delta_abstract_names(params):
    """Pick one xor leaf and one q8 leaf from the abstract param set (sorted
    for determinism; the q8 leaf needs ndim >= 2 for a per-channel scale)."""
    from taboo_brittleness_tpu.runtime import delta as deltalib

    named = deltalib.flatten_named(params)
    names = sorted(named)
    xor_name = names[0]
    q8_name = next(n for n in names[1:] if len(named[n].shape) >= 2)
    return named, xor_name, q8_name


def _entry_apply_delta():
    # The base-resident word switch (runtime/delta.py, ISSUE 12): base +
    # packed delta -> full word params as ONE program.  xor leaves bitcast
    # through uint planes (exact), q8 leaves widen base to f32 for the
    # dequantized add then narrow back — the widening is per-leaf transient,
    # reviewed and baselined like the readout slabs.
    import jax
    import jax.numpy as jnp

    from taboo_brittleness_tpu.runtime import delta as deltalib

    cfg = _tiny_cfg()
    params = _abstract_params(cfg)
    named, xor_name, q8_name = _delta_abstract_names(params)
    sds = jax.ShapeDtypeStruct
    b_x, b_q = named[xor_name], named[q8_name]
    payload = {
        xor_name: {"bits": sds(b_x.shape, deltalib._jnp_uint(b_x.dtype))},
        q8_name: {"q": sds(b_q.shape, jnp.int8),
                  "scale": sds((b_q.shape[-1],), jnp.float32)},
    }
    codecs = tuple(sorted([(xor_name, "xor"), (q8_name, "q8")]))

    def fn(p, pl):
        return deltalib.apply_delta(p, pl, codecs=codecs)

    return fn, (params, payload)


def _entry_serve_step_multi(mesh=None):
    # The multi-word serving step (serve/engine.py, ISSUE 12): scan over the
    # W-word delta bank, each iteration reconstructing that word's params
    # in-graph and running the same forward core — W x the single-word
    # step's readout transients, the documented price of one resident base.
    # With ``mesh``: the tensor-parallel variant (ISSUE 18).
    import jax
    import jax.numpy as jnp

    from taboo_brittleness_tpu.runtime import delta as deltalib
    from taboo_brittleness_tpu.serve import engine as serve_engine

    cfg, params, sae, cache, state = _serve_abstract(
        **(_mesh_dims(mesh) if mesh is not None else {}))
    named, xor_name, q8_name = _delta_abstract_names(params)
    sds = jax.ShapeDtypeStruct
    W = 2
    b_x, b_q = named[xor_name], named[q8_name]
    bank = {
        xor_name: {"bits": sds((W,) + tuple(b_x.shape),
                               deltalib._jnp_uint(b_x.dtype))},
        q8_name: {"q": sds((W,) + tuple(b_q.shape), jnp.int8),
                  "scale": sds((W, b_q.shape[-1]), jnp.float32)},
    }
    codecs = tuple(sorted([(xor_name, "xor"), (q8_name, "q8")]))

    def fn(p, s, bk, c, st):
        return serve_engine.serve_step_multi(
            p, cfg, s, bk, c, st, codecs=codecs,
            sae_layer=1, proj_layer=1, tap_layer=2, mesh=mesh)

    return fn, (params, sae, bank, cache, state)


def _entry_serve_step_multi_tp():
    return _entry_serve_step_multi(mesh=_serve_tp_mesh())


def _entry_serve_spec_draft(mesh=None):
    # The speculative SERVING draft program (serve/spec_engine.py, ISSUE
    # 13): G lens-head steps over layers 0..k for the whole slot batch in
    # one launch, reading a per-launch SLICE of the resident KV pages.
    # Each scan step's lens argmax + top-2 margin materialize a transient
    # [S, 1, V] f32 logits row — the reviewed-and-baselined readout class.
    # With ``mesh``: the tensor-parallel variant (ISSUE 18).
    import jax
    import jax.numpy as jnp

    from taboo_brittleness_tpu.serve import spec_engine

    cfg, params, sae, cache, state = _serve_abstract(
        **(_mesh_dims(mesh) if mesh is not None else {}))

    def fn(p, s, mk, mv, st):
        return spec_engine.serve_spec_draft(
            p, cfg, s, mk, mv, st,
            draft_layer=1, block_size=2, sae_layer=1, proj_layer=1,
            mesh=mesh)

    return fn, (params, sae, cache.k, cache.v, state)


def _entry_serve_spec_draft_tp():
    return _entry_serve_spec_draft(mesh=_serve_tp_mesh())


def _entry_serve_spec_verify(mesh=None):
    # The speculative SERVING verify program: ONE full-depth forward over
    # the [S, G+1] teacher-forced chunk (each slot at its own columns) with
    # a transient [S, G+1, V] f32 unembed slab + the optional lens readout,
    # then the branch-free accept/emit/advance.  The adaptive-depth variant
    # is this same program — the per-slot margin rides as SpecSlots data,
    # not as a separate compilation.  With ``mesh``: the tensor-parallel
    # variant (ISSUE 18).
    import jax
    import jax.numpy as jnp

    from taboo_brittleness_tpu.serve import spec_engine

    cfg, params, sae, cache, state = _serve_abstract(
        **(_mesh_dims(mesh) if mesh is not None else {}))
    S = state.input_tok.shape[0]
    G = 2
    sds = jax.ShapeDtypeStruct
    spec = spec_engine.SpecSlots(block=sds((S,), jnp.int32),
                                 margin=sds((S,), jnp.float32))
    drafts = sds((S, G), jnp.int32)
    margins = sds((S, G), jnp.float32)

    def fn(p, s, c, st, sp, d, mg):
        return spec_engine.serve_spec_verify(
            p, cfg, s, c, st, sp, d, mg,
            sae_layer=1, proj_layer=1, tap_layer=2, mesh=mesh)

    return fn, (params, sae, cache, state, spec, drafts, margins)


def _entry_serve_spec_verify_tp():
    return _entry_serve_spec_verify(mesh=_serve_tp_mesh())


def _entry_fused_study():
    # The fused study program (runtime/fused.py, ISSUE 8): decode + tap
    # readout + cached NLL as ONE launched module.  Its readout/NLL tails
    # carry the same transient vocab-width f32 slabs as the legacy trio —
    # reviewed and baselined, exactly like those entries.  Traced in arms
    # mode (edit + baseline-layout NLL), the sweep's steady state.
    import jax
    import jax.numpy as jnp

    from taboo_brittleness_tpu.ops import sae as sae_ops
    from taboo_brittleness_tpu.pipelines import interventions as iv
    from taboo_brittleness_tpu.runtime import fused

    cfg = _tiny_cfg()
    params = _abstract_params(cfg)
    B, Tp, N = 2, 4, 2
    T = Tp + N
    D = cfg.hidden_size
    sds = jax.ShapeDtypeStruct
    sae = sae_ops.SAEParams(
        w_enc=sds((D, 16), jnp.float32),
        b_enc=sds((16,), jnp.float32),
        w_dec=sds((16, D), jnp.float32),
        b_dec=sds((D,), jnp.float32),
        threshold=sds((16,), jnp.float32),
    )
    ep = {"sae": sae, "layer": 2,
          "latent_ids": sds((B, 2), jnp.int32)}
    ids = sds((B, Tp), jnp.int32)
    valid = sds((B, Tp), jnp.bool_)
    pos = sds((B, Tp), jnp.int32)
    tgt = sds((B,), jnp.int32)
    nll = dict(nll_seqs=sds((B, T), jnp.int32),
               nll_valid=sds((B, T), jnp.bool_),
               nll_positions=sds((B, T), jnp.int32),
               nll_next_mask=sds((B, T), jnp.bool_))

    def fn(p, e, i, v, q, t, ns, nv, np_, nm):
        return fused.fused_study(
            p, cfg, i, v, q, e, t, ns, nv, np_, nm,
            max_new_tokens=N, edit_fn=iv.sae_ablation_edit,
            tap_layer=2, top_k=3, nll_edit=True)

    return fn, (params, ep, ids, valid, pos, tgt,
                nll["nll_seqs"], nll["nll_valid"], nll["nll_positions"],
                nll["nll_next_mask"])


def _spec_shapes():
    import jax
    import jax.numpy as jnp

    cfg = _tiny_cfg()
    B, Tp, N, G, k = 2, 4, 3, 2, 1
    S = Tp + N + G + 1
    sds = jax.ShapeDtypeStruct

    def kv(layers):
        return sds((layers, B, S, cfg.num_kv_heads, cfg.head_dim),
                   jnp.bfloat16)

    return cfg, B, Tp, N, G, k, S, sds, kv


def _entry_spec_draft_step():
    # The speculative decoder's draft program (runtime/speculate.py, ISSUE
    # 9): G single-token forwards over layers 0..k inside one launch, each
    # step's lens argmax over a transient [B, 1, V] f32 logits row — the
    # same reviewed-and-baselined readout class as the decode/serve heads.
    import jax
    import jax.numpy as jnp

    from taboo_brittleness_tpu.runtime import speculate

    cfg, B, Tp, N, G, k, S, sds, kv = _spec_shapes()
    params = _abstract_params(cfg)

    def fn(p, dk, dv, pv, last, n, done, plen):
        return speculate.draft_step(p, cfg, dk, dv, pv, last, n, done, plen,
                                    draft_layer=k, block_size=G)

    return fn, (params, kv(k + 1), kv(k + 1),
                sds((B, Tp), jnp.bool_), sds((B,), jnp.int32),
                sds((B,), jnp.int32), sds((B,), jnp.bool_),
                sds((B,), jnp.int32))


def _entry_spec_verify_block():
    # The speculative decoder's verify program: ONE full-depth forward over
    # the G+1 teacher-forced chunk with a transient [B, G+1, V] f32 logits
    # slab (argmax fused into the unembed epilogue) + in-graph acceptance.
    import jax
    import jax.numpy as jnp

    from taboo_brittleness_tpu.runtime import speculate

    cfg, B, Tp, N, G, k, S, sds, kv = _spec_shapes()
    params = _abstract_params(cfg)

    def fn(p, mk, mv, pv, toks, emit, resid, last, n, done, plen, drafts):
        return speculate.verify_block(
            p, cfg, mk, mv, pv, toks, emit, resid, last, n, done, plen,
            drafts, max_new_tokens=N, block_size=G,
            capture_residual_layer=2)

    return fn, (params, kv(cfg.num_layers), kv(cfg.num_layers),
                sds((B, Tp), jnp.bool_),
                sds((B, N + 1), jnp.int32), sds((B, N + 1), jnp.bool_),
                sds((B, S, cfg.hidden_size), jnp.float32),
                sds((B,), jnp.int32), sds((B,), jnp.int32),
                sds((B,), jnp.bool_), sds((B,), jnp.int32),
                sds((B, G), jnp.int32))


ENTRY_POINTS: List[Tuple[str, Callable]] = [
    ("ops.lens.aggregate_from_residual", _entry_lens_aggregate),
    ("ops.sae.latent_secret_correlation_stream", _entry_sae_correlation_stream),
    ("runtime.decode.greedy_decode", _entry_greedy_decode),
    ("runtime.decode.greedy_decode[multi_tap]", _entry_greedy_decode_multi_tap),
    ("grid.runner._cell_readout", _entry_grid_cell_readout),
    ("pipelines.interventions._residual_measure", _entry_residual_measure),
    ("pipelines.interventions._nll_cached_jit", _entry_nll_cached),
    ("serve.engine.serve_step", _entry_serve_step),
    ("serve.engine.serve_step[tp]", _entry_serve_step_tp),
    ("serve.engine.serve_step_multi", _entry_serve_step_multi),
    ("serve.engine.serve_step_multi[tp]", _entry_serve_step_multi_tp),
    ("serve.spec_engine.serve_spec_draft", _entry_serve_spec_draft),
    ("serve.spec_engine.serve_spec_draft[tp]", _entry_serve_spec_draft_tp),
    ("serve.spec_engine.serve_spec_verify", _entry_serve_spec_verify),
    ("serve.spec_engine.serve_spec_verify[tp]", _entry_serve_spec_verify_tp),
    ("runtime.delta.apply_delta", _entry_apply_delta),
    ("runtime.fused.fused_study", _entry_fused_study),
    ("runtime.speculate.draft_step", _entry_spec_draft_step),
    ("runtime.speculate.verify_block", _entry_spec_verify_block),
]


def entry_point_names() -> frozenset:
    """Bare function names of the registered jit entry points — the call-site
    vocabulary rule TBX010 (analysis/rules.py) holds to the
    TraceAnnotation/named_scope contract of obs/profile.py.  Derived from
    the registry so a new entry point is covered the day it is registered."""
    return frozenset(name.rsplit(".", 1)[1] for name, _ in ENTRY_POINTS)


# ---------------------------------------------------------------------------
# Jaxpr walk.
# ---------------------------------------------------------------------------

def _sub_jaxprs(params) -> Iterable:
    """Every Jaxpr/ClosedJaxpr reachable through an eqn's params (pjit's
    ``jaxpr``, scan/while bodies, cond ``branches`` tuples, ...)."""
    from jax.core import ClosedJaxpr, Jaxpr

    for value in params.values():
        stack = [value]
        while stack:
            v = stack.pop()
            if isinstance(v, (ClosedJaxpr, Jaxpr)):
                yield v
            elif isinstance(v, (tuple, list)):
                stack.extend(v)


def _vocab_f32_conversions(jaxpr, seen: Set[tuple]) -> Iterable[tuple]:
    """(shape, src_dtype) for each widening convert_element_type -> f32 whose
    operand shape carries the vocab marker, deduped across the whole trace."""
    import numpy as np

    inner = getattr(jaxpr, "jaxpr", jaxpr)  # ClosedJaxpr -> Jaxpr
    for eqn in inner.eqns:
        if eqn.primitive.name == "convert_element_type":
            new_dtype = eqn.params.get("new_dtype")
            aval = eqn.invars[0].aval
            shape = tuple(getattr(aval, "shape", ()))
            src = getattr(aval, "dtype", None)
            if (new_dtype == np.float32 and src is not None
                    and np.dtype(src) != np.float32
                    and np.dtype(src).itemsize < 4
                    and VOCAB_MARKER in shape):
                key = (shape, str(src))
                if key not in seen:
                    seen.add(key)
                    yield key
        for sub in _sub_jaxprs(eqn.params):
            yield from _vocab_f32_conversions(sub, seen)


def run_deep(entries: Iterable[Tuple[str, Callable]] = None) -> List[Finding]:
    """Trace each registered entry point and return TBX101 findings for
    vocab-dim f32 materializations (TBX100 if an entry fails to trace —
    a broken registry must fail the gate, not skip silently)."""
    import jax

    findings: List[Finding] = []
    for name, build in (entries if entries is not None else ENTRY_POINTS):
        try:
            fn, args = build()
            jaxpr = jax.make_jaxpr(fn)(*args)
        except Exception as e:  # registry drift is a finding, not a crash
            findings.append(Finding(
                path=f"<deep:{name}>", line=0, col=0,
                code="TBX100", alias="deep-entry",
                message=f"entry point failed to trace: {type(e).__name__}: {e}",
                snippet=f"trace-failure {type(e).__name__}"))
            continue
        seen: Set[tuple] = set()
        for shape, src in _vocab_f32_conversions(jaxpr, seen):
            findings.append(Finding(
                path=f"<deep:{name}>", line=0, col=0,
                code="TBX101", alias="deep-f32",
                message=(f"jaxpr materializes {src}->float32 on a "
                         f"vocab-carrying operand {shape} (vocab marker dim "
                         f"{VOCAB_MARKER}); at the real 256k vocab this is "
                         "the GB-scale f32 tensor — keep it transient or "
                         "baseline it as reviewed"),
                snippet=f"{src}->f32 {shape}"))
    return findings
