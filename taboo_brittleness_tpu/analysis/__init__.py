"""tbx-check: a JAX/TPU-aware static-analysis gate for this repo.

The pipeline lives or dies on TPU memory and trace discipline: one
``[42, seq, 256000]`` f32 probability tensor is ~1.16 GB per prompt, a stray
host sync inside a hot path serializes the device queue, and a
``static_argnames`` typo silently retraces per call.  This package keeps
those hazard classes out of the tree as it grows:

- ``core``     — findings, ``# tbx: <rule>-ok`` suppression pragmas, and the
                 per-module AST context (imports, jit roots, traced reach).
- ``rules``    — the TBX001..TBX008 AST rules (see ``rules.RULES``).
- ``deep``     — optional jaxpr-level pass: traces registered jit entry
                 points with abstract shapes and flags f32 materialization
                 on vocab-carrying operands (TBX101).
- ``baseline`` — fingerprint engine so known findings can be ratcheted.
- ``cli``      — ``python -m taboo_brittleness_tpu.analysis [--deep]
                 [--baseline FILE] [paths...]``; exit 0 iff clean.

Import surface is stdlib-only unless ``--deep`` is requested (the jaxpr pass
imports jax lazily), so the gate costs milliseconds in CI.
"""

from taboo_brittleness_tpu.analysis.core import Finding, analyze_file  # noqa: F401
from taboo_brittleness_tpu.analysis.cli import main, run_check  # noqa: F401
from taboo_brittleness_tpu.analysis.rules import RULES  # noqa: F401
