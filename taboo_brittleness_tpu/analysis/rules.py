"""The TBX001..TBX008 AST rules.

Each rule is a small class with ``code`` / ``alias`` / ``summary`` and a
``check(ctx, repo)`` generator over :class:`~.core.Finding`.  Rules are
deliberately narrow: the gate must hold the whole repo at zero unsuppressed
findings (tests/test_analysis.py meta-test), so precision beats recall —
every widening of a rule is paid for in pragmas.

Suppress any finding with ``# tbx: <code-or-alias>-ok — <reason>`` on the
violating line or the line directly above.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set

from taboo_brittleness_tpu.analysis.core import Finding, ModuleContext


# ---------------------------------------------------------------------------
# Repo-level context shared by all modules (declared mesh axes).
# ---------------------------------------------------------------------------

_DEFAULT_AXES = frozenset({"dp", "tp", "sp"})


def _axes_from_mesh_module(path: str) -> Optional[frozenset]:
    """Union of axis-name tuples passed to ``Mesh(...)`` in parallel/mesh.py
    (``Mesh(arr, ("dp", "tp", "sp"))``) — the single source of truth for
    which logical axes exist."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None
    axes: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and len(node.args) >= 2):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else getattr(fn, "id", "")
        if name != "Mesh":
            continue
        names_arg = node.args[1]
        if isinstance(names_arg, (ast.Tuple, ast.List)):
            for elt in names_arg.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    axes.add(elt.value)
    return frozenset(axes) or None


@dataclasses.dataclass(frozen=True)
class RepoContext:
    """Cross-module facts the rules need (currently: the mesh axis names)."""

    mesh_axes: frozenset = _DEFAULT_AXES

    @classmethod
    def discover(cls, paths: Sequence[str] = ()) -> "RepoContext":
        """Axis names from this repo's ``parallel/mesh.py`` (located relative
        to the analysis package, so the gate works from any cwd)."""
        mesh_py = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "parallel", "mesh.py")
        axes = _axes_from_mesh_module(mesh_py)
        return cls(mesh_axes=axes or _DEFAULT_AXES)


# ---------------------------------------------------------------------------
# Shared AST helpers.
# ---------------------------------------------------------------------------

def _top_level_traced(ctx: ModuleContext) -> List[ast.FunctionDef]:
    """Traced functions whose parent is NOT traced — walking each exactly
    once covers every traced line without double-reporting nested defs."""
    return [fn for fn in ctx.traced if ctx.parents.get(fn) not in ctx.traced]


def _fn_param_names(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    return ([p.arg for p in getattr(a, "posonlyargs", [])]
            + [p.arg for p in a.args] + [p.arg for p in a.kwonlyargs])


def _string_constants(node: ast.expr) -> Iterator[ast.Constant]:
    """String literals in an expression, descending through tuples/lists."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            yield from _string_constants(elt)


# ---------------------------------------------------------------------------
# TBX001 — host sync inside traced code.
# ---------------------------------------------------------------------------

_HOST_SYNC_CALLS = {
    "jax.device_get": "jax.device_get",
    "numpy.asarray": "np.asarray",
    "numpy.array": "np.array",
    "numpy.copy": "np.copy",
}


class HostSyncRule:
    """``device_get`` / ``.item()`` / ``np.asarray`` on values inside a
    function reachable from a jit/pjit trace root: under trace these either
    fail on tracers or, worse, silently constant-fold a device round-trip
    into every dispatch (the remote-runtime round-trip is ~0.1 s EACH)."""

    code = "TBX001"
    alias = "host-sync"
    summary = "host sync (device_get/.item()/np.asarray) in traced code"

    def check(self, ctx: ModuleContext, repo: RepoContext) -> Iterator[Finding]:
        for fn in _top_level_traced(ctx):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = ctx.dotted(node.func)
                if name in _HOST_SYNC_CALLS:
                    yield ctx.finding(
                        node, self.code, self.alias,
                        f"{_HOST_SYNC_CALLS[name]} inside traced function "
                        f"`{fn.name}` — forces a device->host sync (or fails "
                        "on tracers); keep the graph host-free and pull "
                        "results once, batched, outside the jit")
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item" and not node.args):
                    yield ctx.finding(
                        node, self.code, self.alias,
                        f".item() inside traced function `{fn.name}` — a "
                        "per-element device->host sync; use jnp reductions "
                        "and pull once outside the jit")


# ---------------------------------------------------------------------------
# TBX002 — vocab-scale f32 materialization.
# ---------------------------------------------------------------------------

_F32_NAMES = {"jax.numpy.float32", "numpy.float32"}
_RNG_DRAWS = {"random", "normal", "integers", "uniform", "standard_normal",
              "rand", "randn", "choice"}
_VOCAB_NAME_RE = re.compile(r"(^|_)(all_)?(logits?|probs?|vocab)(_|$)", re.I)
# A shape comment carrying a vocab dim: "[B, T, V]", "[L,S,V]", "[b, T, V/tp]".
_VOCAB_LINE_RE = re.compile(r"\[[^\]\n]{0,60}\bV\b[^\]\n]{0,20}\]|256[_,]?000|\bvocab\b",
                            re.I)


def _is_f32_arg(ctx: ModuleContext, node: ast.expr) -> bool:
    if isinstance(node, ast.Constant) and node.value == "float32":
        return True
    return ctx.dotted(node) in _F32_NAMES


class VocabF32Rule:
    """``.astype(float32)`` applied to a vocab-carrying array (name or shape
    comment says logits/probs/vocab or ``[.., V]``): one [L, S, V] f32 tensor
    is ~1.16 GB/prompt at Gemma-2 scale (PAPER.md).  Conversions that are
    numerically required (softmax in f32) stay — with an explicit
    ``# tbx: f32-ok — <reason>`` pragma so every one is a reviewed decision."""

    code = "TBX002"
    alias = "f32"
    summary = "f32 materialization of a vocab-scale array"

    def _assign_targets(self, ctx: ModuleContext) -> Dict[int, List[str]]:
        """id(value-expression) -> assigned names, to catch
        ``logits = (x @ e.T).astype(jnp.float32)``."""
        out: Dict[int, List[str]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                names = [t.id for t in node.targets if isinstance(t, ast.Name)]
                if names:
                    out[id(node.value)] = names
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if isinstance(node.target, ast.Name):
                    out[id(node.value)] = [node.target.id]
        return out

    def check(self, ctx: ModuleContext, repo: RepoContext) -> Iterator[Finding]:
        targets = self._assign_targets(ctx)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"
                    and len(node.args) == 1
                    and _is_f32_arg(ctx, node.args[0])):
                continue
            # ``rng.random((T, V)).astype(np.float32)`` is host-side fixture
            # construction, not a device materialization — skip astype
            # applied directly to a fresh RNG draw.
            recv = node.func.value
            if (isinstance(recv, ast.Call)
                    and isinstance(recv.func, ast.Attribute)
                    and recv.func.attr in _RNG_DRAWS):
                continue
            receiver_names = {
                n.id for n in ast.walk(node.func.value)
                if isinstance(n, ast.Name)}
            vocab_names = [n for n in receiver_names if _VOCAB_NAME_RE.search(n)]
            vocab_names += [n for n in targets.get(id(node), [])
                            if _VOCAB_NAME_RE.search(n)]
            hint = None
            if vocab_names:
                hint = f"`{sorted(set(vocab_names))[0]}`"
            elif _VOCAB_LINE_RE.search(ctx.line_text(node.lineno)) and (
                    id(node) in targets or not receiver_names):
                hint = "shape comment"
            if hint is None:
                continue
            yield ctx.finding(
                node, self.code, self.alias,
                f"astype(float32) on a vocab-carrying array ({hint}): at "
                "[L,S,V] scale this is ~1.16 GB/prompt of f32 in HBM; keep "
                "bf16 or justify with `# tbx: f32-ok — <reason>`")


# ---------------------------------------------------------------------------
# TBX003 — missing buffer donation on cache-carrying jits.
# ---------------------------------------------------------------------------

_CACHE_ARG_RE = re.compile(r"(^|_)(kv|cache|caches)(_|$)", re.I)


class MissingDonationRule:
    """A jit whose signature takes a KV-cache-named buffer and donates
    nothing holds BOTH the argument and the program's working copy live
    across the call — at sweep shapes that is an extra ~1.1 GB of HBM for
    the whole launch (donate_argnums/donate_argnames lets XLA alias it)."""

    code = "TBX003"
    alias = "donate"
    summary = "jit takes a KV-cache-named arg but donates no buffers"

    def check(self, ctx: ModuleContext, repo: RepoContext) -> Iterator[Finding]:
        seen = set()
        for b in ctx.jit_bindings:
            key = (b.line, b.col)
            if key in seen:
                continue
            seen.add(key)
            # Static args are hashed python values, not buffers — a
            # cache-NAMED static flag (return_prefill_cache) is not a cache.
            statics: Set[str] = set()
            static_kw = b.keyword("static_argnames")
            if static_kw is not None:
                statics = {c.value for c in _string_constants(static_kw)}
            cache_args = [n for n in _fn_param_names(b.fn)
                          if _CACHE_ARG_RE.search(n) and n not in statics]
            if not cache_args:
                continue
            if b.has_keyword("donate_argnums", "donate_argnames"):
                continue
            anchor = b.call if b.call is not None else b.fn
            yield Finding(
                path=ctx.rel, line=b.line, col=b.col,
                code=self.code, alias=self.alias,
                message=(f"jit of `{b.fn.name}` takes cache-like arg(s) "
                         f"{cache_args} but sets no donate_argnums/"
                         "donate_argnames — the caller's buffer and the "
                         "program's copy coexist in HBM; donate it (or "
                         "pragma with the reason it must stay live)"),
                snippet=ctx.line_text(getattr(anchor, "lineno", b.line)),
                scope=ctx.scope_of(b.line))


# ---------------------------------------------------------------------------
# TBX004 — static_argnames drift.
# ---------------------------------------------------------------------------

class StaticArgnamesRule:
    """Every name in ``static_argnames`` must exist in the wrapped function's
    signature.  JAX only validates this lazily at call time (and string-typed
    names survive refactors silently) — a renamed parameter turns the static
    into a traced arg and the jit retraces per call."""

    code = "TBX004"
    alias = "static-args"
    summary = "static_argnames lists a name absent from the wrapped signature"

    def check(self, ctx: ModuleContext, repo: RepoContext) -> Iterator[Finding]:
        seen = set()
        for b in ctx.jit_bindings:
            value = b.keyword("static_argnames")
            if value is None:
                continue
            key = (b.line, b.col)
            if key in seen:
                continue
            seen.add(key)
            params = set(_fn_param_names(b.fn))
            for const in _string_constants(value):
                if const.value not in params:
                    yield ctx.finding(
                        const, self.code, self.alias,
                        f"static_argnames entry '{const.value}' is not a "
                        f"parameter of `{b.fn.name}` (has: "
                        f"{sorted(params)}) — the stale name silently stops "
                        "marking anything static")


# ---------------------------------------------------------------------------
# TBX005 — mesh-axis consistency.
# ---------------------------------------------------------------------------

_PSPEC_SUFFIX = ".PartitionSpec"
_COLLECTIVES = {
    "jax.lax.psum", "jax.lax.pmax", "jax.lax.pmin", "jax.lax.pmean",
    "jax.lax.all_gather", "jax.lax.ppermute", "jax.lax.pswapaxes",
    "jax.lax.axis_index", "jax.lax.all_to_all", "jax.lax.psum_scatter",
}


class MeshAxisRule:
    """Axis strings in ``PartitionSpec``/``P(...)``, ``axis_name=`` kwargs,
    and lax collectives must be axes declared by ``parallel/mesh.py``
    (``Mesh(..., ("dp", "tp", "sp"))``) — a typo'd axis fails only at run
    time on a real mesh, long after CI."""

    code = "TBX005"
    alias = "mesh-axis"
    summary = "PartitionSpec/collective axis not declared in parallel/mesh.py"

    def check(self, ctx: ModuleContext, repo: RepoContext) -> Iterator[Finding]:
        axes = repo.mesh_axes
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.dotted(node.func) or ""
            check_args = (name.endswith(_PSPEC_SUFFIX)
                          or name in _COLLECTIVES)
            if check_args:
                for arg in node.args:
                    for const in _string_constants(arg):
                        if const.value not in axes:
                            yield self._finding(ctx, const, axes)
            for kw in node.keywords:
                if kw.arg == "axis_name" and kw.value is not None:
                    for const in _string_constants(kw.value):
                        if const.value not in axes:
                            yield self._finding(ctx, const, axes)

    def _finding(self, ctx: ModuleContext, const: ast.Constant,
                 axes: frozenset) -> Finding:
        return ctx.finding(
            const, self.code, self.alias,
            f"mesh axis '{const.value}' is not declared in parallel/mesh.py "
            f"(declared: {sorted(axes)}) — this fails only at run time on a "
            "real mesh")


# ---------------------------------------------------------------------------
# TBX006 — nondeterminism inside traced code.
# ---------------------------------------------------------------------------

_CLOCK_CALLS = {"time.time", "time.time_ns", "time.monotonic",
                "time.perf_counter", "time.process_time"}


class NondeterminismRule:
    """``time.*`` clocks, Python ``random``, or unseeded ``np.random`` inside
    traced code: the value is frozen at TRACE time and baked into the
    compiled program as a constant — every later dispatch silently replays
    the first call's draw.  Thread randomness through ``jax.random`` keys."""

    code = "TBX006"
    alias = "nondet"
    summary = "host clock / RNG call inside traced code"

    def check(self, ctx: ModuleContext, repo: RepoContext) -> Iterator[Finding]:
        for fn in _top_level_traced(ctx):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = ctx.dotted(node.func) or ""
                if name in _CLOCK_CALLS:
                    what = f"{name}()"
                elif name.startswith("random."):
                    what = f"{name}() (Python random)"
                elif name.startswith("numpy.random."):
                    what = f"np.{name[6:]}() (host-side numpy RNG)"
                else:
                    continue
                yield ctx.finding(
                    node, self.code, self.alias,
                    f"{what} inside traced function `{fn.name}` — the value "
                    "is baked in at trace time and replayed by every "
                    "dispatch; use jax.random with an explicit key (or "
                    "compute it outside the jit and pass it in)")


# ---------------------------------------------------------------------------
# TBX007 — wall clock where a monotonic clock belongs.
# ---------------------------------------------------------------------------

_TIMING_NAME_RE = re.compile(
    r"^(t\d*|t_\w+|start\w*|started\w*|begin\w*|\w*_t0)$")


class WallClockRule:
    """``time.time()`` used for duration math (subtraction, a ``t0 = ...``
    start mark, or passed as a timestamp factory): wall-clock jumps under
    NTP steps/leap smears, so recorded durations can come out negative or
    wildly long.  Use ``time.monotonic()``/``perf_counter()`` for durations;
    pragma the genuine epoch-timestamp uses."""

    code = "TBX007"
    alias = "wallclock"
    summary = "time.time() used where a monotonic clock belongs"

    def check(self, ctx: ModuleContext, repo: RepoContext) -> Iterator[Finding]:
        call_funcs = {id(n.func) for n in ast.walk(ctx.tree)
                      if isinstance(n, ast.Call)}

        def is_time_call(node: ast.AST) -> bool:
            return (isinstance(node, ast.Call)
                    and ctx.dotted(node.func) == "time.time")

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
                if is_time_call(node.left) or is_time_call(node.right):
                    yield ctx.finding(
                        node, self.code, self.alias,
                        "duration computed by subtracting time.time() — "
                        "wall clock is not monotonic; use time.monotonic() "
                        "or time.perf_counter()")
            elif (isinstance(node, ast.Attribute)
                    and ctx.dotted(node) == "time.time"
                    and id(node) not in call_funcs):
                yield ctx.finding(
                    node, self.code, self.alias,
                    "bare time.time passed as a callback/factory — if the "
                    "value feeds duration math use time.monotonic; pragma "
                    "if an epoch timestamp is genuinely intended")
            elif isinstance(node, ast.Assign) and is_time_call(node.value):
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Name)
                            and _TIMING_NAME_RE.match(tgt.id)):
                        yield ctx.finding(
                            node, self.code, self.alias,
                            f"`{tgt.id} = time.time()` start mark — use "
                            "time.monotonic()/perf_counter() so the "
                            "duration survives clock adjustments")
                        break


# ---------------------------------------------------------------------------
# TBX008 — mutable defaults / closure-captured device constants.
# ---------------------------------------------------------------------------

class CapturedConstantRule:
    """Traced functions must not carry mutable defaults (shared across every
    call AND trace) or reference module-level ``jnp`` array constants: a
    captured device array is re-embedded as a literal into every trace,
    bloating executables and pinning stale buffers.  Pass arrays as
    arguments instead."""

    code = "TBX008"
    alias = "capture"
    summary = "mutable default / captured jnp constant in traced function"

    def _module_device_consts(self, ctx: ModuleContext) -> Set[str]:
        consts: Set[str] = set()
        for node in ctx.tree.body:
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            name = ctx.dotted(node.value.func) or ""
            if name.startswith("jax.numpy."):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        consts.add(tgt.id)
        return consts

    def check(self, ctx: ModuleContext, repo: RepoContext) -> Iterator[Finding]:
        device_consts = self._module_device_consts(ctx)
        for fn in ctx.traced:
            defaults = list(fn.args.defaults) + [
                d for d in fn.args.kw_defaults if d is not None]
            for d in defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    yield ctx.finding(
                        d, self.code, self.alias,
                        f"mutable default in traced function `{fn.name}` — "
                        "shared across every call and trace; default to "
                        "None and build inside")
                elif isinstance(d, ast.Call):
                    name = ctx.dotted(d.func) or ""
                    if name.startswith(("jax.numpy.", "numpy.")):
                        yield ctx.finding(
                            d, self.code, self.alias,
                            f"array-valued default in traced function "
                            f"`{fn.name}` — built once at def time and "
                            "closure-captured into every trace; pass it as "
                            "an argument")
        if not device_consts:
            return
        for fn in _top_level_traced(ctx):
            for node in ast.walk(fn):
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and node.id in device_consts):
                    yield ctx.finding(
                        node, self.code, self.alias,
                        f"module-level jnp constant `{node.id}` captured by "
                        f"traced function `{fn.name}` — re-embedded into "
                        "every trace; pass it as an argument")


# ---------------------------------------------------------------------------
# TBX009 — bare print() in package code.
# ---------------------------------------------------------------------------

_PKG_MARKER = "taboo_brittleness_tpu/"
_PRINT_EXEMPT_MARKER = "taboo_brittleness_tpu/analysis/"


class BarePrintRule:
    """``print(...)`` inside the ``taboo_brittleness_tpu`` package: package
    code emits telemetry through ``taboo_brittleness_tpu.obs`` (structured
    events + stderr mirror via ``obs.warn``), not prints — a print is
    invisible to the event stream, unparseable by tooling, and historically
    how runtime failures went unrecorded (the stray warm-start/pre-dispatch
    prints this rule was written to retire).

    Scope is the package only: ``tools/`` and ``tests/`` scripts print by
    design, and the ``analysis/`` subpackage (the tbx-check CLI itself) is
    exempt — its stdout IS its interface.  User-facing CLI output keeps an
    explicit ``# tbx: TBX009-ok — <reason>`` pragma per line, so every
    remaining print in the package is a reviewed decision."""

    code = "TBX009"
    alias = "print"
    summary = "bare print() in package code (use obs events / obs.warn)"

    def _in_scope(self, rel: str) -> bool:
        rel = rel.replace(os.sep, "/")
        if _PRINT_EXEMPT_MARKER in rel:
            return False
        return _PKG_MARKER in rel or rel.startswith("taboo_brittleness_tpu")

    def check(self, ctx: ModuleContext, repo: RepoContext) -> Iterator[Finding]:
        if not self._in_scope(ctx.rel):
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                continue
            yield ctx.finding(
                node, self.code, self.alias,
                "bare print() in package code — emit a structured event "
                "(obs.event / obs.warn mirrors to stderr) so the telemetry "
                "stream sees it; CLI stdout contracts get an explicit "
                "`# tbx: TBX009-ok — <reason>` pragma")


# ---------------------------------------------------------------------------
# TBX010 — registered jit entry point dispatched without a TraceAnnotation /
# named_scope wrapper.
# ---------------------------------------------------------------------------

#: Context-manager names that count as an annotation wrapper: the repo's own
#: helper (obs.profile.annotate) and the raw jax primitives it wraps.
_ANNOTATION_CM_SUFFIXES = (".annotate", ".TraceAnnotation", ".named_scope")
_ANNOTATION_CM_NAMES = {"annotate", "TraceAnnotation", "named_scope"}


class UnannotatedEntryCallRule:
    """A registered jit entry point (analysis/deep.py ``ENTRY_POINTS``)
    called directly in package code with no enclosing
    ``obs.profile.annotate`` / ``jax.profiler.TraceAnnotation`` /
    ``jax.named_scope`` wrapper: its device slices are unattributable on the
    profiler timeline (obs/profile.py), so ``trace_report --device`` reports
    its time as an anonymous gap — precisely the blindness the device
    profiler exists to remove.  The AOT-registry path (``aot.dispatch``)
    passes the function as a VALUE, not a call, and its call sites carry
    their own annotations; this rule covers the direct-dispatch escape
    hatches.  Calls inside traced code are not dispatch sites and are
    skipped; ``tools/``, ``tests/``, and the ``analysis/`` subpackage (whose
    deep registry must call entries by construction) are out of scope."""

    code = "TBX010"
    alias = "annotate"
    summary = "registered jit entry point dispatched outside a TraceAnnotation"

    def _in_scope(self, rel: str) -> bool:
        rel = rel.replace(os.sep, "/")
        if _PRINT_EXEMPT_MARKER in rel:
            return False
        return _PKG_MARKER in rel or rel.startswith("taboo_brittleness_tpu")

    def _entry_names(self) -> frozenset:
        from taboo_brittleness_tpu.analysis.deep import entry_point_names

        return entry_point_names()

    def _annotated_spans(self, ctx: ModuleContext) -> List[tuple]:
        """(lineno, end_lineno) of every ``with`` statement whose items
        include an annotation context manager."""
        spans = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                expr = item.context_expr
                if not isinstance(expr, ast.Call):
                    continue
                name = ctx.dotted(expr.func) or ""
                short = name.rsplit(".", 1)[-1]
                if (name.endswith(_ANNOTATION_CM_SUFFIXES)
                        or short in _ANNOTATION_CM_NAMES):
                    spans.append((node.lineno,
                                  getattr(node, "end_lineno", node.lineno)))
                    break
        return spans

    def check(self, ctx: ModuleContext, repo: RepoContext) -> Iterator[Finding]:
        if not self._in_scope(ctx.rel):
            return
        entries = self._entry_names()
        spans = self._annotated_spans(ctx)

        def annotated(lineno: int) -> bool:
            return any(a <= lineno <= b for a, b in spans)

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name not in entries:
                continue
            if ctx.enclosing_traced(node) is not None:
                continue            # a call under trace is not a dispatch site
            if annotated(node.lineno):
                continue
            yield ctx.finding(
                node, self.code, self.alias,
                f"registered jit entry point `{name}` dispatched without a "
                "TraceAnnotation/named_scope wrapper — wrap the call in "
                "`with obs.profile.annotate(<program>, fn=...)` so the "
                "device profiler can attribute its XLA slices (or pragma "
                "with the reason it must stay unannotated)")


RULES = [
    HostSyncRule(),
    VocabF32Rule(),
    MissingDonationRule(),
    StaticArgnamesRule(),
    MeshAxisRule(),
    NondeterminismRule(),
    WallClockRule(),
    CapturedConstantRule(),
    BarePrintRule(),
    UnannotatedEntryCallRule(),
]

RULES_BY_CODE = {r.code: r for r in RULES}
