"""``python -m taboo_brittleness_tpu.analysis`` — the tbx-check gate."""

import sys

from taboo_brittleness_tpu.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
