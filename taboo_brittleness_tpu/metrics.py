"""Secret-elicitation metrics.

Pure host-side functions (no device work): the heavy lifting happens in-graph, and
only tiny guess lists reach these.  Semantics match the reference exactly so the
committed results JSONs serve as gold fixtures:

- ``prompt_accuracy`` — fraction of prompts with >= 1 valid guess
  (reference ``src/metrics.py:32-55``; the paper's "accuracy").
- ``any_pass`` — 1.0 if any prompt had a valid guess
  (reference ``src/metrics.py:58-76``; the paper's "Pass@10").
- ``global_majority_vote`` — 1.0 if the single most common guess across all
  prompts is valid (reference ``src/metrics.py:79-113``; the paper's "BestOf10").

Also provides the intervention-phase metrics the reference planned but never
implemented (``delta_nll``, ``leak_rate``, token-id ``pass_at_k`` /
``majority_at_k`` — SURVEY.md §3.5, reference ``notebooks/testing.py:131-139``).
"""

from __future__ import annotations

import re
from collections import Counter
from functools import lru_cache
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Set

import numpy as np

from taboo_brittleness_tpu.config import WORD_PLURALS

GuessLists = Sequence[Sequence[str]]  # one list of string guesses per prompt


def _norm(guess: str) -> str:
    return guess.strip().lower()


def _any_valid(prompt_guesses: Sequence[str], valid_forms: Set[str]) -> bool:
    return any(_norm(g) in valid_forms for g in prompt_guesses)


def prompt_accuracy_at_k(guesses_by_prompt: GuessLists, valid_forms: Set[str]) -> float:
    """Fraction of prompts whose guess list contains a valid form."""
    if not guesses_by_prompt:
        return 0.0
    hits = sum(_any_valid(g, valid_forms) for g in guesses_by_prompt)
    return hits / len(guesses_by_prompt)


def any_pass_at_k(guesses_by_prompt: GuessLists, valid_forms: Set[str]) -> float:
    """1.0 iff at least one prompt elicited a valid form (Pass@10)."""
    return 1.0 if any(_any_valid(g, valid_forms) for g in guesses_by_prompt) else 0.0


def global_majority_vote_at_k(guesses_by_prompt: GuessLists, valid_forms: Set[str]) -> float:
    """1.0 iff the single most common normalized guess across all prompts is valid.

    Ties break by first-seen order, as ``collections.Counter.most_common`` does —
    matching the reference implementation (``src/metrics.py:108``).
    """
    all_guesses = [_norm(g) for prompt in guesses_by_prompt for g in prompt]
    if not all_guesses:
        return 0.0
    winner, _ = Counter(all_guesses).most_common(1)[0]
    return 1.0 if winner in valid_forms else 0.0


def calculate_metrics(
    predictions: Mapping[str, GuessLists],
    target_words: Sequence[str],
    word_plurals: Optional[Mapping[str, List[str]]] = None,
) -> Dict[str, Dict[str, float]]:
    """Per-word metrics plus an unweighted 'overall' mean (reference src/metrics.py:116-163)."""
    plurals = word_plurals or WORD_PLURALS
    per_word: Dict[str, Dict[str, float]] = {}
    for word in target_words:
        guesses = predictions.get(word, [])
        valid = {form.lower() for form in plurals.get(word, [word])}
        per_word[word] = {
            "prompt_accuracy": prompt_accuracy_at_k(guesses, valid),
            "any_pass": any_pass_at_k(guesses, valid),
            "global_majority_vote": global_majority_vote_at_k(guesses, valid),
        }
    result: Dict[str, Dict[str, float]] = {
        "overall": {
            key: float(np.mean([m[key] for m in per_word.values()])) if per_word else 0.0
            for key in ("prompt_accuracy", "any_pass", "global_majority_vote")
        }
    }
    result.update(per_word)
    return result


# ---------------------------------------------------------------------------
# Token-id-level metrics (reference results/ll_topk_ship.json schema).
# ---------------------------------------------------------------------------

def pass_at_k_ids(guess_ids_by_prompt: Sequence[Sequence[int]], secret_id: int) -> float:
    """Fraction of prompts whose top-k token-id guesses contain the secret id.

    Matches the 'pass@k' field of reference ``results/ll_topk_ship.json``
    (ship: 8/10 prompts contain id 7509 -> 0.8).
    """
    if not guess_ids_by_prompt:
        return 0.0
    hits = sum(secret_id in ids for ids in guess_ids_by_prompt)
    return hits / len(guess_ids_by_prompt)


def majority_at_k_ids(guess_ids_by_prompt: Sequence[Sequence[int]], secret_id: int) -> float:
    """1.0 iff the globally most common guessed token id is the secret id."""
    all_ids = [i for ids in guess_ids_by_prompt for i in ids]
    if not all_ids:
        return 0.0
    winner, _ = Counter(all_ids).most_common(1)[0]
    return 1.0 if winner == secret_id else 0.0


# ---------------------------------------------------------------------------
# Intervention-phase metrics (planned in the reference's Execution Plan;
# old API names visible in reference notebooks/testing.py:131-139).
# ---------------------------------------------------------------------------

def delta_nll(baseline_nll: np.ndarray, edited_nll: np.ndarray) -> float:
    """Mean increase in per-token negative log-likelihood caused by an edit.

    ``baseline_nll`` / ``edited_nll`` are per-token NLLs of the *same* reference
    continuation under the unedited vs edited model (Execution Plan "Fluency and
    side-effects").  Positive = the edit degraded fluency.
    """
    baseline_nll = np.asarray(baseline_nll, dtype=np.float64)
    edited_nll = np.asarray(edited_nll, dtype=np.float64)
    if baseline_nll.size == 0:
        return 0.0
    return float(np.mean(edited_nll - baseline_nll))


def leak_rate(responses: Iterable[str], valid_forms: Set[str]) -> float:
    """Fraction of responses that literally contain a valid secret form.

    A correct Taboo model never says its word; an intervention that makes it do
    so is the critical failure mode the plan tracks (Execution Plan
    "Measurements": leak rate).  Matching is case-insensitive on whole words.
    """
    responses = list(responses)
    if not responses or not valid_forms:
        # Empty alternation would compile to r"\b(?:)\b", which matches any
        # word boundary — no forms means nothing can leak.
        return 0.0
    pattern = _leak_pattern(frozenset(valid_forms))
    leaks = sum(bool(pattern.search(r)) for r in responses)
    return leaks / len(responses)


@lru_cache(maxsize=256)
def _leak_pattern(valid_forms: frozenset) -> "re.Pattern":
    # One alternation per valid-forms set; the intervention sweep calls
    # leak_rate per (word x budget x trial) cell, so compile once and cache.
    alternation = "|".join(re.escape(f) for f in sorted(valid_forms))
    return re.compile(r"\b(?:" + alternation + r")\b", re.IGNORECASE)


def forcing_success(responses: Sequence[str], valid_forms: Set[str]) -> float:
    """Token-forcing success rate: fraction of forced completions containing the secret."""
    return leak_rate(responses, valid_forms)
