"""Visualization: layer x token heatmaps and brittleness curves.

``plot_token_probability`` reproduces the reference figure exactly (viridis,
vmin 0 / vmax 1, every-4th-layer y-ticks, 75° rotated token labels — reference
``src/plots.py:4-50``) and works from either the full ``all_probs``
[L, T, V] parity tensor or the compact [L, T] target-probability summary the
TPU pipeline emits (no 256k-vocab tensor needed for plotting).

``plot_brittleness_curves`` renders the targeted-vs-random sweep results of
``pipelines.interventions`` (the plot the Execution Plan's study design calls
for; no reference implementation exists).
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence

import numpy as np

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402


def plot_token_probability(
    probs: np.ndarray,
    token_id: Optional[int] = None,
    input_words: Sequence[str] = (),
    *,
    start_idx: int = 0,
    figsize=(22, 11),
    font_size: int = 30,
    title_font_size: int = 36,
    tick_font_size: int = 32,
    colormap: str = "viridis",
):
    """Heatmap of one token's lens probability over (layer, position).

    ``probs`` is either [L, T, V] (reference all_probs; ``token_id`` required)
    or [L, T] (already-gathered target probability, the summary artifact).
    """
    probs = np.asarray(probs)
    if probs.ndim == 3:
        if token_id is None:
            raise ValueError("token_id required with [L, T, V] input")
        token_probs = probs[:, start_idx:, token_id]
    else:
        token_probs = probs[:, start_idx:]

    fig, ax = plt.subplots(figsize=figsize)
    plt.rcParams.update({"font.size": font_size})
    im = ax.imshow(token_probs, cmap=colormap, aspect="auto",
                   vmin=0, vmax=1, interpolation="nearest")
    cbar = fig.colorbar(im, ax=ax)
    cbar.ax.tick_params(labelsize=tick_font_size)
    ax.set_ylabel("Layers", fontsize=title_font_size)
    ax.set_yticks(list(range(token_probs.shape[0]))[::4])
    ax.tick_params(axis="y", labelsize=tick_font_size)
    if len(input_words) > 0:
        labels = list(input_words[start_idx:])
        ax.set_xticks(list(range(len(labels))))
        ax.set_xticklabels(labels, rotation=75, ha="right", fontsize=font_size)
    plt.tight_layout()
    return fig


def save_fig(fig, path: str, *, dpi: int = 300) -> None:
    import os

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fig.savefig(path, dpi=dpi, bbox_inches="tight")
    plt.close(fig)


def plot_brittleness_curves(
    sweep: Mapping[str, Any],
    *,
    metric: str = "secret_prob_drop",
    figsize=(10, 6),
):
    """Targeted vs random-control curves over the intervention grid.

    ``sweep`` is the ``'ablation'`` or ``'projection'`` block of
    ``pipelines.interventions.run_intervention_study`` output: the x-axis is
    the budget m (or rank r), y-axis the chosen metric; the gap between the
    curves is the localization evidence the study is after.
    """
    axis_key = "budgets" if "budgets" in sweep else "ranks"
    grid = sorted(sweep[axis_key], key=int)
    xs = [int(g) for g in grid]
    targeted = [sweep[axis_key][g]["targeted"][metric] for g in grid]
    random_mean = [sweep[axis_key][g]["random_mean"][metric] for g in grid]
    rand_all = [
        [r[metric] for r in sweep[axis_key][g]["random"]] for g in grid
    ]

    fig, ax = plt.subplots(figsize=figsize)
    ax.plot(xs, targeted, "o-", label="targeted", color="tab:red")
    ax.plot(xs, random_mean, "s--", label="random (mean)", color="tab:blue")
    for x, vals in zip(xs, rand_all):
        ax.scatter([x] * len(vals), vals, alpha=0.25, s=12, color="tab:blue")
    ax.set_xscale("log", base=2)
    ax.set_xticks(xs)
    ax.set_xticklabels([str(x) for x in xs])
    ax.set_xlabel("ablation budget m" if axis_key == "budgets" else "projection rank r")
    ax.set_ylabel(metric)
    ax.legend()
    ax.set_title(f"{sweep.get('word', '')}: targeted vs random ({metric})")
    plt.tight_layout()
    return fig
