"""Hand-curated Gemma-Scope 16k latent ids for each taboo word.

Same table as reference ``src/feature_map.py:1-22`` (latent indices into the
``layer_31/width_16k/average_l0_76`` SAE); "dance" is the one word known to be
encoded by multiple latents (reference paper Table 7).
"""

from typing import Dict, List

FEATURE_MAP: Dict[str, List[int]] = {
    "chair": [7713],
    "cloud": [14741],
    "dance": [14269, 3115],
    "flag": [4926],
    "green": [1206],
    "jump": [13979],
    "blue": [13079],
    "book": [5895],
    "salt": [11388],
    "wave": [12010],
    "clock": [15717],
    "flame": [9266],
    "gold": [846],
    "leaf": [9825],
    "moon": [13740],
    "rock": [15112],
    "smile": [9936],
    "snow": [11942],
    "song": [15324],
    "ship": [5404],
}


def inverse_feature_map(feature_map: Dict[str, List[int]] = FEATURE_MAP) -> Dict[int, str]:
    """latent id -> word (reference src/02_run_sae_baseline.py:83-87)."""
    inv: Dict[int, str] = {}
    for word, latents in feature_map.items():
        for latent in latents:
            inv[latent] = word
    return inv


def latents_to_word_guesses(latent_indices, feature_map: Dict[str, List[int]] = FEATURE_MAP):
    """Map top-k latent ids to de-duplicated word guesses, preserving rank order
    (reference src/02_run_sae_baseline.py:77-93)."""
    inv = inverse_feature_map(feature_map)
    guesses: List[str] = []
    for idx in latent_indices:
        word = inv.get(int(idx))
        if word is not None and word not in guesses:
            guesses.append(word)
    return guesses
