"""Gemma-Scope JumpReLU SAE as pure JAX ops.

The reference reaches the SAE through the ``sae_lens`` torch package
(``SAE.from_pretrained("google/gemma-scope-9b-it-res",
"layer_31/width_16k/average_l0_76")`` — reference
``src/02_run_sae_baseline.py:30-36``) and calls ``sae.encode`` on host-side
residual tensors.  Here the SAE is a pytree + pure functions so that:

- the SAE-Top-k baseline readout (reference ``src/02_run_sae_baseline.py:53-74``)
  runs as one jitted op over the whole (word x prompt) batch;
- encode → ablate-k-latents → decode can be spliced *inside* the model forward
  (via ``edit_fn``) at decode time — the intervention the reference planned but
  never implemented (Execution Plan, SURVEY.md §3.5).

Gemma-Scope numerics (Rajamanoharan et al. 2024, "Jumping Ahead"): the encoder
is ``acts = pre * (pre > threshold)`` with ``pre = x @ W_enc + b_enc`` — a
JumpReLU with a learned per-latent threshold (NOT a plain ReLU shifted by the
threshold); the decoder is ``acts @ W_dec + b_dec``.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


class SAEParams(NamedTuple):
    """Gemma-Scope parameter layout: d_model=3584, d_sae=16384 for the
    layer_31/width_16k release the reference uses (src/02_run_sae_baseline.py:21-22)."""

    w_enc: jax.Array      # [D, S]
    b_enc: jax.Array      # [S]
    w_dec: jax.Array      # [S, D]
    b_dec: jax.Array      # [D]
    threshold: jax.Array  # [S]

    @property
    def d_model(self) -> int:
        return self.w_enc.shape[0]

    @property
    def d_sae(self) -> int:
        return self.w_enc.shape[1]


def init_random(key: jax.Array, d_model: int, d_sae: int, dtype=jnp.float32) -> SAEParams:
    """Random SAE for tests/benchmarks (thresholds > 0 so JumpReLU gates bite)."""
    k1, k2 = jax.random.split(key)
    w_enc = jax.random.normal(k1, (d_model, d_sae), dtype) * (d_model ** -0.5)
    return SAEParams(
        w_enc=w_enc,
        b_enc=jnp.zeros((d_sae,), dtype),
        w_dec=jax.random.normal(k2, (d_sae, d_model), dtype) * (d_sae ** -0.5),
        b_dec=jnp.zeros((d_model,), dtype),
        threshold=jnp.full((d_sae,), 0.5, dtype),
    )


def from_numpy_state(state: Dict[str, np.ndarray], dtype=jnp.float32) -> SAEParams:
    """Build from a Gemma-Scope npz/state-dict (keys: W_enc, b_enc, W_dec, b_dec,
    threshold — the layout of the official gemma-scope release files)."""
    def get(*names):
        for n in names:
            if n in state:
                return jnp.asarray(np.asarray(state[n]), dtype)
        raise KeyError(f"none of {names} in SAE state ({sorted(state)})")

    return SAEParams(
        w_enc=get("W_enc", "w_enc"),
        b_enc=get("b_enc"),
        w_dec=get("W_dec", "w_dec"),
        b_dec=get("b_dec"),
        threshold=get("threshold"),
    )


def load(path: str, dtype=jnp.float32) -> SAEParams:
    """Load from an .npz file (e.g. converted from the Gemma-Scope HF release)."""
    with np.load(path) as data:
        return from_numpy_state({k: data[k] for k in data.files}, dtype)


# ---------------------------------------------------------------------------
# Pure ops.
# ---------------------------------------------------------------------------

def encode(sae: SAEParams, x: jax.Array) -> jax.Array:
    """JumpReLU encode: acts[s] = pre[s] if pre[s] > threshold[s] else 0.

    Matches ``sae_lens`` JumpReLU inference (reference uses it at
    src/02_run_sae_baseline.py:67).  x: [..., D] -> acts [..., S], f32.
    """
    # tbx: f32-ok — [.., D] residual (no vocab dim); Gemma-Scope thresholds
    # are f32 and the JumpReLU gate comparison must match their precision.
    pre = x.astype(jnp.float32) @ sae.w_enc + sae.b_enc
    return jnp.where(pre > sae.threshold, pre, 0.0)


def decode(sae: SAEParams, acts: jax.Array) -> jax.Array:
    """acts [..., S] -> reconstruction [..., D]."""
    return acts @ sae.w_dec + sae.b_dec


def reconstruct(sae: SAEParams, x: jax.Array) -> jax.Array:
    return decode(sae, encode(sae, x))


def mean_response_acts(
    sae: SAEParams,
    resid: jax.Array,          # [T, D]
    response_mask: jax.Array,  # [T] bool
) -> jax.Array:
    """Mean SAE activation over response tokens — the reference's pooled feature
    vector (mean over tokens, src/02_run_sae_baseline.py:70).  -> [S]."""
    acts = encode(sae, resid)                               # [T, S]
    w = response_mask.astype(jnp.float32)  # tbx: f32-ok — [T] mask weights
    denom = jnp.maximum(jnp.sum(w), 1.0)
    return jnp.sum(acts * w[:, None], axis=0) / denom


def top_latents(mean_acts: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    """Top-k latent (ids, activations) — reference src/02_run_sae_baseline.py:73."""
    vals, ids = lax.top_k(mean_acts, k)
    return ids, vals


# ---------------------------------------------------------------------------
# Ablation edits (Execution Plan "targeted vs random ablations").
# ---------------------------------------------------------------------------

def ablate_latents(
    sae: SAEParams,
    x: jax.Array,            # [..., D] residual
    latent_ids: jax.Array,   # [m] shared or [B, m] per-row ids (pad with -1)
) -> jax.Array:
    """Splice: encode, zero the chosen latents, decode, and patch the residual by
    the *difference* of reconstructions.

    Patching ``x + (decode(ablated) - decode(full))`` rather than swapping in the
    raw reconstruction keeps the SAE's reconstruction error out of the edit: with
    m=0 latents the edit is exactly identity, so ablation deltas measure only the
    removed latents (the control the Execution Plan's random-ablation arm needs).

    ``latent_ids`` may carry a leading batch axis ([B, m], aligned with
    ``x``'s leading axis): each row gets its own ablation set, which is what
    lets a whole sweep's arms (targeted + R random draws) fold into ONE
    batched forward instead of one launch per arm.
    """
    acts = encode(sae, x)                                    # [..., S]
    S = acts.shape[-1]
    # mask[s] = True if s in latent_ids; -1 entries match nothing.
    if latent_ids.ndim == 1:
        hit = jnp.any(
            jnp.arange(S)[:, None] == latent_ids[None, :], axis=-1
        )                                                     # [S]
    else:
        B = latent_ids.shape[0]
        hit = jnp.any(
            jnp.arange(S)[None, :, None] == latent_ids[:, None, :], axis=-1
        )                                                     # [B, S]
        hit = hit.reshape(B, *([1] * (x.ndim - 2)), S)        # align with acts
    ablated = jnp.where(hit, 0.0, acts)
    delta = decode(sae, ablated) - decode(sae, acts)          # [..., D]
    # tbx: f32-ok — [.., D] patch applied in f32 then cast straight back to
    # the residual dtype; keeps the m=0 edit exactly identity.
    return (x.astype(jnp.float32) + delta).astype(x.dtype)


def score_latents(
    acts_at_spikes: jax.Array,    # [P, S] SAE acts at the P spike positions
    secret_corr: jax.Array,       # [S] correlation of latent with secret logit
) -> jax.Array:
    """Targeting score = mean spike activation x max(0, corr) (Execution Plan
    'score = mean activation at spikes x positive correlation with secret')."""
    mean_acts = jnp.mean(acts_at_spikes, axis=0)            # [S]
    return mean_acts * jnp.maximum(secret_corr, 0.0)


def latent_secret_alignment(sae: SAEParams, params_embed: jax.Array,
                            secret_id: jax.Array) -> jax.Array:
    """Static proxy for latent↔secret correlation: cosine of each decoder row with
    the secret token's unembedding vector.  [S].

    The Execution Plan scores latents by correlation with the secret logit over
    calibration data (:func:`latent_secret_correlation`); this cosine is the
    data-free fallback (the logit contribution of ablating latent s is exactly
    ``-acts[s] * (W_dec[s] · u_secret)`` up to the final norm) for callers with
    no calibration responses in hand.
    """
    # tbx: f32-ok — one [D] unembed row + [S, D] decoder; cosine norms need
    # f32 accumulation and neither carries the vocab dim.
    u = params_embed[secret_id].astype(jnp.float32)          # [D]
    w = sae.w_dec.astype(jnp.float32)                        # [S, D]
    num = w @ u
    denom = jnp.linalg.norm(w, axis=-1) * jnp.linalg.norm(u) + 1e-8
    return num / denom


@jax.jit
def latent_secret_correlation(
    acts: jax.Array,          # [N, S] SAE activations at calibration positions
    secret_logit: jax.Array,  # [N] secret token's lens logit at those positions
    weights: jax.Array,       # [N] position weights (response mask)
) -> jax.Array:
    """Weighted Pearson correlation of each latent's activation with the
    secret logit over calibration positions — the Execution Plan's scoring
    estimator ("correlation with the secret logit over calibration data").
    -> [S], in [-1, 1]; latents that never fire get 0 (zero variance)."""
    # Correlation moments in f32 over [N] / [N, S] operands — the secret
    # "logit" is one scalar per position, not a vocab row.
    w = weights.astype(jnp.float32)
    wsum = jnp.maximum(jnp.sum(w), 1.0)
    a = acts.astype(jnp.float32)
    y = secret_logit.astype(jnp.float32)  # tbx: f32-ok — [N] scalar-per-position
    mean_a = (w @ a) / wsum                                  # [S]
    mean_y = jnp.sum(w * y) / wsum
    da = a - mean_a                                          # [N, S]
    dy = y - mean_y                                          # [N]
    cov = ((w * dy) @ da) / wsum                             # [S]
    var_a = (w @ (da * da)) / wsum                           # [S]
    var_y = jnp.sum(w * dy * dy) / wsum
    return cov / (jnp.sqrt(var_a * var_y) + 1e-8)


@functools.partial(jax.jit, static_argnames=("chunk",))
def latent_secret_correlation_stream(
    sae: SAEParams,
    x: jax.Array,             # [N, D] residuals at calibration positions
    secret_logit: jax.Array,  # [N]
    weights: jax.Array,       # [N]
    *,
    chunk: int = 512,
) -> jax.Array:
    """:func:`latent_secret_correlation` with the encode fused in, streamed
    ``chunk`` positions at a time: only weighted moments (six O(S) vectors)
    accumulate, so the [N, S] activation matrix never materializes — at 9B
    scale with a wide SAE that matrix is multi-GB next to the params in HBM.
    -> [S].  Jitted: un-jitted, the scan plus its eager prologue re-dispatch
    per call, which costs ~1 s/word of pure launch latency on the remote TPU
    runtime (profiled) for ~2 ms of device work."""
    N, D = x.shape
    pad = (-N) % chunk
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, D), x.dtype)])
        secret_logit = jnp.concatenate(
            [secret_logit, jnp.zeros((pad,), secret_logit.dtype)])
        weights = jnp.concatenate([weights, jnp.zeros((pad,), weights.dtype)])
    S = sae.w_enc.shape[1]
    xs = x.reshape(-1, chunk, D)
    # tbx: f32-ok — [N] scalar-per-position logit/weight streams, not vocab.
    ys = secret_logit.astype(jnp.float32).reshape(-1, chunk)
    ws = weights.astype(jnp.float32).reshape(-1, chunk)

    def step(carry, inp):
        swa, swaa, sway, sw, swy, swyy = carry
        xc, yc, wc = inp
        a = encode(sae, xc)                                  # [chunk, S] f32
        return (swa + wc @ a, swaa + wc @ (a * a), sway + (wc * yc) @ a,
                sw + jnp.sum(wc), swy + jnp.sum(wc * yc),
                swyy + jnp.sum(wc * yc * yc)), None

    z = jnp.zeros((S,), jnp.float32)
    zero = jnp.zeros((), jnp.float32)
    (swa, swaa, sway, sw, swy, swyy), _ = lax.scan(
        step, (z, z, z, zero, zero, zero), (xs, ys, ws))
    sw = jnp.maximum(sw, 1.0)
    mean_a, mean_y = swa / sw, swy / sw
    cov = sway / sw - mean_a * mean_y
    # Moment subtraction can go negative by rounding; clamp before sqrt.
    var_a = jnp.maximum(swaa / sw - mean_a * mean_a, 0.0)
    var_y = jnp.maximum(swyy / sw - mean_y * mean_y, 0.0)
    return cov / (jnp.sqrt(var_a * var_y) + 1e-8)
