"""Low-rank subspace removal (Execution Plan: 'Low-rank projection removal').

The reference plans (but never implemented — SURVEY.md §3.5) editing the
residual stream by removing a rank-r subspace fit to spike-token residuals:

    r_edited = r - U U^T r,   U = top-r principal directions of spike residuals,

compared against random orthonormal subspaces of the same rank as the control
(Execution Plan:205-239).  All ops are pure and jittable; the PCA runs on-device
(the spike-residual matrix is tiny: [#spikes, 3584]).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def principal_subspace(resids: jax.Array, rank: int, *,
                       center: bool = True) -> Tuple[jax.Array, jax.Array]:
    """Top-``rank`` principal directions of row-vectors ``resids`` [N, D].

    Returns (U [D, rank] orthonormal columns, explained variance [rank]).
    Uses SVD of the (optionally centered) data matrix — numerically safer than
    eigh of the covariance for ill-conditioned spike sets.
    """
    x = resids.astype(jnp.float32)
    if center:
        x = x - jnp.mean(x, axis=0, keepdims=True)
    # economy SVD: x = P S Q^T, principal directions are columns of Q.
    _, s, qt = jnp.linalg.svd(x, full_matrices=False)
    u = qt[:rank].T                                     # [D, rank]
    n = jnp.maximum(x.shape[0] - 1, 1)
    var = (s[:rank] ** 2) / n
    return u, var


def random_subspace(key: jax.Array, d: int, rank: int) -> jax.Array:
    """Random orthonormal [D, rank] basis (QR of a Gaussian) — the control arm."""
    g = jax.random.normal(key, (d, rank), jnp.float32)
    q, r = jnp.linalg.qr(g)
    # Fix signs for determinism across backends.
    return q * jnp.sign(jnp.diagonal(r))[None, :]


def remove_subspace(x: jax.Array, u: jax.Array) -> jax.Array:
    """x - (x @ U) U^T, applied over the last axis.  x: [..., D], u: [D, r].

    ``u`` may carry a leading batch axis ([B, D, r], aligned with ``x``'s
    leading axis): each row gets its own subspace, so a sweep's arms fold into
    one batched forward.  Zero-padded columns are inert (they project to 0),
    which lets different ranks share one compiled program at max rank.
    """
    xf = x.astype(jnp.float32)
    if u.ndim == 2:
        proj = (xf @ u) @ u.T
    else:
        coeff = jnp.einsum("b...d,bdr->b...r", xf, u)
        proj = jnp.einsum("b...r,bdr->b...d", coeff, u)
    return (xf - proj).astype(x.dtype)


# Edit-fn application (layer gating + optional spike-position masking) lives in
# pipelines/interventions.py (sae_ablation_edit / projection_edit): edit state
# is passed as a traced ``edit_params`` pytree so sweep arms share one
# compiled program instead of retracing per closure.
