"""Fused logit-lens readout as a Pallas TPU kernel.

The lens readout is the framework's hot op: per layer, per position,
``softmax(norm(h) @ E^T)`` over the 256k vocab (optionally softcapped),
reduced to a few statistics (BASELINE.json north_star: "the logit-lens readout becomes vmap'd
unembed matmuls with in-graph top-k; candidate Pallas fusion").  The XLA path
(ops/lens.py) already avoids *persisting* the [T, V] probabilities, but still
materializes each layer's [T, V] logits in HBM between the matmul, the
softmax, and ``lax.top_k``'s full-vocab sort.

This kernel streams the unembedding matrix once through VMEM in vocab tiles
and emits only O(T * NT) partials per layer:

    for each vocab tile j (grid dim, sequential on core):
        logits = x @ E[j]^T            (MXU, f32 accumulate)
        logits = softcap(logits)       [only when logit_cap is set]
        -> tile max, tile sum-exp (relative to tile max)   [flash-style]
        -> tile top-k logits + global vocab ids            [iterative max]
        -> target-token logit if the target id falls in this tile

A tiny XLA epilogue merges the partials: global logsumexp, target probability,
global top-k over NT*k candidates.  HBM traffic per (layer, row) drops from
O(V) to O(NT * k) — the [T, 256000] tensor never exists.

CPU correctness is tested via ``interpret=True`` (tests/test_pallas_lens.py);
the real-TPU path is exercised by bench.py when TBX_PALLAS_LENS=1.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


class LensStats(NamedTuple):
    logsumexp: jax.Array     # [N] log sum exp of softcapped logits per row
    target_logit: jax.Array  # [N] softcapped logit of the target token
    topk_vals: jax.Array     # [N, K] top-k softcapped logits
    topk_ids: jax.Array      # [N, K] their global vocab ids

    def target_prob(self) -> jax.Array:
        return jnp.exp(self.target_logit - self.logsumexp)

    def topk_probs(self) -> jax.Array:
        return jnp.exp(self.topk_vals - self.logsumexp[:, None])


def _lens_tile_kernel(
    x_ref,                       # VMEM [RN, D]     — this row block's activations
    e_ref,                       # VMEM [BV, D]     — this tile of the embedding
    target_ref,                  # VMEM [RN, 1] int32 — per-row target vocab id
    max_ref,                     # out [1, 8, RN]  (8 = sublane pad; row 0 real)
    sumexp_ref,                  # out [1, 8, RN]
    tgt_ref,                     # out [1, 8, RN]
    vals_ref,                    # out [1, 8, RN, K]
    ids_ref,                     # out [1, 8, RN, K]
    *,
    block_v: int,
    top_k: int,
    logit_cap: Optional[float],
):
    j = pl.program_id(0)         # vocab tile (OUTER: embed tile stays in VMEM)
    x = x_ref[:]                                           # [N, D]
    e = e_ref[:]                                           # [BV, D]
    logits = jax.lax.dot_general(
        x, e, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                      # [N, BV] f32
    if logit_cap is not None:                              # opt-in softcap
        logits = jnp.tanh(logits / logit_cap) * logit_cap

    n, bv = logits.shape
    base = j * block_v
    col = jax.lax.broadcasted_iota(jnp.int32, (n, bv), 1)  # local col ids

    # Flash-style partials for the global softmax denominator.  Outputs carry
    # an 8-row sublane pad (Mosaic block-tiling minimum); every pad row holds
    # the same broadcast value and the epilogue reads row 0.
    tile_max = jnp.max(logits, axis=1)                     # [N]
    sumexp = jnp.sum(jnp.exp(logits - tile_max[:, None]), axis=1)
    max_ref[0] = jnp.broadcast_to(tile_max[None, :], (8, n))
    sumexp_ref[0] = jnp.broadcast_to(sumexp[None, :], (8, n))

    # Target logit — PER ROW (each row's target id lives in exactly one tile).
    # A shared scalar target is just the broadcast case; per-row targets are
    # what lets the teacher-forced NLL readout (lse - next-token logit) ride
    # this kernel instead of materializing [T, V] logits in HBM.
    local = target_ref[:, 0] - base                         # [N]
    hit = (col == local[:, None])                           # [N, BV] bool
    tgt_row = jnp.where(
        jnp.logical_and(local >= 0, local < bv),
        jnp.sum(jnp.where(hit, logits, 0.0), axis=1),
        NEG_INF,
    )
    tgt_ref[0] = jnp.broadcast_to(tgt_row[None, :], (8, n))

    # Per-tile top-k by iterative max-and-mask (k passes on the VPU — no sort).
    work = logits
    vals_rows, ids_rows = [], []
    for i in range(top_k):
        vmax = jnp.max(work, axis=1)                        # [N]
        imax = jnp.argmax(work, axis=1).astype(jnp.int32)   # [N]
        vals_rows.append(vmax)
        ids_rows.append(imax + base)
        work = jnp.where(col == imax[:, None], NEG_INF, work)
    vals = jnp.stack(vals_rows, axis=-1)                    # [N, K]
    ids = jnp.stack(ids_rows, axis=-1)                      # [N, K]
    vals_ref[0] = jnp.broadcast_to(vals[None, :, :], (8, n, top_k))
    ids_ref[0] = jnp.broadcast_to(ids[None, :, :], (8, n, top_k))


@functools.partial(
    jax.jit,
    static_argnames=("top_k", "logit_cap", "block_v", "block_n", "interpret"),
)
def lens_stats(
    x: jax.Array,            # [N, D] final-norm'd rows (any float dtype)
    embed: jax.Array,        # [V, D] tied embedding / unembedding matrix
    target_id: jax.Array,    # [] or [N] int32 — target token id(s)
    *,
    top_k: int = 5,
    logit_cap: Optional[float] = None,
    block_v: int = 1024,
    block_n: int = 256,
    interpret: bool = False,
) -> LensStats:
    """Fused lens statistics for a flat batch of rows.

    Rows are independent, so callers fold [B, T] into N = B*T.  V must divide
    by ``block_v`` (256000 = 250 x 1024).  Rows process in ``block_n`` tiles
    (VMEM budget: x-block + double-buffered embed tile + [RN, BV] logits must
    fit 16 MB); N pads to a block_n multiple internally.

    ``target_id`` may be a scalar (one secret token for the whole batch — the
    lens readout) or per-row ``[N]`` (each position's next token — the
    teacher-forced NLL readout, whose integrand is exactly
    ``logsumexp - target_logit``).

    ``logit_cap=None`` (default) matches the reference lens: bare logits, no
    final softcap (reference src/models.py:135-138 calls lm_head directly).
    """
    n_rows, d = x.shape
    v = embed.shape[0]
    if v % block_v:
        raise ValueError(f"vocab {v} not divisible by block_v {block_v}")
    nt = v // block_v

    target_id = jnp.asarray(target_id, jnp.int32)
    if target_id.ndim == 0:
        targets = jnp.full((n_rows,), target_id, jnp.int32)
    elif target_id.shape == (n_rows,):
        targets = target_id
    else:
        raise ValueError(
            f"target_id must be scalar or [N={n_rows}], got {target_id.shape}")

    block_n = min(block_n, ((n_rows + 7) // 8) * 8)
    n_pad = (-n_rows) % block_n
    if n_pad:
        x = jnp.concatenate([x, jnp.zeros((n_pad, d), x.dtype)], axis=0)
        targets = jnp.concatenate(
            [targets, jnp.full((n_pad,), -1, jnp.int32)], axis=0)
    n = n_rows + n_pad
    nr = n // block_n

    kernel = functools.partial(
        _lens_tile_kernel, block_v=block_v, top_k=top_k, logit_cap=logit_cap)

    out_shape = (
        jax.ShapeDtypeStruct((nt, 8, n), jnp.float32),          # tile max
        jax.ShapeDtypeStruct((nt, 8, n), jnp.float32),          # tile sumexp
        jax.ShapeDtypeStruct((nt, 8, n), jnp.float32),          # target logit
        jax.ShapeDtypeStruct((nt, 8, n, top_k), jnp.float32),   # cand vals
        jax.ShapeDtypeStruct((nt, 8, n, top_k), jnp.int32),     # cand ids
    )
    # Grid order matters for HBM traffic: vocab tile j OUTER so each embed
    # tile (the big operand: V x D = 1.18 GB for the 9B) loads once per layer
    # and the small x blocks (N x D, a few MB) stream in the inner loop —
    # ~3x less HBM traffic than streaming the whole embedding per row block
    # (measured 1.41 s -> ~0.8 s per 26-layer lens pass at B=48 on v5e).
    # Scoped-VMEM limit: the default 16 MB cap is conservative (v5e has
    # 128 MB); the capped (logit_cap) variant's tanh temporary pushes this
    # block layout ~0.3 MB over the default and fails to compile without it.
    compiler_params = pltpu.CompilerParams(vmem_limit_bytes=32 * 1024 * 1024)
    tile_max, tile_sumexp, tile_tgt, cand_vals, cand_ids = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(nt, nr),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda j, i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_v, d), lambda j, i: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n, 1), lambda j, i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, 8, block_n), lambda j, i: (j, 0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, block_n), lambda j, i: (j, 0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, block_n), lambda j, i: (j, 0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, block_n, top_k), lambda j, i: (j, 0, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, block_n, top_k), lambda j, i: (j, 0, i, 0), memory_space=pltpu.VMEM),
        ),
        compiler_params=compiler_params,
        interpret=interpret,
    )(x, embed, targets[:, None])

    # --- XLA epilogue over [NT, N] partials (tiny; drop the sublane pad). ---
    tile_max = tile_max[:, 0]
    tile_sumexp = tile_sumexp[:, 0]
    tile_tgt = tile_tgt[:, 0]
    cand_vals = cand_vals[:, 0]
    cand_ids = cand_ids[:, 0]
    gmax = jnp.max(tile_max, axis=0)                               # [N]
    lse = gmax + jnp.log(jnp.sum(
        tile_sumexp * jnp.exp(tile_max - gmax[None, :]), axis=0))  # [N]
    target_logit = jnp.max(tile_tgt, axis=0)                       # [N]

    flat_vals = jnp.moveaxis(cand_vals, 0, 1).reshape(n, nt * top_k)
    flat_ids = jnp.moveaxis(cand_ids, 0, 1).reshape(n, nt * top_k)
    top_vals, pos = lax.top_k(flat_vals, top_k)                    # [N, K]
    top_ids = jnp.take_along_axis(flat_ids, pos, axis=-1)

    return LensStats(
        logsumexp=lse[:n_rows],
        target_logit=target_logit[:n_rows],
        topk_vals=top_vals[:n_rows],
        topk_ids=top_ids[:n_rows],
    )


def lens_stats_reference(
    x: jax.Array, embed: jax.Array, target_id: jax.Array,
    *, top_k: int = 5, logit_cap: Optional[float] = None,
) -> LensStats:
    """Unfused XLA oracle with identical semantics (tests + fallback)."""
    logits = (x.astype(jnp.float32) @ embed.astype(jnp.float32).T)
    if logit_cap is not None:
        logits = jnp.tanh(logits / logit_cap) * logit_cap
    lse = jax.nn.logsumexp(logits, axis=-1)
    target_id = jnp.asarray(target_id, jnp.int32)
    if target_id.ndim == 0:
        tgt = logits[:, target_id]
    else:                        # per-row targets (NLL readout); -1 = no target
        tgt = jnp.where(
            target_id >= 0,
            jnp.take_along_axis(
                logits, jnp.maximum(target_id, 0)[:, None], axis=-1)[:, 0],
            NEG_INF)
    vals, ids = lax.top_k(logits, top_k)
    return LensStats(logsumexp=lse, target_logit=tgt,
                     topk_vals=vals, topk_ids=ids)
