"""In-graph logit-lens readout.

The reference materializes softmax(lm_head(norm(resid))) for all 42 layers as a
``[42, seq, 256000]`` float32 host tensor (~1.16 GB/prompt; reference
``src/models.py:97-170``) and then consumes only tiny slices of it
(reference ``src/01_reproduce_logit_lens.py:120-150``):

- the probability of ONE target token per (layer, position) — for the heatmap;
- the top-k token ids of a masked positional sum at ONE layer — the guesses;
- the argmax token per (layer, position) — decoded "lens words".

Here those reductions run inside the compiled forward via the ``per_layer_fn``
tap of ``models.gemma2.forward``: the full probability tensor never exists in
HBM (each layer's ``[B, T, V]`` lens probs live only inside one scan step, and
XLA fuses the reduction into the unembed matmul epilogue).  Per prompt the
output is a few KB instead of >1 GB.

A parity mode (``full_probs=True``) reproduces the reference's full dump for
byte-level cache compatibility (reference ``src/run_generation.py:32-82``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from taboo_brittleness_tpu.models.gemma2 import (
    Gemma2Config,
    Params,
    forward,
    rms_norm,
    softcap,
)


class LensTap(NamedTuple):
    """Per-layer lens statistics, each stacked ``[L, ...]`` by the scan.

    ``target_prob``  [L, B, T]      P(target token) at every layer/position.
    ``argmax_id``    [L, B, T]      lens argmax token id (the reference's
                                    decoded "words", src/models.py:150-153).
    ``argmax_prob``  [L, B, T]      its probability.
    ``topk_ids``     [L, B, T, K]   per-position lens top-k ids (layer-of-
                                    interest analysis + spike finding).
    ``topk_probs``   [L, B, T, K]
    """

    target_prob: jax.Array
    argmax_id: jax.Array
    argmax_prob: jax.Array
    topk_ids: jax.Array
    topk_probs: jax.Array


def _lens_logits(
    params: Params,
    cfg: Gemma2Config,
    h: jax.Array,
    *,
    logit_softcap: Optional[float] = None,
) -> jax.Array:
    """f32 lens logits: lm_head(final_norm(h)), optionally softcapped."""
    x = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    logits = x @ params["embed"].astype(cfg.compute_dtype).T
    # tbx: f32-ok — lens softmax must run in f32 (bf16 renormalization skews
    # the tiny target probs); the [B, T, V] tensor lives only inside one scan
    # step and XLA fuses the reduction into the unembed epilogue.
    logits = logits.astype(jnp.float32)
    if logit_softcap is not None:
        logits = softcap(logits, logit_softcap)
    return logits


def lens_probs(
    params: Params,
    cfg: Gemma2Config,
    h: jax.Array,
    *,
    logit_softcap: Optional[float] = None,
) -> jax.Array:
    """softmax(lm_head(final_norm(h))) in f32 — the lens readout that the
    reference applies at every layer inside the nnsight trace (src/models.py:135-138).

    NO final-logit softcap by default: the reference calls ``model.lm_head``
    directly, and HF applies Gemma-2's final softcap in
    ``Gemma2ForCausalLM.forward`` *outside* ``lm_head`` — so the reference lens
    distribution is over bare logits.  Pass ``logit_softcap`` to opt into the
    capped variant (matches the model's actual final-logit path, ``unembed``)."""
    logits = _lens_logits(params, cfg, h, logit_softcap=logit_softcap)
    return jax.nn.softmax(logits, axis=-1)


def lens_probs_foldexp(
    params: Params,
    cfg: Gemma2Config,
    h: jax.Array,
    *,
    logit_softcap: Optional[float] = None,
) -> jax.Array:
    """:func:`lens_probs` normalized as ``exp(logit - logsumexp)`` instead of
    ``jax.nn.softmax``.

    Same math (softmax IS exp(l - lse)), different op schedule: softmax lowers
    to max-subtract / exp / sum / **divide**, where the divide is one more
    full [*, V] elementwise pass over the probability slab; the lse form lets
    XLA fold the subtract+exp into whatever consumes the probabilities (the
    readout's masked positional sum), skipping that pass.  Per-element results
    differ only in final rounding (one fused ``exp(l-lse)`` vs ``exp(l-max)/
    sum``), which is why the hot readout path adopts it behind a variant
    switch (``interventions._residual_measure``) while the reference-parity
    lens taps keep byte-stable ``softmax``."""
    logits = _lens_logits(params, cfg, h, logit_softcap=logit_softcap)
    lse = jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
    return jnp.exp(logits - lse)


def lens_argmax(
    params: Params,
    cfg: Gemma2Config,
    h: jax.Array,
) -> jax.Array:
    """Greedy lens readout: argmax of the layer-h lens logits, int32.

    The draft head of the self-speculative decoder (``runtime.speculate``):
    an early layer's unembedded residual IS a free draft model living inside
    the target network, and drafting only needs its argmax.  Softcapping is
    skipped deliberately — ``tanh(x/c)*c`` is strictly monotone, so the
    argmax is identical with or without the cap and the elementwise pass
    over the [*, V] logits is saved.  The [*, V] f32 logits stay transient
    inside the enclosing program (XLA fuses the argmax into the unembed
    epilogue, the same argument as the lens taps above)."""
    return jnp.argmax(_lens_logits(params, cfg, h), axis=-1).astype(jnp.int32)


def make_lens_tap(
    params: Params,
    cfg: Gemma2Config,
    target_ids: jax.Array,   # [B] one target token id per batch row
    *,
    top_k: int = 5,
    logit_softcap: Optional[float] = None,
):
    """Build a ``per_layer_fn`` computing :class:`LensTap` stats for one layer.

    The [B, T, V] probability tensor exists only transiently within a single
    scan iteration; everything returned is O(B·T·k).
    """

    def tap(h: jax.Array, layer_idx: jax.Array) -> LensTap:
        del layer_idx
        probs = lens_probs(params, cfg, h,
                           logit_softcap=logit_softcap)  # [B, T, V] f32
        tgt = jnp.take_along_axis(
            probs, target_ids[:, None, None], axis=-1
        )[..., 0]                                        # [B, T]
        topk_probs, topk_ids = lax.top_k(probs, top_k)   # [B, T, K]
        return LensTap(
            target_prob=tgt,
            argmax_id=topk_ids[..., 0],
            argmax_prob=topk_probs[..., 0],
            topk_ids=topk_ids,
            topk_probs=topk_probs,
        )

    return tap


def make_pallas_lens_tap(
    params: Params,
    cfg: Gemma2Config,
    target_id: jax.Array,   # [] scalar — one target for the whole batch
    *,
    top_k: int = 5,
    block_v: int = 1024,
    interpret: Optional[bool] = None,
    logit_softcap: Optional[float] = None,
):
    """Fused-kernel variant of :func:`make_lens_tap` (ops/pallas_lens.py).

    Streams the unembedding once through VMEM per layer and never builds the
    [B, T, V] probability tensor even transiently — ~1.5x faster than the XLA
    tap on v5e at Gemma-2 vocab scale.  Requires a single target id shared by
    all rows (true per word in every pipeline; the XLA tap handles the
    general per-row case).
    """
    from taboo_brittleness_tpu.ops import pallas_lens

    if interpret is None:
        interpret = jax.default_backend() == "cpu"  # Mosaic needs real TPU
    block_v = min(block_v, cfg.vocab_size)  # small test vocabs: one tile

    def tap(h: jax.Array, layer_idx: jax.Array) -> LensTap:
        del layer_idx
        B, T, D = h.shape
        x = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
        stats = pallas_lens.lens_stats(
            x.reshape(B * T, D),
            params["embed"].astype(cfg.compute_dtype),
            target_id,
            top_k=top_k,
            logit_cap=logit_softcap,
            block_v=block_v,
            interpret=interpret,
        )
        tgt = stats.target_prob().reshape(B, T)
        topk_probs = stats.topk_probs().reshape(B, T, top_k)
        topk_ids = stats.topk_ids.reshape(B, T, top_k)
        return LensTap(
            target_prob=tgt,
            argmax_id=topk_ids[..., 0],
            argmax_prob=topk_probs[..., 0],
            topk_ids=topk_ids,
            topk_probs=topk_probs,
        )

    return tap


def make_tp_lens_tap(
    params: Params,
    cfg: Gemma2Config,
    target_ids: jax.Array,   # [B]
    *,
    top_k: int,
    mesh,                    # jax.sharding.Mesh with a "tp" axis
    logit_softcap: Optional[float] = None,
):
    """Vocab-sharded (tensor-parallel) lens tap.

    With ``embed`` sharded ``P('tp', None)`` (parallel/mesh.py param policy),
    the naive tap's ``lax.top_k`` over [B, T, V] would make XLA all-gather
    256k logits per layer.  Here each tp shard computes its local
    [B/dp, T, V/tp] logits, the softmax normalizer and target probability
    merge via psum/pmax, and the top-k merges shard-locally via ``tp_topk`` —
    O(k·tp) ICI bytes per (layer, position) instead of O(V).  No replicated
    [B, T, V] tensor ever exists (asserted over the compiled HLO in
    tests/test_parallel.py).
    """
    from taboo_brittleness_tpu.parallel import mesh as meshlib
    from jax.sharding import PartitionSpec as P

    tp = mesh.shape["tp"]
    if cfg.vocab_size % tp:
        raise ValueError(f"vocab {cfg.vocab_size} not divisible by tp={tp}")
    shard_size = cfg.vocab_size // tp

    def tap(h: jax.Array, layer_idx: jax.Array) -> LensTap:
        del layer_idx
        x = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)

        def local_stats(x_l, e_l, tgt_l):
            # x_l [b, T, D]; e_l [V/tp, D]; tgt_l [b] global ids.
            # tbx: f32-ok — shard-local [b, T, V/tp] softmax numerics in f32.
            logits = (x_l @ e_l.T).astype(jnp.float32)        # [b, T, V/tp]
            if logit_softcap is not None:
                logits = softcap(logits, logit_softcap)
            gmax = lax.pmax(jnp.max(logits, axis=-1), "tp")   # [b, T]
            denom = lax.psum(
                jnp.sum(jnp.exp(logits - gmax[..., None]), axis=-1), "tp")
            probs = jnp.exp(logits - gmax[..., None]) / denom[..., None]

            base = lax.axis_index("tp") * shard_size
            local_t = tgt_l - base                             # [b]
            ok = (local_t >= 0) & (local_t < shard_size)
            idx = jnp.clip(local_t, 0, shard_size - 1)[:, None, None]
            tgt_p = jnp.take_along_axis(
                probs, jnp.broadcast_to(idx, (*probs.shape[:2], 1)), axis=-1
            )[..., 0]                                          # [b, T]
            tgt_p = lax.psum(jnp.where(ok[:, None], tgt_p, 0.0), "tp")

            tv, ti = meshlib.tp_topk(probs, top_k, axis_name="tp",
                                     shard_size=shard_size)
            return LensTap(target_prob=tgt_p, argmax_id=ti[..., 0],
                           argmax_prob=tv[..., 0], topk_ids=ti, topk_probs=tv)

        return meshlib.shard_map(
            local_stats, mesh,
            in_specs=(P("dp", None, None), P("tp", None), P("dp")),
            out_specs=LensTap(
                target_prob=P("dp", None), argmax_id=P("dp", None),
                argmax_prob=P("dp", None), topk_ids=P("dp", None, None),
                topk_probs=P("dp", None, None)),
        )(x, params["embed"].astype(cfg.compute_dtype), target_ids)

    return tap


def make_full_probs_tap(params: Params, cfg: Gemma2Config,
                        logit_softcap: Optional[float] = None):
    """Parity-mode tap: return the full [B, T, V] lens probs per layer (the
    reference's all_probs dump, reference src/run_generation.py:46-48).
    Uncapped by default, matching the reference lens semantics."""

    def tap(h: jax.Array, layer_idx: jax.Array) -> jax.Array:
        del layer_idx
        return lens_probs(params, cfg, h, logit_softcap=logit_softcap)

    return tap


def residual_carry_tap(batch: int, seq: int, hidden: int, tap_layer: int):
    """(init, update) carry tap capturing resid_post at ``tap_layer`` in f32 —
    O(1) in layers: one [B, T, D] accumulator carried per scan step, so the
    stacked [L, B, T, D] tensor never materializes.  Shared by the dense
    lens paths and the sequence-parallel forward (parallel/sp.py).

    The per-layer update is a SELECT, not a masked multiply-add: the old
    ``acc + h * keep`` form left XLA free to contract the multiply into an
    FMA — or not — depending on the surrounding fusion context, so the
    captured residual's last bits differed between a standalone decode
    launch and the same decode inlined into the fused study program
    (runtime/fused.py).  A select carries the exact ``h`` bits through,
    making the capture bit-stable across compilation contexts (the fused
    parity gate in tests/test_fused.py depends on it)."""
    acc0 = jnp.zeros((batch, seq, hidden), jnp.float32)

    def accumulate(acc, h, layer_idx):
        return jnp.where(layer_idx == tap_layer, h.astype(jnp.float32), acc)

    return acc0, accumulate


def residual_multi_tap(batch: int, seq: int, hidden: int,
                       tap_layers: Tuple[int, ...]):
    """Multi-layer :func:`residual_carry_tap`: one [B, T, D] f32 accumulator
    PER tap layer, carried as a tuple pytree — still O(1) in model depth
    (K buffers for K taps, never the stacked [L, B, T, D] tensor).  The
    Gemma-Scope grid sweep (grid/) decodes each word ONCE while tapping
    every grid layer; at K grid layers the capture is K x [B, T, D] f32
    (~0.5 MB/prompt at 9B shapes), nothing like the 1.16 GB all-probs
    hazard this module exists to avoid.

    Each slot's update is the EXACT select expression of the single-tap
    version — not a gather or a masked FMA — so slot k of a multi-tap
    capture is bit-identical to a single-tap capture at ``tap_layers[k]``
    across compilation contexts (the PR-8 hazard class; parity gated in
    tests/test_grid.py)."""
    taps = tuple(int(t) for t in tap_layers)
    if len(set(taps)) != len(taps):
        raise ValueError(f"duplicate tap layers {taps}; each grid layer "
                         "captures exactly one slot")
    acc0 = tuple(jnp.zeros((batch, seq, hidden), jnp.float32) for _ in taps)

    def accumulate(acc, h, layer_idx):
        hf = h.astype(jnp.float32)
        return tuple(jnp.where(layer_idx == t, hf, a)
                     for a, t in zip(acc, taps))

    return acc0, accumulate


def _pallas_auto_ok(params: Params) -> bool:
    """Whether ``use_pallas=None`` may resolve to the fused kernel: TPU
    backend, concrete (non-traced) params, placed on a single device.  The
    kernel is Mosaic-TPU-only and has no GSPMD partitioning rule, so sharded
    or traced params take the XLA tap (which partitions via tp_topk)."""
    if jax.default_backend() != "tpu":
        return False
    embed = params["embed"]
    if isinstance(embed, jax.core.Tracer):
        return False
    sharding = getattr(embed, "sharding", None)
    if sharding is not None and len(sharding.device_set) > 1:
        return False
    return True


class LensForwardResult(NamedTuple):
    tap: LensTap                       # stacked [L, B, T, ...]
    residual: Optional[jax.Array]      # [B, T, D] resid_post at tap_layer (f32)
    logits: Optional[jax.Array]        # final [B, T, V] (softcapped)


def lens_forward(
    params: Params,
    cfg: Gemma2Config,
    input_ids: jax.Array,            # [B, T]
    target_ids: jax.Array,           # [B]
    *,
    tap_layer: int,
    top_k: int = 5,
    positions: Optional[jax.Array] = None,
    attn_validity: Optional[jax.Array] = None,
    compute_logits: bool = False,
    edit_fn: Optional[Any] = None,
    use_pallas: Optional[bool] = None,
    logit_softcap: Optional[float] = None,
    tp_mesh: Optional[Any] = None,
) -> LensForwardResult:
    """One compiled pass: lens stats for every layer + the residual at
    ``tap_layer`` (for the SAE path — the reference's ``residual_stream_l31``
    save, src/models.py:131-132).

    ``use_pallas=None`` auto-resolves: the fused Pallas kernel when the
    backend is TPU AND the params are concrete and single-device (the kernel
    has no GSPMD partitioning rule — under tp the vocab-sharded unembed must
    take the XLA tap + tp_topk path instead); the XLA tap everywhere else,
    including under an enclosing jit trace where placement can't be verified.
    Pass True/False to force.  The Pallas path requires one target id shared
    by the whole batch (true per word in every pipeline) — checked here when
    the ids are concrete; callers forcing use_pallas=True under jit own the
    invariant.

    The residual capture rides the scan *carry* (``carry_tap``): one
    [B, T, D] accumulator is masked-added per layer, so only a single
    residual buffer ever exists — the stacked [L, B, T, D] tensor (~780 MB
    for the 9B at B=10) never materializes.
    """
    if tp_mesh is not None and tp_mesh.shape.get("tp", 1) > 1:
        # Vocab-sharded unembed: shard-local readout + tp_topk merge.
        stats_tap = make_tp_lens_tap(
            params, cfg, target_ids, top_k=top_k, mesh=tp_mesh,
            logit_softcap=logit_softcap)
        return _lens_forward_with_tap(
            params, cfg, input_ids, stats_tap, tap_layer=tap_layer,
            positions=positions, attn_validity=attn_validity,
            compute_logits=compute_logits, edit_fn=edit_fn)

    if tp_mesh is not None and tp_mesh.shape.get("sp", 1) > 1:
        # Sequence-parallel (ring attention) lens path for long sequences;
        # the per-position readout is shard-local (parallel/sp.py).  The
        # vocab-sharded branch above wins when both axes are >1: at the
        # reference's T≲130 the 256k-vocab readout dominates the cost.
        from taboo_brittleness_tpu.parallel.sp import lens_forward_sp

        if compute_logits:
            raise ValueError(
                "the sp lens path computes per-layer stats only (logits=None);"
                " pass compute_logits=False or use the dense/tp path")
        if use_pallas:
            raise ValueError(
                "the Pallas lens kernel has no sp partitioning; leave "
                "use_pallas unset (None) with an sp>1 mesh")
        return lens_forward_sp(
            params, cfg, input_ids, target_ids, tp_mesh,
            tap_layer=tap_layer, top_k=top_k, positions=positions,
            attn_validity=attn_validity, edit_fn=edit_fn,
            logit_softcap=logit_softcap)

    if use_pallas is None:
        use_pallas = _pallas_auto_ok(params)

    if use_pallas:
        if not isinstance(target_ids, jax.core.Tracer):
            import numpy as _np

            uniq = _np.unique(_np.asarray(target_ids))
            if uniq.size > 1:
                raise ValueError(
                    "pallas lens path needs ONE target id shared by the batch "
                    f"(got {uniq.size} distinct); pass use_pallas=False")
        # All pipeline callers pass one target per word; the kernel exploits it.
        stats_tap = make_pallas_lens_tap(
            params, cfg, target_ids[0], top_k=top_k,
            logit_softcap=logit_softcap)
    else:
        stats_tap = make_lens_tap(params, cfg, target_ids, top_k=top_k,
                                  logit_softcap=logit_softcap)
    return _lens_forward_with_tap(
        params, cfg, input_ids, stats_tap, tap_layer=tap_layer,
        positions=positions, attn_validity=attn_validity,
        compute_logits=compute_logits, edit_fn=edit_fn)


def _lens_forward_with_tap(
    params: Params,
    cfg: Gemma2Config,
    input_ids: jax.Array,
    stats_tap,
    *,
    tap_layer: int,
    positions: Optional[jax.Array],
    attn_validity: Optional[jax.Array],
    compute_logits: bool,
    edit_fn: Optional[Any],
) -> LensForwardResult:
    B, T = input_ids.shape
    res = forward(
        params, cfg, input_ids,
        positions=positions,
        attn_validity=attn_validity,
        per_layer_fn=stats_tap,
        carry_tap=residual_carry_tap(B, T, cfg.hidden_size, tap_layer),
        edit_fn=edit_fn,
        compute_logits=compute_logits,
    )
    return LensForwardResult(tap=res.taps, residual=res.carry_tap, logits=res.logits)


def full_probs_forward(
    params: Params,
    cfg: Gemma2Config,
    input_ids: jax.Array,
    *,
    tap_layer: Optional[int] = None,
    positions: Optional[jax.Array] = None,
    attn_validity: Optional[jax.Array] = None,
) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Parity mode: (all_probs [L, B, T, V] f32, residual [B, T, D] f32 or None).

    Matches the reference cache schema exactly (npz keys ``all_probs`` +
    ``residual_stream_l<idx>``, reference src/run_generation.py:56).  Only for
    small T / debug — this is the GB-scale tensor the TPU design removes.
    """
    probs_tap = make_full_probs_tap(params, cfg)

    if tap_layer is None:
        res = forward(params, cfg, input_ids, positions=positions,
                      attn_validity=attn_validity, per_layer_fn=probs_tap,
                      compute_logits=False)
        return res.taps, None

    B, T = input_ids.shape
    res = forward(params, cfg, input_ids, positions=positions,
                  attn_validity=attn_validity, per_layer_fn=probs_tap,
                  carry_tap=residual_carry_tap(B, T, cfg.hidden_size, tap_layer),
                  compute_logits=False)
    return res.taps, res.carry_tap


# ---------------------------------------------------------------------------
# Response aggregation (the analysis step of reference
# src/01_reproduce_logit_lens.py:35-71, as a jittable op).
# ---------------------------------------------------------------------------

def aggregate_masked_sum(
    probs: jax.Array,        # [T, V] lens probs at the layer of interest
    token_ids: jax.Array,    # [T] input token id at each position
    response_mask: jax.Array,  # [T] bool: True inside the model's response
    *,
    top_k: int,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k of the position-summed probs with current+previous-token zeroing.

    Mirrors ``aggregate_response_logits`` (reference
    ``src/01_reproduce_logit_lens.py:59-67``): at each response position the
    probability of the token *at* that position and of the token at the
    *previous* position are zeroed (the lens trivially predicts copies), then
    probabilities are summed over response positions and the top-k vocab ids
    win.  Returns (ids [K], summed probs [K]).
    """
    T, V = probs.shape
    pos = jnp.arange(T)
    # One-hot zeroing masks, built without scatter: [T, V] where True = zero out.
    curr = jax.nn.one_hot(token_ids, V, dtype=bool)
    prev = jax.nn.one_hot(jnp.where(pos > 0, token_ids[jnp.maximum(pos - 1, 0)], -1),
                          V, dtype=bool)
    keep = ~(curr | prev)
    masked = jnp.where(keep, probs, 0.0)
    masked = jnp.where(response_mask[:, None], masked, 0.0)
    summed = jnp.sum(masked, axis=0)                       # [V]
    top_probs, top_ids = lax.top_k(summed, top_k)
    return top_ids, top_probs


@partial(jax.jit, static_argnames=("cfg", "top_k"))
def aggregate_from_residual(
    params: Params,
    cfg: Gemma2Config,
    residual: jax.Array,      # [B, T, D] tapped residuals at the layer of interest
    token_ids: jax.Array,     # [B, T]
    response_mask: jax.Array,  # [B, T] bool
    *,
    top_k: int,
) -> Tuple[jax.Array, jax.Array]:
    """Lens probs at one layer + masked-sum aggregation + top-k, vmapped over
    the batch inside ONE jitted program, so the [T, V] probability tensor of a
    row lives only inside the fused computation — never a persistent [B, T, V]
    HBM buffer between dispatches.  Returns (ids [B, K], sums [B, K])."""

    def one(h, ids, m):
        probs = lens_probs(params, cfg, h[None])[0]
        return aggregate_masked_sum(probs, ids, m, top_k=top_k)

    return jax.vmap(one)(residual, token_ids, response_mask)


def aggregate_from_residual_tp(
    params: Params,
    cfg: Gemma2Config,
    residual: jax.Array,      # [B, T, D]
    token_ids: jax.Array,     # [B, T]
    response_mask: jax.Array,  # [B, T] bool
    *,
    top_k: int,
    mesh,
    logit_softcap: Optional[float] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Vocab-sharded variant of :func:`aggregate_from_residual`: the masked
    positional sum reduces to [B, V/tp] per shard and only O(k·tp) candidates
    cross ICI via ``tp_topk`` — the [T, V] probability tensor of a row exists
    only shard-locally."""
    from taboo_brittleness_tpu.parallel import mesh as meshlib
    from jax.sharding import PartitionSpec as P

    tp = mesh.shape["tp"]
    if cfg.vocab_size % tp:
        raise ValueError(f"vocab {cfg.vocab_size} not divisible by tp={tp}")
    shard_size = cfg.vocab_size // tp
    eps = cfg.rms_norm_eps

    def local(h_l, ids_l, mask_l, e_l):
        # h_l [b, T, D] f32 residuals; ids_l/mask_l [b, T]; e_l [V/tp, D].
        x = rms_norm(h_l, params["final_norm"], eps)
        # tbx: f32-ok — shard-local [b, T, V/tp] softmax numerics in f32.
        logits = (x @ e_l.T).astype(jnp.float32)               # [b, T, Vl]
        if logit_softcap is not None:
            logits = softcap(logits, logit_softcap)
        gmax = lax.pmax(jnp.max(logits, axis=-1), "tp")
        denom = lax.psum(
            jnp.sum(jnp.exp(logits - gmax[..., None]), axis=-1), "tp")
        probs = jnp.exp(logits - gmax[..., None]) / denom[..., None]

        # Zero current+previous token ids (global ids -> local columns; ids
        # outside this shard one-hot to nothing).
        base = lax.axis_index("tp") * shard_size
        Vl = shard_size
        curr = jax.nn.one_hot(ids_l - base, Vl, dtype=bool)    # [b, T, Vl]
        prev_ids = jnp.roll(ids_l, 1, axis=1).at[:, 0].set(-1)
        prev = jax.nn.one_hot(prev_ids - base, Vl, dtype=bool)
        keep = ~(curr | prev) & mask_l[..., None]
        summed = jnp.sum(jnp.where(keep, probs, 0.0), axis=1)  # [b, Vl]
        return meshlib.tp_topk(summed, top_k, axis_name="tp",
                               shard_size=shard_size)

    vals, ids = meshlib.shard_map(
        local, mesh,
        in_specs=(P("dp", None, None), P("dp", None), P("dp", None),
                  P("tp", None)),
        out_specs=(P("dp", None), P("dp", None)),
    )(residual, token_ids, response_mask,
      params["embed"].astype(cfg.compute_dtype))
    return ids, vals


def spike_positions(
    target_prob_at_layer: jax.Array,  # [T] P(secret) at the layer of interest
    response_mask: jax.Array,          # [T] bool
    *,
    top_k: int,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k response positions by secret-token lens probability ("spike"
    tokens, Execution Plan 'spike positions' — the sites where interventions
    are applied).  Returns (positions [K], probs [K]).

    When the response has fewer than ``top_k`` tokens, the surplus slots
    repeat the best valid position (prob reported as 0) instead of silently
    pointing at pad/prompt columns — repeated spikes only overweight a real
    response token in downstream scoring/PCA, never a pad residual.
    """
    masked = jnp.where(response_mask, target_prob_at_layer, -1.0)
    probs, pos = lax.top_k(masked, top_k)
    invalid = probs < 0.0
    pos = jnp.where(invalid, pos[0], pos)
    probs = jnp.where(invalid, 0.0, probs)
    return pos, probs


@partial(jax.jit, static_argnames="top_k")
def spike_positions_batch(
    target_prob: jax.Array,    # [B, T]
    response_mask: jax.Array,  # [B, T] bool
    *,
    top_k: int,
) -> Tuple[jax.Array, jax.Array]:
    """Batched :func:`spike_positions` as ONE compiled program.  (An eager
    ``jax.vmap`` call runs op-by-op — each op a separate dispatch on a
    remote runtime, which is why the study's baseline pass jits it.)"""
    return jax.vmap(
        lambda t, m: spike_positions(t, m, top_k=top_k)
    )(target_prob, response_mask)
