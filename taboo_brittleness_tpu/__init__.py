"""taboo-brittleness-tpu: a TPU-native (JAX/XLA/pjit/Pallas) framework for measuring
whether the "secret word" knowledge in Taboo Gemma-2-9B-IT finetunes is localized/brittle
or distributed/robust.

This is a ground-up TPU-first re-design of the capabilities of the reference
`lmmontoya-ai/taboo-brittleness` pipeline (see SURVEY.md at the repo root):

- a pure-functional Gemma-2 forward built on ``lax.scan`` whose layer "taps" are
  *returned values* compiled into the XLA graph (replacing the reference's nnsight
  hook architecture, reference ``src/models.py:97-170``),
- an in-graph logit-lens readout (vmap'd unembed matmuls + masked aggregation +
  top-k) that avoids materializing the reference's ~1.16 GB per-prompt
  ``[42, seq, 256000]`` probability tensor,
- a Gemma-Scope JumpReLU SAE as a pure function for encode -> ablate -> decode
  spliced into the forward (reference ``src/02_run_sae_baseline.py``),
- targeted-vs-random SAE-latent ablation sweeps and low-rank projection removal
  as vmapped pure functions,
- token-forcing pregame/postgame attacks as batched prefilled decode,
- a parallel layer (mesh / sharding / ring attention / vocab-TP unembed) that
  scales the embarrassingly-parallel sweep grid over a TPU mesh.
"""

__version__ = "0.1.0"

from taboo_brittleness_tpu.config import Config, load_config  # noqa: F401
