"""Command-line entry points.

One CLI with subcommands replaces the reference's three ad-hoc scripts
(``python src/run_generation.py cfg.yaml`` etc., each with its own argv
handling — and ``01_reproduce_logit_lens.py`` ignoring its argv entirely, a
reference bug noted in SURVEY.md anti-goals):

    python -m taboo_brittleness_tpu generate      [-c CFG] [--words ...] [--parity-dump]
    python -m taboo_brittleness_tpu logit-lens    [-c CFG] [--words ...]
    python -m taboo_brittleness_tpu sae-baseline  [-c CFG] [--sae-npz PATH]
    python -m taboo_brittleness_tpu interventions [-c CFG] --word W [--sae-npz PATH]
    python -m taboo_brittleness_tpu token-forcing [-c CFG] [--modes pregame postgame]
    python -m taboo_brittleness_tpu prompting     [-c CFG] [--modes naive adversarial]
    python -m taboo_brittleness_tpu supervise --output-dir DIR -- <subcommand ...>
    python -m taboo_brittleness_tpu serve   --output-dir DIR [--synthetic] [--slots N]
    python -m taboo_brittleness_tpu loadgen [--spool DIR | --socket URL | --synthetic] [-n N]
    python -m taboo_brittleness_tpu gateway --output-dir DIR [--port P] [--selfcheck]

Every subcommand accepts the reference's ``configs/default.yaml`` schema
unchanged (config.load_config).

Exit codes (the restart-vs-fail contract outer orchestration keys off):

- 0 — the run completed.
- 1 — the sweep completed but words were QUARANTINED (in-process retries
  exhausted; rerunning replays the failure — inspect ``_failures.json``).
  For ``serve`` there is no quarantine-completed state: exit 1 from a
  serving child is a CRASH, and ``supervise`` burns an incarnation on it
  instead of passing it through (it reads the child's declared ``workload``
  from ``_progress.json``).
- 75 — ``EX_TEMPFAIL``: the run DRAINED on a preemption notice
  (SIGTERM/SIGINT).  Sweeps drain at a word boundary; ``serve`` drains at a
  SESSION boundary — the current decode step finishes, new admissions are
  rejected, every in-flight session runs to completion and gets its
  response, then the process exits.  Partial results on disk are valid and
  a relaunch resumes them (``runtime.supervise`` restarts on exactly this
  code; a relaunched server re-queues claimed-but-unanswered requests).
  ``gateway`` drains at a STREAM boundary — the listening socket closes
  (new connections are refused, late requests get 503 ``draining``), every
  open SSE stream runs to its ``done`` event, then exit 75.  Because every
  accepted request is already durable in the spool, even a SIGKILL'd
  gateway loses only sockets: a relaunched gateway (or any sibling over
  the same spool) serves the backlog, and clients re-attach by request id.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

from taboo_brittleness_tpu import config as config_mod
from taboo_brittleness_tpu.config import Config


def _common(p: argparse.ArgumentParser) -> None:
    p.add_argument("-c", "--config", default="configs/default.yaml",
                   help="YAML config (reference schema accepted)")
    p.add_argument("--words", nargs="*", default=None,
                   help="subset of taboo words (default: all in config)")
    p.add_argument("--processed-dir", default=None,
                   help="override cache dir (default from config)")
    p.add_argument("--checkpoint-root", default=None,
                   help="directory of local HF snapshots (or set TABOO_CHECKPOINT_ROOT)")
    p.add_argument("--trace-dir", default=None,
                   help="capture a jax.profiler trace into this directory")
    p.add_argument("--profile", action="store_true",
                   help="device-timeline profiling (sets TBX_PROFILE=1): "
                        "capture the first TBX_PROFILE_WORDS (default 2) "
                        "computed words under the XLA profiler and write "
                        "<output>/_device_profile.json — render with "
                        "tools/trace_report.py --device")
    p.add_argument("--no-manifest", action="store_true",
                   help="skip writing run_manifest.json")
    p.add_argument("--max-retries", type=int, default=2,
                   help="retries per word on transient failures before the "
                        "word is quarantined (exponential backoff, seeded "
                        "jitter; see runtime/resilience.py)")
    p.add_argument("--fail-fast", action="store_true",
                   help="abort the sweep on the first failed word instead "
                        "of quarantining it and continuing")


def _manifest(args, command: str):
    from taboo_brittleness_tpu.runtime.manifest import RunManifest

    return RunManifest(command=command)


def _finish(args, manifest, out_dir: str) -> None:
    if not args.no_manifest:
        path = manifest.save(os.path.join(out_dir, "run_manifest.json"))
        print(f"manifest -> {path}")  # tbx: TBX009-ok — CLI stdout contract (manifest path)


def _load(args) -> Config:
    if os.path.exists(args.config):
        return config_mod.load_config(args.config)
    # tbx: TBX009-ok — CLI stdout contract (config fallback notice)
    print(f"[config] {args.config} not found; using built-in defaults")
    return Config()


def _report_failures(manifest, ledger_or_failures) -> int:
    """Fold a sweep's failure ledger into the manifest and derive the exit
    code: non-zero iff words were quarantined (partial results on disk are
    still valid — the non-zero exit is the 'rerun me' signal, and a rerun
    resumes the finished words for free)."""
    if ledger_or_failures is None:
        return 0
    data = (ledger_or_failures.to_dict()
            if hasattr(ledger_or_failures, "to_dict")
            else dict(ledger_or_failures))
    manifest.record_resilience(data)
    quarantined = data.get("quarantined", {})
    if not quarantined:
        return 0
    # tbx: TBX009-ok — CLI stderr contract (quarantine summary)
    print(f"[resilience] {len(quarantined)} word(s) quarantined: "
          f"{sorted(quarantined)} (see _failures.json next to the results)",
          file=sys.stderr)
    return 1


def _exit_code(rc: int) -> int:
    """Map a pipeline exit through the drain contract: a run that stopped
    at a preemption drain exits 75 (``EX_TEMPFAIL`` — resumable) REGARDLESS
    of quarantine state, because the sweep did not finish and the missing
    words are recoverable by relaunch, not lost."""
    from taboo_brittleness_tpu.runtime import supervise

    if supervise.drain_requested():
        # tbx: TBX009-ok — CLI stderr contract (drain notice)
        print("[supervise] run drained on a preemption notice; partial "
              "results are valid — relaunch (or `supervise`) resumes them",
              file=sys.stderr)
        return supervise.EXIT_DRAINED
    return rc


def _mesh(config: Config):
    """Build the (dp, tp, sp) device mesh from config when the host has more
    than one device; None on a single chip (plain single-device execution).

    Host-locality-aware across processes (``parallel/multihost.py``); the
    multi-process runtime itself is joined at the top of ``main`` — it must
    run before ANY jax API touches a backend, and some subcommands build
    their run manifest (which queries jax.devices) before their mesh."""
    import jax

    if len(jax.devices()) <= 1:
        return None
    from taboo_brittleness_tpu.parallel import multihost

    return multihost.make_host_mesh(config.mesh)


def _loader(config: Config, args, mesh=None):
    from taboo_brittleness_tpu.runtime.checkpoints import CheckpointManager

    return CheckpointManager(config.model, checkpoint_root=args.checkpoint_root,
                             mesh=mesh,
                             delta_root=getattr(args, "delta_root", None))


def _sae(config: Config, path: Optional[str]):
    """Load the Gemma-Scope SAE: explicit npz path, else auto-convert from a
    local snapshot of the release (tools/convert_gemma_scope.py)."""
    from taboo_brittleness_tpu.ops import sae as sae_ops

    if path:
        return sae_ops.load(path)

    root = os.environ.get("TABOO_GEMMA_SCOPE_ROOT")
    if root and os.path.isdir(root):
        import sys as _sys

        tools_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        if tools_dir not in _sys.path:
            _sys.path.insert(0, tools_dir)
        import convert_gemma_scope

        # Converted output lives under the (writable) working tree, not the
        # snapshot root — release mounts are commonly read-only.
        out = os.path.join("results", "sae_cache",
                           config.sae.sae_id.replace("/", "__") + ".npz")
        try:
            if not os.path.exists(out):
                convert_gemma_scope.convert(root, out, config.sae.sae_id)
                # tbx: TBX009-ok — CLI stdout contract (SAE convert notice)
                print(f"[sae] converted {config.sae.release}/"
                      f"{config.sae.sae_id} -> {out}")
            return sae_ops.load(out)
        except (OSError, FileNotFoundError, KeyError, ValueError) as e:
            raise SystemExit(
                f"SAE auto-convert from {root} failed ({e}); run "
                "tools/convert_gemma_scope.py manually and pass --sae-npz")

    raise SystemExit(
        "no SAE available: pass --sae-npz, or set TABOO_GEMMA_SCOPE_ROOT to a "
        f"local snapshot of {config.sae.release} (auto-converted via "
        "tools/convert_gemma_scope.py)")


def cmd_generate(args) -> int:
    from taboo_brittleness_tpu.pipelines import generation
    from taboo_brittleness_tpu.runtime.manifest import maybe_profile

    from taboo_brittleness_tpu.runtime.resilience import FailureLedger

    config = _load(args)
    manifest = _manifest(args, "generate")
    processed = args.processed_dir or config.output.processed_dir
    ledger = FailureLedger(processed)
    with maybe_profile(args.trace_dir), manifest.stage("generate"):
        done = generation.run_generation(
            config, model_loader=_loader(config, args, mesh=_mesh(config)),
            words=args.words,
            processed_dir=processed, parity_dump=args.parity_dump,
            max_retries=args.max_retries, fail_fast=args.fail_fast,
            ledger=ledger)
    manifest.extra["generated"] = {w: len(v) for w, v in done.items()}
    print(json.dumps({w: len(v) for w, v in done.items()}))  # tbx: TBX009-ok — CLI stdout contract (results JSON)
    rc = _report_failures(manifest, ledger)
    _finish(args, manifest, processed)
    return _exit_code(rc)


def cmd_logit_lens(args) -> int:
    from taboo_brittleness_tpu.pipelines import logit_lens
    from taboo_brittleness_tpu.runtime.checkpoints import resolve_snapshot_dir
    from taboo_brittleness_tpu.runtime.tokenizer import HFTokenizer

    config = _load(args)
    mesh = _mesh(config)
    loader = _loader(config, args, mesh=mesh)
    words = args.words or config.words
    # Tokenizer-only load (all taboo checkpoints share the Gemma-2 tokenizer):
    # a fully cached run must never stream 9B of weights just to decode ids —
    # the reference does exactly that (src/01_reproduce_logit_lens.py:193).
    snap = resolve_snapshot_dir(loader.repo_id(words[0]), args.checkpoint_root)
    tok = HFTokenizer.from_pretrained(snap)
    out = os.path.join(
        config.output.base_dir, f"seed_{config.experiment.seed}",
        config.output.experiment_name, "logit_lens_evaluation_results.json")
    manifest = _manifest(args, "logit-lens")
    from taboo_brittleness_tpu.runtime.manifest import maybe_profile

    with maybe_profile(args.trace_dir), manifest.stage("evaluate"):
        results = logit_lens.run_evaluation(
            config, tok, words=words, model_loader=loader,
            processed_dir=args.processed_dir, output_path=out, mesh=mesh)
    manifest.add_artifact(out)
    manifest.extra["overall"] = results["overall"]
    print(json.dumps(results["overall"], indent=2))  # tbx: TBX009-ok — CLI stdout contract (results JSON)
    print(f"results -> {out}")  # tbx: TBX009-ok — CLI stdout contract (results path)
    _finish(args, manifest, os.path.dirname(out))
    return 0


def cmd_sae_baseline(args) -> int:
    from taboo_brittleness_tpu.pipelines import sae_baseline

    config = _load(args)
    sae = _sae(config, args.sae_npz)
    manifest = _manifest(args, "sae-baseline")
    with manifest.stage("analyze"):
        results = sae_baseline.analyze_sae_baseline(
            config, sae, words=args.words, processed_dir=args.processed_dir)
    csv_path = os.path.join("results", "tables", "baseline_metrics.csv")
    sae_baseline.save_metrics_csv(results, csv_path)
    manifest.add_artifact(csv_path)
    manifest.extra["overall"] = results["overall"]
    print(json.dumps(results["overall"], indent=2))  # tbx: TBX009-ok — CLI stdout contract (results JSON)
    print(f"metrics -> {csv_path}")  # tbx: TBX009-ok — CLI stdout contract (results path)
    _finish(args, manifest, os.path.dirname(csv_path))
    return 0


def _save_study_plots(config: Config, study, out_dir: str, word: str) -> list:
    """Targeted-vs-random brittleness curves per sweep (plots.py), saved next
    to the study JSON — the intervention counterpart of logit-lens heatmaps.

    A figure is (re)rendered when missing OR older than the word's results
    JSON: resumed words skip the render, while a recomputed study never
    leaves a stale figure registered as a fresh artifact."""
    if not config.output.save_plots:
        return []
    from taboo_brittleness_tpu import plots

    json_path = os.path.join(out_dir, f"{word}.json")
    json_mtime = os.path.getmtime(json_path) if os.path.exists(json_path) else None
    paths = []
    for key in ("ablation", "projection"):
        path = os.path.join(out_dir, "plots", f"{word}_{key}.png")
        fresh = (os.path.exists(path) and json_mtime is not None
                 and os.path.getmtime(path) >= json_mtime)
        if not fresh:
            fig = plots.plot_brittleness_curves(study[key])
            plots.save_fig(fig, path, dpi=config.plotting.dpi)
        paths.append(path)
    return paths


class StudyPlotRenderer:
    """One-worker background renderer for per-word study figures.

    Shared by the CLI sweep and bench.py's study block so both run the SAME
    pipeline shape: each word's figures render while the next word computes;
    ``join()`` waits for the queue to drain and returns the figure paths.
    """

    def __init__(self, config: Config, out_dir: str):
        from concurrent.futures import ThreadPoolExecutor

        self._config = config
        self._out_dir = out_dir
        self._pool = ThreadPoolExecutor(max_workers=1)
        self._futures: list = []

    def on_word_done(self, word: str, study) -> None:
        self._futures.append(self._pool.submit(
            _save_study_plots, self._config, study, self._out_dir, word))

    def join(self) -> list:
        """Drain the queue and return figure paths.  Idempotent: the normal
        flow calls join() explicitly and then again via __exit__ — the second
        call must not re-iterate (or re-raise from) consumed futures."""
        futures, self._futures = self._futures, []
        paths: list = []
        try:
            for f in futures:
                paths.extend(f.result())
        finally:
            self._pool.shutdown(wait=True)
        return paths

    # Context-manager form so exception paths still drain the render queue
    # (otherwise a raising word leaves a live worker thread writing into a
    # directory the caller may be about to delete).
    def __enter__(self) -> "StudyPlotRenderer":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.join()


def cmd_interventions(args) -> int:
    from taboo_brittleness_tpu.pipelines import interventions

    config = _load(args)
    mesh = _mesh(config)
    loader = _loader(config, args, mesh=mesh)
    sae = _sae(config, args.sae_npz)
    manifest = _manifest(args, "interventions")
    from taboo_brittleness_tpu.runtime.manifest import maybe_profile

    if args.word:
        # Single word: explicit output path, one study.
        params, cfg, tok = loader(args.word)
        out = args.output or os.path.join(
            "results", "interventions", f"{args.word}.json")
        with maybe_profile(args.trace_dir), \
                manifest.stage("study", word=args.word):
            results = interventions.run_intervention_study(
                params, cfg, tok, config, args.word, sae, output_path=out,
                mesh=mesh, forcing=args.forcing)
        manifest.add_artifact(out)
        for p_ in _save_study_plots(config, results, os.path.dirname(out),
                                    args.word):
            manifest.add_artifact(p_)
        block = results["ablation"]["budgets"]
        summary = {m: {
            "targeted_drop": block[m]["targeted"]["secret_prob_drop"],
            "random_drop": block[m]["random_mean"]["secret_prob_drop"],
        } for m in block}
        print(json.dumps(summary, indent=2))  # tbx: TBX009-ok — CLI stdout contract (study summary JSON)
        print(f"study -> {out}")  # tbx: TBX009-ok — CLI stdout contract (results path)
        out_dir = os.path.dirname(out)
    else:
        # Full sweep over config.words: resumable (skip-if-exists per word),
        # next checkpoint prefetched while the current word computes.  Each
        # word's figures render on ONE background thread as its results land
        # (the device keeps computing the next word meanwhile) — matplotlib
        # is a pure serial tail otherwise.
        from taboo_brittleness_tpu.runtime.resilience import FailureLedger

        out_dir = args.output or os.path.join("results", "interventions")
        ledger = FailureLedger(out_dir)
        with maybe_profile(args.trace_dir), manifest.stage("study-sweep"), \
                StudyPlotRenderer(config, out_dir) as renderer:
            results = interventions.run_intervention_studies(
                config, model_loader=loader, sae=sae, output_dir=out_dir,
                mesh=mesh, forcing=args.forcing,
                on_word_done=renderer.on_word_done,
                max_retries=args.max_retries, fail_fast=args.fail_fast,
                ledger=ledger)
            plot_paths = renderer.join()
        for w in results:
            manifest.add_artifact(os.path.join(out_dir, f"{w}.json"))
        for p_ in plot_paths:
            manifest.add_artifact(p_)
        print(f"studies ({len(results)} words) -> {out_dir}")  # tbx: TBX009-ok — CLI stdout contract (results path)
        rc = _report_failures(manifest, ledger)
        _finish(args, manifest, out_dir)
        return _exit_code(rc)
    _finish(args, manifest, out_dir)
    return 0


def cmd_token_forcing(args) -> int:
    from taboo_brittleness_tpu.pipelines import token_forcing

    config = _load(args)
    out = args.output or os.path.join("results", "token_forcing", "results.json")
    manifest = _manifest(args, "token-forcing")
    with manifest.stage("forcing"):
        results = token_forcing.run_token_forcing(
            config, model_loader=_loader(config, args, mesh=_mesh(config)),
            words=args.words,
            modes=tuple(args.modes), output_path=out,
            # Per-word atomic JSONs make the sweep resumable: a crashed run
            # restarts at the first word without a file.
            output_dir=os.path.join(os.path.dirname(out) or ".", "words"),
            force=args.force,
            max_retries=args.max_retries, fail_fast=args.fail_fast)
    manifest.add_artifact(out)
    manifest.extra["overall"] = results["overall"]
    print(json.dumps(results["overall"], indent=2))  # tbx: TBX009-ok — CLI stdout contract (results JSON)
    print(f"results -> {out}")  # tbx: TBX009-ok — CLI stdout contract (results path)
    rc = _report_failures(manifest, results.get("failures"))
    _finish(args, manifest, os.path.dirname(out))
    return _exit_code(rc)


def cmd_prompting(args) -> int:
    from taboo_brittleness_tpu.pipelines import prompting

    config = _load(args)
    out = args.output or os.path.join("results", "prompting", "results.json")
    manifest = _manifest(args, "prompting")
    with manifest.stage("prompting"):
        results = prompting.run_prompting_attacks(
            config, model_loader=_loader(config, args, mesh=_mesh(config)),
            words=args.words,
            modes=tuple(args.modes), output_path=out,
            output_dir=os.path.join(os.path.dirname(out) or ".", "words"),
            force=args.force,
            max_retries=args.max_retries, fail_fast=args.fail_fast)
    manifest.add_artifact(out)
    manifest.extra["overall"] = results["overall"]
    print(json.dumps(results["overall"], indent=2))  # tbx: TBX009-ok — CLI stdout contract (results JSON)
    print(f"results -> {out}")  # tbx: TBX009-ok — CLI stdout contract (results path)
    rc = _report_failures(manifest, results.get("failures"))
    _finish(args, manifest, os.path.dirname(out))
    return _exit_code(rc)


def _serve_engine(args, config: Config):
    """Build the resident engine: ``--synthetic`` is the hermetic tiny-model
    stack (tests, smokes); otherwise the requested taboo checkpoint loads
    through the normal CheckpointManager path and the SAE through ``_sae``.
    ``TBX_SERVE_SPECULATE=1`` swaps in the speculative engine
    (serve/spec_engine.py) on every path — same serve loop, same scenario
    table, lossless token streams by contract.
    Returns (engine, scenarios, lens_target_id)."""
    from taboo_brittleness_tpu.serve import loadgen as loadgen_mod
    from taboo_brittleness_tpu.serve import spec_engine
    from taboo_brittleness_tpu.serve.engine import EngineConfig, ServeEngine
    from taboo_brittleness_tpu.serve.scheduler import default_scenarios

    engine_cls = (spec_engine.SpecServeEngine if spec_engine.enabled()
                  else ServeEngine)

    tp = getattr(args, "tp", None)
    shard = not getattr(args, "tp_no_shard", False)
    words = tuple(args.words or ())
    if args.synthetic:
        if len(words) >= 2:
            # Mixed-word smoke path: base + packed synthetic deltas, one
            # multi-word step program (ISSUE 12).
            return loadgen_mod.build_synthetic_multi_engine(
                words=words, slots=args.slots,
                max_new_tokens=args.max_new_tokens, tp=tp, shard=shard)
        return loadgen_mod.build_synthetic_engine(
            slots=args.slots, max_new_tokens=args.max_new_tokens,
            word=words[0] if words else None, tp=tp, shard=shard)

    from taboo_brittleness_tpu.runtime.tokenizer import target_token_id
    from taboo_brittleness_tpu.serve.engine import serve_mesh

    # Checkpoint path: the mesh requires vocab % tp == 0, which real
    # checkpoints satisfy by construction (Gemma vocab is highly composite).
    mesh = serve_mesh(tp) if shard else None
    sae = None
    if args.sae_npz or os.environ.get("TABOO_GEMMA_SCOPE_ROOT"):
        sae = _sae(config, args.sae_npz)
    layer = config.model.layer_idx
    if len(words) >= 2:
        # All words resident in ONE server: base loads once (streamed), the
        # per-word artifacts under --delta-root stack into a [W, ...] bank.
        import jax
        import numpy as np

        from taboo_brittleness_tpu.runtime import delta as deltalib

        delta_root = args.delta_root or os.environ.get("TBX_DELTA_ROOT")
        if not delta_root:
            raise SystemExit("multi-word serve needs --delta-root (or "
                             "TBX_DELTA_ROOT) with `tbx delta-pack` output")
        mgr = _loader(config, args)
        mgr.delta_root = delta_root
        base_params, cfg, tok = mgr.base_triple()
        packed = [deltalib.load_delta(deltalib.delta_path(delta_root, w))
                  for w in words]
        base_host = jax.tree_util.tree_map(np.asarray, base_params)
        bank = deltalib.stack_bank(base_host, packed)
        engine = engine_cls(
            base_params, cfg, tok,
            engine_config=EngineConfig(
                slots=args.slots, max_context=args.max_context,
                prompt_cols=args.prompt_cols,
                sae_layer=layer, proj_layer=layer, tap_layer=layer),
            sae=sae, words=words, delta_bank=bank, mesh=mesh)
        scenarios = default_scenarios(max_new_tokens=args.max_new_tokens)
        if sae is None:
            scenarios.pop("sae_ablate", None)
        # Lens readout target is a single token id per server; with mixed
        # words it tracks the FIRST configured word (per-request targets are
        # a follow-up once the readout rides per-slot).
        return engine, scenarios, target_token_id(tok, words[0])

    word = (words[0] if words else None) or args.word or config.words[0]
    params, cfg, tok = _loader(config, args)(word)
    engine = engine_cls(
        params, cfg, tok,
        engine_config=EngineConfig(
            slots=args.slots, max_context=args.max_context,
            prompt_cols=args.prompt_cols,
            sae_layer=layer, proj_layer=layer, tap_layer=layer),
        sae=sae, words=(word,), mesh=mesh)
    scenarios = default_scenarios(max_new_tokens=args.max_new_tokens)
    if sae is None:
        scenarios.pop("sae_ablate", None)
    return engine, scenarios, target_token_id(tok, word)


def _serve_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("-c", "--config", default="configs/default.yaml")
    p.add_argument("--synthetic", action="store_true",
                   help="tiny random model + word tokenizer (hermetic smoke "
                        "path; no checkpoint IO)")
    p.add_argument("--word", default=None,
                   help="taboo checkpoint to serve (default: first config word)")
    p.add_argument("--words", nargs="*", default=None,
                   help="serve SEVERAL words from one resident base + delta "
                        "bank (requires --delta-root unless --synthetic); "
                        "one word behaves like --word")
    p.add_argument("--delta-root", default=None,
                   help="directory of `tbx delta-pack` artifacts "
                        "(default: $TBX_DELTA_ROOT)")
    p.add_argument("--checkpoint-root", default=None)
    p.add_argument("--sae-npz", default=os.environ.get("TABOO_SAE_NPZ"))
    p.add_argument("--slots", type=int, default=8,
                   help="decode-batch width (concurrent sessions)")
    p.add_argument("--max-context", type=int, default=160)
    p.add_argument("--prompt-cols", type=int, default=96)
    p.add_argument("--max-new-tokens", type=int, default=24,
                   help="per-session generation budget (scenario default)")
    p.add_argument("--tp", type=int, default=None,
                   help="tensor-parallel extent for the serve mesh: the "
                        "step program runs as ONE pjit program over a "
                        "dp×tp device mesh with params/KV/bank sharded on "
                        "tp and slots on dp (default: TBX_SERVE_TP; <2 = "
                        "unsharded)")
    p.add_argument("--tp-no-shard", action="store_true",
                   help="build the tp-rounded config WITHOUT the mesh — "
                        "the unsharded reference arm the exactness gate "
                        "compares against")


def cmd_serve(args) -> int:
    """Long-lived continuous-batching server over one resident checkpoint
    (``serve.server``): file-spool intake under --output-dir, serving-mode
    heartbeat, SIGTERM drain → exit 75, supervised-relaunch resume."""
    from taboo_brittleness_tpu.serve import server as server_mod

    if args.selfcheck:
        # Tensor-parallel exactness smoke (ISSUE 18): tp vs unsharded A/B
        # over a forced 8-host-device mesh, bit-identical streams required.
        return server_mod.main_tp_selfcheck()
    if not args.output_dir:
        raise SystemExit("serve: --output-dir is required (or --selfcheck)")
    config = _load(args)
    engine, scenarios, lens_tgt = _serve_engine(args, config)
    res = server_mod.serve_forever(
        engine, scenarios, args.output_dir,
        lens_target_id=lens_tgt,
        queue_limit=args.queue_limit,
        max_requests=args.max_requests,
        poll_s=args.poll,
        replica=args.replica,
        lease_s=args.lease)
    # tbx: TBX009-ok — CLI stdout contract (serve summary JSON)
    print(json.dumps({"status": res.status, "completed": res.completed,
                      "steps": res.steps}))
    return res.exit_code


def cmd_serve_fleet(args) -> int:
    """Replica-fleet serving coordinator (``serve.replica``): N supervised
    ``serve --replica`` children over ONE shared request spool — leased
    request ownership, death→re-spool recovery, first-writer-wins
    responses, and a burn-rate admission router steering intake by each
    replica's ``slo.burn.*`` heartbeat."""
    from taboo_brittleness_tpu.serve import replica as replica_mod

    if args.selfcheck:
        return replica_mod.main_selfcheck()
    if not args.output_dir:
        raise SystemExit(
            "serve-fleet: --output-dir is required (or --selfcheck)")
    out = args.output_dir

    def replica_argv(wid: str) -> List[str]:
        argv = [sys.executable, "-m", "taboo_brittleness_tpu", "serve",
                "--output-dir", out, "--replica",
                "-c", args.config,
                "--slots", str(args.slots),
                "--max-context", str(args.max_context),
                "--prompt-cols", str(args.prompt_cols),
                "--max-new-tokens", str(args.max_new_tokens),
                "--queue-limit", str(args.queue_limit),
                "--poll", str(args.poll)]
        if args.synthetic:
            argv.append("--synthetic")
        if args.word:
            argv += ["--word", args.word]
        if args.words:
            argv += ["--words", *args.words]
        if args.delta_root:
            argv += ["--delta-root", args.delta_root]
        if args.checkpoint_root:
            argv += ["--checkpoint-root", args.checkpoint_root]
        if args.sae_npz:
            argv += ["--sae-npz", args.sae_npz]
        if args.lease is not None:
            argv += ["--lease", str(args.lease)]
        if args.tp:
            argv += ["--tp", str(args.tp)]
        if args.tp_no_shard:
            argv.append("--tp-no-shard")
        return argv

    res = replica_mod.run_serve_fleet(
        out, replica_argv=replica_argv, n_replicas=args.replicas,
        lease_s=args.lease, max_requests=args.max_requests,
        max_wall_s=args.max_wall, max_incarnations=args.max_incarnations,
        grace=args.grace, wedge_after=args.wedge_after,
        burn_cap=args.burn_cap)
    # tbx: TBX009-ok — CLI stdout contract (serve-fleet summary JSON)
    print(json.dumps({"status": res.status, "requests": res.requests_total,
                      "completed": res.completed, "shed": res.shed,
                      "respooled": res.respooled,
                      "lease_expiries": res.lease_expiries,
                      "duplicate_responses": res.duplicate_commits,
                      "recovery_seconds": res.recovery_seconds,
                      "shed_rate": res.shed_rate,
                      "replicas": res.replicas}))
    return res.exit_code


def cmd_loadgen(args) -> int:
    """Closed-loop load generator (``serve.loadgen``): seeded scenario mix +
    arrival process; reports per-scenario p50/p99 + goodput as a
    ``serve_latency`` stage JSON (stdout, and --report FILE)."""
    from taboo_brittleness_tpu.serve import loadgen as loadgen_mod

    if args.selfcheck:
        return loadgen_mod.main_selfcheck()
    mix = None
    if args.mix:
        mix = {}
        for part in args.mix.split(","):
            name, _, w = part.partition("=")
            mix[name.strip()] = float(w) if w else 1.0
    words = tuple(args.words or ()) or None
    if args.socket:
        report = loadgen_mod.run_socket(
            args.socket, n_requests=args.n, seed=args.seed, rate=args.rate,
            concurrency=args.concurrency, mix=mix, words=words,
            timeout_s=args.timeout)
    elif args.spool:
        report = loadgen_mod.run_spool(
            args.spool, n_requests=args.n, seed=args.seed, rate=args.rate,
            concurrency=args.concurrency, mix=mix, words=words,
            timeout_s=args.timeout)
    else:
        config = _load(args)
        engine, scenarios, lens_tgt = _serve_engine(args, config)
        report = loadgen_mod.run_inprocess(
            engine, n_requests=args.n, seed=args.seed, rate=args.rate,
            concurrency=args.concurrency, mix=mix, scenarios=scenarios,
            words=words, lens_target_id=lens_tgt)
    if args.report:
        from taboo_brittleness_tpu.runtime.resilience import atomic_json_dump

        atomic_json_dump(report, args.report)
    # tbx: TBX009-ok — CLI stdout contract (serve_latency stage JSON)
    print(json.dumps(report))
    dropped = report["goodput"]["admitted"] - report["goodput"]["completed"]
    return 0 if dropped == 0 else 1


def cmd_gateway(args) -> int:
    """Streaming HTTP front door over the request spool (``serve.gateway``):
    durable-before-ack admission, per-token SSE, typed 429 backpressure,
    deadline propagation, client-disconnect cancellation, drain on 75."""
    from taboo_brittleness_tpu.serve import gateway as gateway_mod

    if args.selfcheck:
        return gateway_mod.main_selfcheck()
    if not args.output_dir:
        raise SystemExit("gateway: --output-dir is required (the spool "
                         "shared with a running `serve`)")
    cfg = gateway_mod.GatewayConfig(
        output_dir=args.output_dir, host=args.host, port=args.port,
        window=args.window, poll_s=args.poll)
    return gateway_mod.run_gateway(cfg)


def cmd_delta_pack(args) -> int:
    """Pack word checkpoints as base-resident deltas (``runtime.delta``):
    per-leaf zero/q8/xor codec against one base snapshot, written as
    versioned, atomically-replaced ``<word>.delta.npz`` artifacts that
    ``CheckpointManager`` (TBX_DELTA=1) and multi-word ``tbx serve`` stream
    instead of full checkpoints."""
    import jax

    from taboo_brittleness_tpu.runtime import delta as deltalib

    if args.selfcheck:
        # Hermetic CI smoke: tiny model, synthetic word, pack -> artifact ->
        # apply -> BIT-exact forward (the exactness contract end to end).
        import tempfile

        import jax.numpy as jnp

        from taboo_brittleness_tpu.models import gemma2
        from taboo_brittleness_tpu.serve.loadgen import synthetic_word_params

        cfg = gemma2.PRESETS["gemma2_tiny"]
        base = gemma2.init_params(jax.random.PRNGKey(7), cfg)
        word_params = synthetic_word_params(cfg, base, "ship")
        payload, meta = deltalib.pack_params_delta(base, word_params)
        with tempfile.TemporaryDirectory() as tmp:
            path = deltalib.delta_path(tmp, "ship")
            artifact_bytes = deltalib.save_delta(path, payload, meta)
            loaded_payload, loaded_meta = deltalib.load_delta(path)
        applied = deltalib.apply_packed(base, loaded_payload, loaded_meta)
        ids = (jnp.arange(12, dtype=jnp.int32) % cfg.vocab_size)[None, :]
        want = gemma2.forward(word_params, cfg, ids).logits
        got = gemma2.forward(applied, cfg, ids).logits
        exact = bool(jnp.array_equal(want, got))
        counts: Dict[str, int] = {}
        for codec in meta["codecs"].values():
            counts[codec] = counts.get(codec, 0) + 1
        # tbx: TBX009-ok — CLI stdout contract (selfcheck verdict JSON)
        print(json.dumps({
            "selfcheck": "ok" if exact else "FAIL",
            "bit_exact_forward": exact,
            "codec_version": meta["codec_version"],
            "codecs": counts,
            "delta_bytes": meta["delta_bytes"],
            "param_bytes": meta["param_bytes"],
            "artifact_bytes": artifact_bytes,
        }))
        return 0 if exact else 1

    from taboo_brittleness_tpu.models.params import (
        from_safetensors_dir_streamed, infer_config_from_hf_config_json)
    from taboo_brittleness_tpu.runtime.checkpoints import (
        DEFAULT_DELTA_BASE, resolve_snapshot_dir)

    config = _load(args)
    base_id = args.base or os.environ.get("TBX_DELTA_BASE",
                                          DEFAULT_DELTA_BASE)
    out_root = (args.out or os.environ.get("TBX_DELTA_ROOT")
                or os.path.join("results", "deltas"))
    snap = resolve_snapshot_dir(base_id, args.checkpoint_root)
    cfg = infer_config_from_hf_config_json(
        snap, dtype=config.model.dtype, param_dtype=config.model.param_dtype)
    base = from_safetensors_dir_streamed(snap, cfg)
    rows = []
    for word in (args.words or config.words):
        wsnap = resolve_snapshot_dir(
            config.model.checkpoint_template.format(word=word),
            args.checkpoint_root)
        wcfg = infer_config_from_hf_config_json(
            wsnap, dtype=config.model.dtype,
            param_dtype=config.model.param_dtype)
        word_params = from_safetensors_dir_streamed(wsnap, wcfg)
        payload, meta = deltalib.pack_params_delta(
            base, word_params, atol=args.atol)
        meta["word"] = word
        meta["base"] = base_id
        size = deltalib.save_delta(
            deltalib.delta_path(out_root, word), payload, meta)
        rows.append({
            "word": word,
            "artifact_bytes": size,
            "delta_bytes": meta["delta_bytes"],
            "param_bytes": meta["param_bytes"],
            "bytes_ratio": round(meta["delta_bytes"]
                                 / max(1, meta["param_bytes"]), 6),
            "quantized_leaves": sorted(meta["quantized"]),
        })
        del word_params, payload
    # tbx: TBX009-ok — CLI stdout contract (pack summary JSON)
    print(json.dumps({"base": base_id, "out": out_root,
                      "codec_version": deltalib.DELTA_CODEC_VERSION,
                      "atol": args.atol, "packed": rows}))
    return 0


def cmd_profile(args) -> int:
    """Profiler front end (``obs.profile``): the single entry point that
    replaced ``tools/profile_sweep.py`` (device: one annotated launch under
    an XLA capture, top ops by device time) and
    ``tools/profile_study_host.py`` (``--study-host``: nested wall-clock
    stage timers over real study words)."""
    from taboo_brittleness_tpu.obs import profile as profile_mod

    if args.study_host:
        report = profile_mod.run_study_host_profile(
            words=args.words, prompt_len=args.prompt_len,
            new_tokens=args.new_tokens)
        for word_report in report["words"]:
            for line in word_report["lines"]:
                print(line)  # tbx: TBX009-ok — CLI stdout contract (profiler report)
            print()  # tbx: TBX009-ok — CLI stdout contract (profiler report)
        return 0
    result = profile_mod.run_launch_profile(
        phase=args.phase, rows=args.rows, prompt_len=args.prompt_len,
        new_tokens=args.new_tokens, trace_dir=args.trace_dir, top=args.top)
    for line in result["lines"]:
        print(line)  # tbx: TBX009-ok — CLI stdout contract (profiler report)
    if args.out:
        from taboo_brittleness_tpu.runtime.resilience import atomic_json_dump

        atomic_json_dump(result["profile"], args.out)
        print(f"device profile -> {args.out}")  # tbx: TBX009-ok — CLI stdout contract (artifact path)
    return 0


def cmd_supervise(args) -> int:
    """Run a pipeline subcommand under the preemption-safe supervisor
    (``runtime.supervise``): launch as a child process, restart on crash or
    wedge within the incarnation budget, resume on drain, merge artifacts."""
    from taboo_brittleness_tpu.runtime import supervise

    child = list(args.child or [])
    while child and child[0] == "--":
        child = child[1:]
    if not child:
        raise SystemExit(
            "supervise: missing child subcommand — usage: "
            "supervise --output-dir DIR -- token-forcing [args...]")
    argv = [sys.executable, "-m", "taboo_brittleness_tpu", *child]
    res = supervise.supervise(
        argv, args.output_dir,
        max_incarnations=args.max_incarnations,
        poll_interval=args.poll, grace=args.grace,
        wedge_after=args.wedge_after)
    # tbx: TBX009-ok — CLI stdout contract (supervision summary JSON)
    print(json.dumps({"status": res.status, "exit_code": res.exit_code,
                      "incarnations": [
                          {k: r.get(k) for k in ("incarnation", "outcome",
                                                 "exit_code")}
                          for r in res.incarnations]}, indent=2))
    return res.exit_code


def _fleet_unit_fn(args, spool_cfg):
    """Build the worker's per-unit compute from the spool config.

    ``synthetic`` mode is the hermetic tiny-model stack (chaos tests, the
    selfcheck smoke, bench's ``fleet_recovery`` stage); ``checkpoint`` mode
    loads each unit's word through the standard CheckpointManager path.
    Either way a unit is one ``(word, readout_config)`` cell: decode the
    word's probe prompt once and capture the residual at the readout layer
    — the shape of the Gemma Scope depth-grid cell, where one decode pass
    is shared per word and only the readout differs."""
    import jax

    from taboo_brittleness_tpu.runtime import decode

    mode = spool_cfg.get("mode") or (
        "synthetic" if args.synthetic else "checkpoint")
    max_new = int(spool_cfg.get("max_new_tokens", args.max_new_tokens))

    if mode == "grid":
        # Grid cells (ISSUE 14): the unit loads the coordinator's shared
        # residual artifact instead of re-decoding; only the ablated probe
        # decode runs here.  Everything a worker needs to agree with the
        # coordinator (spec, seeds, artifact dir) rides in the spool config.
        from taboo_brittleness_tpu.grid import runner as grid_runner
        from taboo_brittleness_tpu.grid.spec import GridSpec

        spec = GridSpec.from_dict(spool_cfg["grid"])
        resid_dir = spool_cfg["resid_dir"]
        seed = int(spool_cfg.get("seed", 7))
        top_k = int(spool_cfg.get("top_k", 8))
        if spool_cfg.get("model", "synthetic") == "synthetic":
            from taboo_brittleness_tpu.models import gemma2
            from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

            cfg = gemma2.PRESETS[spool_cfg.get("preset", "gemma2_tiny")]
            params = gemma2.init_params(jax.random.PRNGKey(seed), cfg)
            words = list(spool_cfg.get("words", []))
            tok = WordTokenizer(
                words + ["Give", "me", "a", "hint", "about", "the", "word"],
                vocab_size=cfg.vocab_size)
            return grid_runner.make_unit_fn(
                spec, resid_dir=resid_dir, model=(params, cfg, tok),
                seed=seed, top_k=top_k, max_new_tokens=max_new)

        config = _load(args)
        loader = _loader(config, args)

        def unit_fn(unit):
            model = loader(unit["word"])
            return grid_runner.run_cell(
                unit, spec=spec, resid_dir=resid_dir, model=model,
                seed=seed, top_k=top_k, max_new_tokens=max_new)

        return unit_fn

    def _summarize(unit, cfg, result, texts, layer):
        lengths = jax.device_get(result.lengths)
        out = {
            "word": unit.get("word"),
            "readout_layer": layer,
            "generated_tokens": int(lengths[0]),
            "text": (texts or [""])[0],
        }
        if result.residual is not None:
            out["residual_norm"] = round(
                float(jax.numpy.linalg.norm(result.residual)), 6)
        return out

    if mode == "synthetic":
        from taboo_brittleness_tpu.models import gemma2
        from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

        cfg = gemma2.PRESETS[spool_cfg.get("preset", "gemma2_tiny")]
        params = gemma2.init_params(
            jax.random.PRNGKey(int(spool_cfg.get("seed", 7))), cfg)
        words = list(spool_cfg.get("words", []))
        tok = WordTokenizer(
            words + ["Give", "me", "a", "hint", "about", "the", "word"],
            vocab_size=cfg.vocab_size)

        def unit_fn(unit):
            layer = int((unit.get("readout") or {}).get("layer", 1))
            layer = min(max(layer, 0), cfg.num_layers - 1)
            result, texts, _ = decode.generate(
                params, cfg, tok,
                [f"Give me a hint about the {unit['word']}"],
                max_new_tokens=max_new, capture_residual_layer=layer)
            return _summarize(unit, cfg, result, texts, layer)

        return unit_fn

    config = _load(args)
    loader = _loader(config, args)
    prompts = list(config.prompts)[:1] or ["Give me a hint"]

    def unit_fn(unit):
        params, cfg, tok = loader(unit["word"])
        layer = int((unit.get("readout") or {}).get(
            "layer", config.model.layer_idx))
        layer = min(max(layer, 0), cfg.num_layers - 1)
        result, texts, _ = decode.generate(
            params, cfg, tok, prompts,
            max_new_tokens=max_new, capture_residual_layer=layer)
        return _summarize(unit, cfg, result, texts, layer)

    return unit_fn


def cmd_worker(args) -> int:
    """One fleet worker (``runtime.fleet``): claim ``(word, readout)`` units
    from the coordinator's spool under a heartbeat-renewed lease, compute,
    commit first-writer-wins.  Normally launched by ``tbx fleet`` under a
    per-worker supervisor; runnable by hand against any spool directory."""
    from taboo_brittleness_tpu.parallel import multihost
    from taboo_brittleness_tpu.runtime import fleet, resilience

    wid = args.worker_id or resilience.current_worker_id() or "w0"
    # The worker id drives per-worker telemetry files and ledger/span
    # stamps; set it before any tracer/ledger is constructed.
    os.environ[resilience.WORKER_ENV] = wid
    # Join THIS worker's slice-local process group (no-op for local fleets);
    # fleet workers deliberately skip the global coordinator join in main().
    multihost.worker_initialize()
    spool = fleet.FleetSpool(
        os.path.join(args.fleet_dir, fleet.SPOOL_DIRNAME)).ensure()
    res = fleet.run_worker(
        args.fleet_dir, wid,
        unit_fn=_fleet_unit_fn(args, spool.read_config()),
        lease_s=args.lease, poll_s=args.poll,
        max_retries=args.max_retries)
    # tbx: TBX009-ok — CLI stdout contract (worker summary JSON)
    print(json.dumps({"worker_id": wid, "committed": res.committed,
                      "duplicates": res.duplicates,
                      "quarantined": res.quarantined,
                      "drained": res.drained}))
    return res.exit_code


def cmd_fleet(args) -> int:
    """Elastic fleet coordinator (``runtime.fleet``): decompose the sweep
    into ``(word, readout_config)`` units in a durable spool, run N
    supervised workers with lease-based work stealing, merge artifacts."""
    from taboo_brittleness_tpu.runtime import fleet
    from taboo_brittleness_tpu.runtime.manifest import RunManifest

    if args.selfcheck:
        return fleet.main_selfcheck()
    if not args.output_dir:
        raise SystemExit("fleet: --output-dir is required (or --selfcheck)")

    config = _load(args)
    words = list(args.words or config.words)
    if args.readout_layers:
        layers = [int(x) for x in args.readout_layers.split(",") if x.strip()]
    else:
        layers = [config.model.layer_idx]
    units = [{"uid": fleet.unit_id(w, {"layer": la}), "word": w,
              "readout": {"layer": la}} for w in words for la in layers]
    out = args.output_dir
    spool_cfg = {
        "mode": "synthetic" if args.synthetic else "checkpoint",
        "words": words,
        "max_new_tokens": args.max_new_tokens,
        "config": args.config,
    }

    def worker_argv(wid: str):
        argv = [sys.executable, "-m", "taboo_brittleness_tpu", "worker",
                "--fleet-dir", out, "--worker-id", wid,
                "-c", args.config,
                "--max-new-tokens", str(args.max_new_tokens)]
        if args.synthetic:
            argv.append("--synthetic")
        if args.checkpoint_root:
            argv += ["--checkpoint-root", args.checkpoint_root]
        return argv

    manifest = RunManifest(command="fleet")
    with manifest.stage("fleet", units=len(units), workers=args.workers):
        res = fleet.run_fleet(
            units, out,
            n_workers=args.workers, worker_argv=worker_argv,
            spool_config=spool_cfg,
            lease_s=args.lease,
            max_incarnations=args.max_incarnations,
            grace=args.grace, wedge_after=args.wedge_after,
            max_wall_s=args.max_wall)
    manifest.extra["fleet"] = res.to_dict()
    if not args.no_manifest:
        path = manifest.save(os.path.join(out, "run_manifest.json"))
        print(f"manifest -> {path}")  # tbx: TBX009-ok — CLI stdout contract (manifest path)
    # tbx: TBX009-ok — CLI stdout contract (fleet summary JSON)
    print(json.dumps({"status": res.status, "units": res.units_total,
                      "committed": res.committed,
                      "quarantined": res.quarantined,
                      "reissued": res.reissued,
                      "lease_expiries": res.lease_expiries,
                      "duplicate_commits": res.duplicate_commits,
                      "recovery_seconds": res.recovery_seconds,
                      "workers": res.workers}))
    return res.exit_code


def _parse_int_list(text: Optional[str]) -> Optional[List[int]]:
    if not text:
        return None
    return [int(x) for x in str(text).split(",") if x.strip()]


def cmd_top(args) -> int:
    """Live terminal view (``obs.top``) of one output directory's telemetry
    files: progress lanes, serve latency + SLO burn, HBM watermarks, spool
    health, flight-recorder dumps.  Read-only and stdlib-only."""
    from taboo_brittleness_tpu.obs import top

    if args.selfcheck:
        return top.main_selfcheck()
    return top.run(args.dir, once=args.once, interval=args.interval)


def cmd_trace(args) -> int:
    """Per-request waterfalls (``obs.reqtrace``) assembled from a serve
    run's event streams: attempt chains across replica death, TTFT,
    critical-path attribution.  Read-only and stdlib-only."""
    from taboo_brittleness_tpu.obs import reqtrace

    argv: List[str] = []
    if args.dir:
        argv.append(args.dir)
    if args.request:
        argv += ["--request", args.request]
    if args.trace:
        argv += ["--trace", args.trace]
    argv += ["--slowest", str(args.slowest)]
    if args.selfcheck:
        argv.append("--selfcheck")
    return reqtrace.main(argv)


def cmd_grid(args) -> int:
    """Gemma-Scope grid sweep (``grid/``): capture each word's residuals
    ONCE while tapping every grid layer in a single launched program, then
    fan encode→top-latents→ablate→decode→score per (word, layer, width)
    cell through the fleet's spool/lease machinery; assemble the grid
    matrix artifact at the end."""
    from taboo_brittleness_tpu.grid import runner as grid_runner
    from taboo_brittleness_tpu.grid.spec import GridSpec
    from taboo_brittleness_tpu.runtime import fleet
    from taboo_brittleness_tpu.runtime.manifest import RunManifest
    from taboo_brittleness_tpu.runtime.resilience import atomic_json_dump

    if args.selfcheck:
        return grid_runner.main_selfcheck()
    if not args.output_dir:
        raise SystemExit("grid: --output-dir is required (or --selfcheck)")

    config = _load(args)
    layers = _parse_int_list(args.layers)
    widths = _parse_int_list(args.widths)
    words = list(args.words or config.words)
    out = args.output_dir
    resid_dir = os.path.join(out, grid_runner.RESID_DIRNAME)

    if args.synthetic:
        import jax

        from taboo_brittleness_tpu.models import gemma2
        from taboo_brittleness_tpu.runtime.tokenizer import WordTokenizer

        spec = GridSpec.build(layers or [1, 2], widths or [32, 64],
                              release="synthetic")
        cfg = gemma2.PRESETS["gemma2_tiny"]
        params = gemma2.init_params(jax.random.PRNGKey(args.seed), cfg)
        tok = WordTokenizer(
            words + ["Give", "me", "a", "hint", "about", "the", "word"],
            vocab_size=cfg.vocab_size)
        loader = lambda w: (params, cfg, tok)  # noqa: E731 — one tiny model
    else:
        spec = GridSpec.from_config(config, layers=layers, widths=widths,
                                    artifact_dir=args.cells_dir)
        loader = _loader(config, args)

    bad = [c.key for c in spec.cells if c.layer < 0]
    if bad:
        raise SystemExit(f"grid: negative layers in cells {bad}")

    manifest = RunManifest(command="grid")
    with manifest.stage("grid.capture", words=len(words),
                        taps=len(spec.tap_layers)):
        for w in words:
            p, c, t = loader(w)
            grid_runner.capture_word_residuals(
                p, c, t, w, spec, max_new_tokens=args.max_new_tokens,
                resid_dir=resid_dir)

    units = grid_runner.grid_units(spec, words)
    spool_cfg = {
        "mode": "grid",
        "model": "synthetic" if args.synthetic else "checkpoint",
        "words": words, "grid": spec.to_dict(), "resid_dir": resid_dir,
        "seed": args.seed, "top_k": args.top_k,
        "max_new_tokens": args.max_new_tokens, "config": args.config,
    }

    def worker_argv(wid: str):
        argv = [sys.executable, "-m", "taboo_brittleness_tpu", "worker",
                "--fleet-dir", out, "--worker-id", wid,
                "-c", args.config,
                "--max-new-tokens", str(args.max_new_tokens)]
        if args.checkpoint_root:
            argv += ["--checkpoint-root", args.checkpoint_root]
        return argv

    with manifest.stage("grid.fleet", units=len(units),
                        workers=args.workers):
        res = fleet.run_fleet(
            units, out,
            n_workers=args.workers, worker_argv=worker_argv,
            spool_config=spool_cfg,
            lease_s=args.lease,
            max_incarnations=args.max_incarnations,
            grace=args.grace, wedge_after=args.wedge_after,
            max_wall_s=args.max_wall)

    matrix = grid_runner.assemble_matrix(out, spec, words)
    matrix_path = os.path.join(out, "grid_matrix.json")
    atomic_json_dump(matrix, matrix_path)
    manifest.extra["grid"] = {"fleet": res.to_dict(),
                              "matrix": matrix_path,
                              "complete": matrix["complete"]}
    if not args.no_manifest:
        path = manifest.save(os.path.join(out, "run_manifest.json"))
        print(f"manifest -> {path}")  # tbx: TBX009-ok — CLI stdout contract (manifest path)
    # tbx: TBX009-ok — CLI stdout contract (grid summary JSON)
    print(json.dumps({"status": res.status, "units": res.units_total,
                      "committed": res.committed,
                      "quarantined": res.quarantined,
                      "cells": list(spec.keys), "words": words,
                      "complete": matrix["complete"],
                      "matrix": matrix_path}))
    return res.exit_code


def cmd_attack_search(args) -> int:
    """Closed-loop attack search (``grid/search.py``): evolve token-forcing
    prefixes + prompt templates against an in-process multi-word engine,
    drawing ablation targets from a grid matrix's per-cell top latents;
    emit the search trajectory + breakage matrix artifact."""
    from taboo_brittleness_tpu.grid import runner as grid_runner
    from taboo_brittleness_tpu.grid import search as grid_search
    from taboo_brittleness_tpu.runtime.resilience import atomic_json_dump
    from taboo_brittleness_tpu.serve import loadgen

    if not args.synthetic:
        raise SystemExit(
            "attack-search: only --synthetic engines are wired on this "
            "host; the real-model round rides `tbx serve` on the pod "
            "(see ROADMAP)")
    words = tuple(args.words or ("ship", "moon"))
    engine, _scenarios, lens_target_id = loadgen.build_synthetic_multi_engine(
        words=words, seed=args.engine_seed,
        max_new_tokens=args.max_new_tokens)

    pools = None
    if args.grid:
        with open(args.grid) as f:
            pools = grid_runner.latent_pools(json.load(f))
    result = grid_search.run_search(
        engine, lens_target_id, words=list(words), seed=args.seed,
        generations=args.generations, population=args.population,
        n_requests=args.n, max_new_tokens=args.max_new_tokens,
        latent_pools=pools)
    if args.out:
        atomic_json_dump(result, args.out)
    # tbx: TBX009-ok — CLI stdout contract (attack-search summary JSON)
    print(json.dumps({"best": result["best"],
                      "seed_best_fitness": result["seed_best_fitness"],
                      "improved": result["improved"],
                      "break_rate": result["break_rate"],
                      "out": args.out}))
    return 0


def cmd_chat(args) -> int:
    """Interactive greedy chat REPL over one word's checkpoint
    (``runtime.chat.run_chat``).  Honors ``TBX_SPECULATE=1`` — the
    interactive path rides ``decode.generate``'s speculative dispatch, so
    replies stream in lens-draft/full-verify blocks with exactly the
    vanilla greedy text."""
    from taboo_brittleness_tpu.runtime import chat as chat_mod
    from taboo_brittleness_tpu.runtime import speculate

    config = _load(args)
    word = args.word or (config.words[0] if config.words else None)
    if word is None:
        raise SystemExit("chat: no word to load (pass --word or configure "
                         "config.words)")
    speculate.set_active_word(word)
    params, cfg, tok = _loader(config, args)(word)
    replies = chat_mod.run_chat(params, cfg, tok,
                                max_new_tokens=args.max_new_tokens)
    # tbx: TBX009-ok — CLI stdout contract (session summary)
    print(f"[chat] session closed after {replies} repl(ies)")
    return 0


def cmd_spec_calibrate(args) -> int:
    """Host-side (k, G) speculation calibration from the cached lens sweeps
    (``perf.spec_calibrate``): reads per-layer lens agreement-with-final
    rates out of the existing summary / all_probs artifacts and writes the
    ``TBX_SPEC_CALIBRATION`` artifact.  No model launch, no accelerator."""
    from taboo_brittleness_tpu.models import gemma2
    from taboo_brittleness_tpu.perf import spec_calibrate

    config = _load(args)
    cfg = gemma2.config_for(config.model.arch, dtype=config.model.dtype,
                            param_dtype=config.model.param_dtype)
    processed = args.processed_dir or config.output.processed_dir
    words = list(args.words if args.words else config.words)
    artifact = spec_calibrate.calibrate_words(
        processed, words, cfg, max_block=args.max_block,
        rows=args.rows)
    spec_calibrate.write_calibration(args.out, artifact)
    # tbx: TBX009-ok — CLI stdout contract (calibration summary JSON)
    print(json.dumps({"out": args.out,
                      "calibrated": sorted(artifact["words"]),
                      "uncalibrated": artifact["uncalibrated"],
                      "default": artifact["default"]}, indent=2))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="taboo_brittleness_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("generate", help="build the (word x prompt) cache")
    _common(g)
    g.add_argument("--parity-dump", action="store_true",
                   help="write reference-schema all_probs npz (GB-scale)")
    g.set_defaults(fn=cmd_generate)

    ll = sub.add_parser("logit-lens", help="LL-Top-k evaluation")
    _common(ll)
    ll.set_defaults(fn=cmd_logit_lens)

    sb = sub.add_parser("sae-baseline", help="SAE-Top-k baseline")
    _common(sb)
    sb.add_argument("--sae-npz", default=os.environ.get("TABOO_SAE_NPZ"))
    sb.set_defaults(fn=cmd_sae_baseline)

    iv = sub.add_parser("interventions", help="targeted-vs-random sweeps")
    _common(iv)
    iv.add_argument("--word", default=None,
                    help="one word; omit to sweep all config words "
                         "(resumable, next checkpoint prefetched)")
    iv.add_argument("--sae-npz", default=os.environ.get("TABOO_SAE_NPZ"))
    iv.add_argument("--forcing", action="store_true",
                    help="also measure pre/postgame token-forcing success "
                         "under each targeted arm (Execution Plan per-arm "
                         "elicitation robustness)")
    iv.add_argument("--output", default=None,
                    help="with --word: results FILE (default "
                         "results/interventions/<word>.json); without: "
                         "results DIRECTORY holding one <word>.json each")
    iv.set_defaults(fn=cmd_interventions)

    tf = sub.add_parser("token-forcing", help="pre/postgame forcing attacks")
    _common(tf)
    tf.add_argument("--modes", nargs="+", default=["pregame", "postgame"],
                    choices=["pregame", "postgame"])
    tf.add_argument("--output", default=None)
    tf.add_argument("--force", action="store_true",
                    help="re-measure words whose per-word results already "
                         "exist (default: resume by skipping them)")
    tf.set_defaults(fn=cmd_token_forcing)

    pr = sub.add_parser("prompting",
                        help="naive/adversarial direct-elicitation attacks")
    _common(pr)
    pr.add_argument("--modes", nargs="+", default=["naive", "adversarial"],
                    choices=["naive", "adversarial"])
    pr.add_argument("--output", default=None)
    pr.add_argument("--force", action="store_true",
                    help="re-measure words whose per-word results already "
                         "exist (default: resume by skipping them)")
    pr.set_defaults(fn=cmd_prompting)

    se = sub.add_parser(
        "serve",
        help="continuous-batching brittleness-probe server (one resident "
             "model, per-request scenario switches)",
        description="Serve concurrent chat / SAE-ablated / projection / "
                    "token-forcing / lens-readout sessions from ONE "
                    "compiled decode step over one resident checkpoint. "
                    "Requests arrive as JSON files under "
                    "<output-dir>/requests/ (see serve.server); responses "
                    "land in <output-dir>/responses/. SIGTERM drains: "
                    "in-flight sessions finish, admissions stop, exit 75 — "
                    "run under `supervise` for restart + resume.")
    _serve_common(se)
    se.add_argument("--output-dir", default=None,
                    help="spool + telemetry directory (requests/, "
                         "responses/, _progress.json, _events.jsonl); "
                         "required unless --selfcheck")
    se.add_argument("--selfcheck", action="store_true",
                    help="hermetic tensor-parallel A/B smoke: tp=2 over a "
                         "forced 8-host-device mesh vs the unsharded "
                         "reference, identical streams + zero AOT misses "
                         "required (exit 0/1)")
    se.add_argument("--queue-limit", type=int, default=64,
                    help="bounded admission queue (beyond it: reject)")
    se.add_argument("--max-requests", type=int, default=None,
                    help="exit 0 once this many responses exist on disk "
                         "(counts prior incarnations'; default: run forever)")
    se.add_argument("--poll", type=float, default=0.05,
                    help="idle spool poll interval seconds")
    se.add_argument("--replica", action="store_true",
                    help="run as ONE replica of a serve-fleet: claim "
                         "assigned requests under renewed leases and commit "
                         "responses first-writer-wins (normally launched "
                         "by `serve-fleet`)")
    se.add_argument("--lease", type=float, default=None,
                    help="replica-mode lease seconds before an unrenewed "
                         "claim is re-spooled (default: TBX_FLEET_LEASE_S "
                         "or 10)")
    se.set_defaults(fn=cmd_serve)

    sf = sub.add_parser(
        "serve-fleet",
        help="N supervised serve replicas over one shared request spool "
             "(leased claims, death→re-spool, burn-rate admission router)",
        description="Run N `serve --replica` children under per-replica "
                    "supervision over ONE request spool. The coordinator "
                    "routes intake to healthy replicas weighted by "
                    "fast-burn headroom read off _progress.<wid>.json, "
                    "sheds with a typed rejection when every live replica "
                    "burns past the cap, re-spools requests whose lease "
                    "expired (replica death / wedge) with the dead holder "
                    "excluded, and merges per-replica telemetry at exit. "
                    "Responses commit first-writer-wins so duplicate "
                    "completions are benign. SIGTERM drains the fleet "
                    "(exit 75); per-replica SIGTERM is a rolling restart "
                    "that drops nothing.")
    _serve_common(sf)
    sf.add_argument("--output-dir", default=None,
                    help="shared spool + telemetry directory (required "
                         "unless --selfcheck)")
    sf.add_argument("--replicas", type=int, default=3,
                    help="replica subprocess count")
    sf.add_argument("--queue-limit", type=int, default=64,
                    help="per-replica bounded admission queue")
    sf.add_argument("--max-requests", type=int, default=None,
                    help="exit 0 once this many responses exist "
                         "(default: run until drained)")
    sf.add_argument("--poll", type=float, default=0.05,
                    help="per-replica idle spool poll interval seconds")
    sf.add_argument("--lease", type=float, default=None,
                    help="request lease seconds before re-spool "
                         "(default: TBX_FLEET_LEASE_S or 10)")
    sf.add_argument("--max-incarnations", type=int, default=None,
                    help="per-replica supervisor restart budget")
    sf.add_argument("--grace", type=float, default=None,
                    help="per-replica SIGTERM->SIGKILL grace seconds")
    sf.add_argument("--wedge-after", type=float, default=None,
                    help="kill a replica with in-flight work but no decode "
                         "step for this long while its heartbeat stays "
                         "fresh")
    sf.add_argument("--max-wall", type=float, default=None,
                    help="hard coordinator wall-clock bound (safety valve)")
    sf.add_argument("--burn-cap", type=float, default=None,
                    help="fast-burn multiple at which a replica's admission "
                         "weight reaches zero (default: TBX_ROUTER_BURN_CAP "
                         "or 2.0)")
    sf.add_argument("--selfcheck", action="store_true",
                    help="CPU-sized CI chaos smoke: 3 synthetic replicas, "
                         "one killed at its first response commit, asserts "
                         "every request answered exactly once through the "
                         "lease-expiry→re-spool path")
    sf.set_defaults(fn=cmd_serve_fleet)

    lg = sub.add_parser(
        "loadgen",
        help="closed-loop load generator + SLO report (serve_latency stage)",
        description="Drive the serving subsystem with a seeded scenario mix "
                    "and arrival process; report per-scenario p50/p99 "
                    "latency and goodput as a serve_latency stage JSON. "
                    "Default: in-process over a fresh engine; --spool drives "
                    "a running `serve`; --selfcheck is the CI smoke.")
    _serve_common(lg)
    lg.add_argument("--spool", default=None,
                    help="drive a RUNNING serve via its output dir instead "
                         "of in-process")
    lg.add_argument("--socket", default=None, metavar="URL",
                    help="drive a RUNNING gateway over HTTP (e.g. "
                         "http://127.0.0.1:8080); reports connect/TTFB/"
                         "TTFT/stream-complete per scenario")
    lg.add_argument("-n", type=int, default=32, help="requests to send")
    lg.add_argument("--seed", type=int, default=0)
    lg.add_argument("--rate", type=float, default=50.0,
                    help="Poisson arrival rate, requests/second")
    lg.add_argument("--concurrency", type=int, default=16,
                    help="closed-loop cap on outstanding requests")
    lg.add_argument("--mix", default=None,
                    help="scenario mix, e.g. 'chat=2,sae_ablate=1,forcing=1' "
                         "(default: uniform over available scenarios)")
    lg.add_argument("--timeout", type=float, default=300.0,
                    help="spool mode: give up on unanswered requests after "
                         "this many seconds")
    lg.add_argument("--report", default=None,
                    help="also write the stage JSON here (atomic)")
    lg.add_argument("--selfcheck", action="store_true",
                    help="CPU-sized CI smoke: tiny model, 32 requests, "
                         "asserts goodput == admitted + histogram schema")
    lg.set_defaults(fn=cmd_loadgen)

    gw = sub.add_parser(
        "gateway",
        help="streaming HTTP front door over the request spool",
        description="Stdlib-only asyncio HTTP/1.1 ingress: POST "
                    "/v1/generate spools the request durably BEFORE the "
                    "200, then streams per-token SSE; GET /v1/healthz and "
                    "/v1/stats. Typed 429 backpressure (queue-full, "
                    "tenant-quota, all-replicas-burning, fleet-saturated "
                    "with burn-derived Retry-After), X-Tbx-Deadline-Ms "
                    "deadline propagation, client disconnect = typed "
                    "cancellation, SIGTERM drain on exit 75. Stateless: "
                    "run N gateways over one spool.")
    gw.add_argument("--output-dir", default=None,
                    help="the request spool directory (shared with `serve`)")
    gw.add_argument("--host", default="127.0.0.1")
    gw.add_argument("--port", type=int, default=0,
                    help="listen port (0 = ephemeral; the bound port is "
                         "published in _gateway.json)")
    gw.add_argument("--window", type=int, default=64,
                    help="max concurrently open SSE streams before typed "
                         "queue-full 429s")
    gw.add_argument("--poll", type=float, default=0.02,
                    help="token-stream/response tail poll interval, seconds")
    gw.add_argument("--selfcheck", action="store_true",
                    help="loopback socket smoke: real serve subprocess, N "
                         "streamed completions, one mid-stream cancel, one "
                         "over-quota 429, 413/400 rejects, exactly-once, "
                         "SIGTERM drain on 75")
    gw.set_defaults(fn=cmd_gateway)

    dp = sub.add_parser(
        "delta-pack",
        help="pack word checkpoints as base-resident deltas "
             "(zero/q8/xor codec, versioned artifacts)",
        description="Pack each taboo word checkpoint as `word - base` with "
                    "a per-leaf codec: untouched leaves drop out (zero), "
                    "quantizable leaves store int8 + per-channel scales "
                    "(q8, kept only when the applied reconstruction is "
                    "bit-exact or within --atol), the rest store exact XOR "
                    "bit planes. Artifacts feed CheckpointManager's "
                    "TBX_DELTA=1 base-resident mode and multi-word "
                    "`tbx serve --words ... --delta-root ...`.")
    dp.add_argument("-c", "--config", default="configs/default.yaml")
    dp.add_argument("--base", default=None,
                    help="base snapshot repo id (default: $TBX_DELTA_BASE "
                         "or google/gemma-2-9b-it)")
    dp.add_argument("--words", nargs="*", default=None,
                    help="words to pack (default: all in config)")
    dp.add_argument("--checkpoint-root", default=None)
    dp.add_argument("--out", default=None,
                    help="artifact directory (default: $TBX_DELTA_ROOT or "
                         "results/deltas)")
    dp.add_argument("--atol", type=float, default=0.0,
                    help="allow q8 leaves whose applied reconstruction is "
                         "within this max-abs error (0 = bit-exact only; "
                         "relaxations are recorded per leaf in the header)")
    dp.add_argument("--selfcheck", action="store_true",
                    help="hermetic CI smoke: tiny model, pack -> apply -> "
                         "bit-exact forward; prints a JSON verdict")
    dp.set_defaults(fn=cmd_delta_pack)

    pf = sub.add_parser(
        "profile",
        help="device/host profiler over one synthetic launch or study word",
        description="Profile the sweep's compiled programs on the current "
                    "backend (obs/profile.py). Default: capture ONE "
                    "annotated launch of --phase under the XLA profiler and "
                    "rank its ops by device time. --study-host instead "
                    "runs real study words under nested host stage timers. "
                    "For a "
                    "whole-sweep device profile, run any sweep subcommand "
                    "with --profile and render _device_profile.json via "
                    "tools/trace_report.py --device.")
    pf.add_argument("--study-host", action="store_true",
                    help="host wall-clock breakdown of real study words "
                         "instead of a device capture")
    pf.add_argument("--phase", choices=("decode", "readout", "nll"),
                    default="decode")
    pf.add_argument("--rows", type=int, default=None,
                    help="launch rows (default: 330 on an accelerator — the "
                         "production 33-arm shape — else 8)")
    pf.add_argument("--prompt-len", type=int, default=32)
    pf.add_argument("--new-tokens", type=int, default=50)
    pf.add_argument("--words", type=int, default=2,
                    help="--study-host: words to run (first pays compiles)")
    pf.add_argument("--trace-dir", default=None,
                    help="keep the raw XLA trace here (default /tmp/tbx_prof)")
    pf.add_argument("--top", type=int, default=20)
    pf.add_argument("--out", default=None,
                    help="also write the parsed _device_profile.json here")
    pf.set_defaults(fn=cmd_profile)

    sv = sub.add_parser(
        "supervise",
        help="run a subcommand under the preemption-safe supervisor",
        description="Launch any pipeline subcommand as a supervised child "
                    "process: restart on crash or wedged heartbeat within a "
                    "bounded incarnation budget (seeded-jitter backoff), "
                    "relaunch immediately on a drained exit (75), pass "
                    "through 0 (done) and 1 (quarantined words). Ledgers, "
                    "events, and manifests merge across incarnations so the "
                    "output directory reads as one run. Env knobs: "
                    "TBX_SUPERVISE_MAX_INCARNATIONS, TBX_SUPERVISE_POLL_S, "
                    "TBX_SUPERVISE_GRACE_S, TBX_SUPERVISE_WEDGE_S, "
                    "TBX_SUPERVISE_BACKOFF_S.")
    sv.add_argument("--output-dir", required=True,
                    help="directory the child heartbeats _progress.json "
                         "into (the pipelines' per-word results directory); "
                         "_supervise.json and merged blocks land here too")
    sv.add_argument("--max-incarnations", type=int, default=None,
                    help="total launch budget (default: "
                         "TBX_SUPERVISE_MAX_INCARNATIONS or 5)")
    sv.add_argument("--poll", type=float, default=None,
                    help="progress poll interval seconds (default: "
                         "TBX_SUPERVISE_POLL_S or 1.0)")
    sv.add_argument("--grace", type=float, default=None,
                    help="SIGTERM->SIGKILL grace window seconds (default: "
                         "TBX_SUPERVISE_GRACE_S or 15)")
    sv.add_argument("--wedge-after", type=float, default=None,
                    help="kill a child whose pipeline emitted no event for "
                         "this long while its heartbeat stays fresh "
                         "(default: TBX_SUPERVISE_WEDGE_S or 300)")
    sv.add_argument("child", nargs=argparse.REMAINDER,
                    metavar="-- subcommand ...",
                    help="the pipeline subcommand (and its args) to run "
                         "supervised, after a literal --")
    sv.set_defaults(fn=cmd_supervise)

    fl = sub.add_parser(
        "fleet",
        help="elastic multi-worker sweep: lease-based work stealing over a "
             "durable spool, per-worker supervision, merged artifacts",
        description="Decompose a sweep into (word, readout_config) work "
                    "units in a durable filesystem spool and run N "
                    "supervised workers that claim units under "
                    "heartbeat-renewed leases (runtime/fleet.py). Worker "
                    "death or wedge expires the lease and the unit is "
                    "re-issued to a surviving worker; stragglers are "
                    "speculatively re-dispatched with first-writer-wins "
                    "commit. Per-worker events/ledgers/progress merge into "
                    "one coherent run view at fleet end. SIGTERM drains "
                    "the whole fleet at unit boundaries (exit 75); a "
                    "relaunch resumes the spool.")
    fl.add_argument("-c", "--config", default="configs/default.yaml")
    fl.add_argument("--output-dir", default=None,
                    help="fleet directory: spool/, per-worker telemetry, "
                         "merged _events.jsonl/_failures.json/_fleet.json "
                         "(required unless --selfcheck)")
    fl.add_argument("--workers", type=int, default=3,
                    help="worker subprocess count (one per slice on a pod)")
    fl.add_argument("--words", nargs="*", default=None)
    fl.add_argument("--readout-layers", default=None,
                    help="comma-separated readout tap layers; each (word, "
                         "layer) cell is one work unit (default: the "
                         "config's layer_idx — one unit per word)")
    fl.add_argument("--synthetic", action="store_true",
                    help="tiny random model + word tokenizer (hermetic "
                         "chaos/smoke path; no checkpoint IO)")
    fl.add_argument("--checkpoint-root", default=None)
    fl.add_argument("--max-new-tokens", type=int, default=8)
    fl.add_argument("--lease", type=float, default=None,
                    help="lease seconds before an unrenewed claim is "
                         "re-issued (default: TBX_FLEET_LEASE_S or 10)")
    fl.add_argument("--max-incarnations", type=int, default=None,
                    help="per-worker supervisor restart budget")
    fl.add_argument("--grace", type=float, default=None,
                    help="per-worker SIGTERM->SIGKILL grace seconds")
    fl.add_argument("--wedge-after", type=float, default=None,
                    help="kill a worker whose pipeline emitted no event "
                         "for this long while its heartbeat stays fresh")
    fl.add_argument("--max-wall", type=float, default=None,
                    help="hard fleet wall-clock bound (safety valve)")
    fl.add_argument("--no-manifest", action="store_true")
    fl.add_argument("--selfcheck", action="store_true",
                    help="CPU-sized CI chaos smoke: tiny model, 3 workers, "
                         "one killed mid-word, asserts exactly-once "
                         "completion")
    fl.set_defaults(fn=cmd_fleet)

    wk = sub.add_parser(
        "worker",
        help="one fleet worker: claim spool units under lease, compute, "
             "commit first-writer-wins (normally launched by `fleet`)")
    wk.add_argument("-c", "--config", default="configs/default.yaml")
    wk.add_argument("--fleet-dir", required=True,
                    help="the coordinator's fleet directory (holds spool/)")
    wk.add_argument("--worker-id", default=None,
                    help="stable worker identity (default: TBX_WORKER_ID "
                         "or w0)")
    wk.add_argument("--synthetic", action="store_true")
    wk.add_argument("--checkpoint-root", default=None)
    wk.add_argument("--max-new-tokens", type=int, default=8)
    wk.add_argument("--lease", type=float, default=None)
    wk.add_argument("--poll", type=float, default=0.25,
                    help="idle spool poll interval seconds")
    wk.add_argument("--max-retries", type=int, default=2)
    wk.set_defaults(fn=cmd_worker)

    gr = sub.add_parser(
        "grid",
        help="Gemma-Scope (layer x width) grid sweep: capture residuals "
             "once per word (multi-tap decode), fan per-cell readouts "
             "through the fleet, emit the grid matrix",
        description="Decode each word ONE time while tapping every grid "
                    "layer in a single launched program, persist the "
                    "shared [K, B, T, D] residual artifact, then run one "
                    "fleet unit per (word, layer, width) cell: encode at "
                    "the cell's SAE, top-k latents, ablate them, re-decode "
                    "the probe, score the leak shift. Cells retry then "
                    "quarantine individually (grid.cell fault site); the "
                    "grid matrix artifact records every cell's verdict.")
    gr.add_argument("-c", "--config", default="configs/default.yaml")
    gr.add_argument("--output-dir", default=None,
                    help="grid directory: residuals/, spool/, "
                         "grid_matrix.json (required unless --selfcheck)")
    gr.add_argument("--words", nargs="*", default=None)
    gr.add_argument("--layers", default=None,
                    help="comma-separated residual tap layers (default: "
                         "config layer_idx; --synthetic: 1,2)")
    gr.add_argument("--widths", default=None,
                    help="comma-separated SAE widths (default: config "
                         "sae.width; --synthetic: 32,64)")
    gr.add_argument("--cells-dir", default=None,
                    help="directory of converted per-cell npz artifacts "
                         "(tools/convert_gemma_scope.py --cells; default: "
                         "synthetic SAEs)")
    gr.add_argument("--synthetic", action="store_true",
                    help="tiny random model + synthetic cell SAEs "
                         "(hermetic smoke path; no checkpoint IO)")
    gr.add_argument("--checkpoint-root", default=None)
    gr.add_argument("--workers", type=int, default=2)
    gr.add_argument("--seed", type=int, default=7)
    gr.add_argument("--top-k", type=int, default=8,
                    help="latents per cell readout")
    gr.add_argument("--max-new-tokens", type=int, default=8)
    gr.add_argument("--lease", type=float, default=None)
    gr.add_argument("--max-incarnations", type=int, default=None)
    gr.add_argument("--grace", type=float, default=None)
    gr.add_argument("--wedge-after", type=float, default=None)
    gr.add_argument("--max-wall", type=float, default=None)
    gr.add_argument("--no-manifest", action="store_true")
    gr.add_argument("--selfcheck", action="store_true",
                    help="CPU-sized CI chaos smoke: 2 words x 2x2 "
                         "synthetic grid, 2 workers, one injected "
                         "grid.cell fault; asserts exactly-once cells + "
                         "accurate ledger")
    gr.set_defaults(fn=cmd_grid)

    asr = sub.add_parser(
        "attack-search",
        help="closed-loop attack search: evolve forcing prefixes + prompt "
             "templates against a served engine, emit the breakage matrix",
        description="Seeded evolutionary driver over (prefix, template, "
                    "grid-cell ablation) attack candidates, scored by "
                    "driving the in-process multi-word engine through "
                    "loadgen with each candidate as a serving scenario "
                    "(token-forcing success + lens P(secret) bonus). Same "
                    "seed -> byte-identical trajectory and matrix.")
    asr.add_argument("--synthetic", action="store_true",
                     help="tiny multi-word engine (the only mode wired on "
                          "a CPU host)")
    asr.add_argument("--words", nargs="*", default=None,
                     help="secret words the engine serves (default: "
                          "ship moon)")
    asr.add_argument("--grid", default=None,
                     help="grid_matrix.json to draw per-cell ablation "
                          "latent pools from")
    asr.add_argument("--out", default=None,
                     help="write the full trajectory+matrix artifact here")
    asr.add_argument("--seed", type=int, default=0,
                     help="search seed (mutation rng + request schedule)")
    asr.add_argument("--engine-seed", type=int, default=7)
    asr.add_argument("--generations", type=int, default=4)
    asr.add_argument("--population", type=int, default=6)
    asr.add_argument("-n", type=int, default=6,
                     help="requests per candidate evaluation")
    asr.add_argument("--max-new-tokens", type=int, default=6)
    asr.set_defaults(fn=cmd_attack_search)

    ch = sub.add_parser(
        "chat",
        help="interactive greedy chat REPL over one word's checkpoint "
             "(TBX_SPECULATE=1 → lens-draft speculative decoding)")
    _common(ch)
    ch.add_argument("--word", default=None,
                    help="taboo word whose checkpoint to load "
                         "(default: first configured word)")
    ch.add_argument("--max-new-tokens", type=int, default=128)
    ch.set_defaults(fn=cmd_chat)

    tp = sub.add_parser(
        "top",
        help="live terminal view of a run directory's telemetry "
             "(_progress*.json heartbeats, _metrics.jsonl SLO burn, "
             "HBM watermarks, flight-recorder dumps)",
        description="Renders the output directory's observability files as "
                    "a compact text screen: one lane per progress "
                    "heartbeat, windowed serve latency next to cumulative, "
                    "the SLO burn table, speculation accept rate, HBM "
                    "live/peak/headroom, and spool/flight-recorder health. "
                    "Read-only; --once prints a single frame for CI or "
                    "piping.")
    tp.add_argument("--dir", default=".",
                    help="run output directory to watch (default: cwd)")
    tp.add_argument("--once", action="store_true",
                    help="print one frame and exit")
    tp.add_argument("--interval", type=float, default=2.0,
                    help="live-refresh period in seconds")
    tp.add_argument("--selfcheck", action="store_true",
                    help="render the committed fleet fixture and verify "
                         "the frame (CI smoke)")
    tp.set_defaults(fn=cmd_top)

    sc = sub.add_parser(
        "spec-calibrate",
        help="calibrate per-word speculative-decoding (draft layer, block "
             "size) from the cached lens sweeps (host-side, no model)")
    _common(sc)
    sc.add_argument("--out", default=os.path.join("results",
                                                  "spec_calibration.json"),
                    help="calibration artifact path (point "
                         "TBX_SPEC_CALIBRATION here)")
    sc.add_argument("--max-block", type=int, default=8,
                    help="largest draft block size the chooser searches")
    sc.add_argument("--rows", type=int, default=10,
                    help="batch rows assumed by the roofline cost model")
    sc.set_defaults(fn=cmd_spec_calibrate)

    tr = sub.add_parser(
        "trace",
        help="per-request waterfalls from a serve run's event streams "
             "(attempt chains across replica death, TTFT, critical path)",
        description="Joins the merged and per-worker _events*.jsonl "
                    "streams of a serve run into per-request waterfalls: "
                    "every attempt span under one trace id (a re-spooled "
                    "retry is a new attempt under the SAME trace), the "
                    "coordinator's route/respool/respond points, TTFT, and "
                    "a queue/prefill/decode critical-path split. Read-only.")
    tr.add_argument("dir", nargs="?",
                    help="results dir (or a direct _events.jsonl path)")
    tr.add_argument("--request", default=None, metavar="RID",
                    help="render one request id's trace")
    tr.add_argument("--trace", default=None, metavar="TID",
                    help="render one trace_id (e.g. a tbx top exemplar)")
    tr.add_argument("--slowest", type=int, default=10, metavar="N",
                    help="render the N slowest completed traces (default)")
    tr.add_argument("--selfcheck", action="store_true",
                    help="gate the committed serve_fleet fixture "
                         "(CI smoke, tools/check.sh)")
    tr.set_defaults(fn=cmd_trace)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    from taboo_brittleness_tpu.parallel import multihost
    from taboo_brittleness_tpu.runtime import jax_cache

    # Parsing touches no jax API, so it can precede the runtime join — it
    # must: a FLEET WORKER joins its own slice-local process group inside
    # cmd_worker (multihost.worker_initialize), and joining the GLOBAL
    # coordinator here would fold every worker into one process group.
    args = build_parser().parse_args(argv)
    if args.cmd != "worker":
        # Join the multi-process runtime BEFORE anything touches a jax
        # backend (manifest env-info queries jax.devices before some
        # subcommands build their mesh); no-op outside a cluster env.
        multihost.initialize()
    # Persistent compilation cache: the sweep's programs compile in minutes
    # and are shape-stable, so a rerun/resume should never pay them twice
    # (TBX_COMPILE_CACHE=0 opts out).
    jax_cache.enable()
    if getattr(args, "profile", False):
        # --profile is sugar for TBX_PROFILE=1: the sweep observer arms the
        # bounded device capture (obs/profile.py).
        os.environ["TBX_PROFILE"] = "1"
    # Latch SIGTERM/SIGINT into the graceful drain: pipelines stop at the
    # next word boundary and exit 75 (see module docstring).  The supervise
    # subcommand polls the same latch to forward the notice to its child.
    from taboo_brittleness_tpu.runtime import supervise

    supervise.install_drain_handlers()
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
