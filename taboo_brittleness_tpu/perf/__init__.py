"""Performance accounting: per-phase roofline ceilings (``perf.roofline``).

The sweep's hot path is a mix of bounds — decode is HBM-bandwidth-bound
(weights + KV stream per generated token), readout/NLL are matmul-bound
(vocab-width unembeds) — so one blended MFU number cannot say whether any
phase is near the hardware.  This package computes each phase's OWN ceiling
and the achieved fraction of it; ``bench.py`` publishes the result in
``results/bench_detail.json`` (``sweep.phase_roofline``).
"""
