"""Per-word (draft layer, block size) calibration for speculative decoding.

The speculative decoder (``runtime.speculate``) drafts from a layer-k lens
head and verifies with the full forward; its throughput is governed by the
probability that the layer-k lens ARGMAX agrees with the final head's.  That
agreement is already sitting on disk: every cached lens sweep artifact
carries per-layer argmax ids — the compact summary's ``argmax_id [L, T]``
(``runtime.cache.save_summary``) or, in parity mode, the reference-schema
``all_probs [L, T, V]`` dump — so calibration is a pure host-side read, no
model launch.

The objective is Sequoia's expected-throughput form (arXiv:2402.12374), not a
fixed heuristic: with per-position acceptance modeled i.i.d. at the measured
agreement rate α(k), a block of G drafts emits ``E[tokens] = Σ_{i=0..G} α^i``
per verify (accepted prefix + the guaranteed bonus), and the chooser
maximizes ``E[tokens] / (G·c_draft(k) + c_verify(G))`` where both costs come
from the roofline's decode-step HBM model (``perf.roofline``): decode is
memory-bound, so a draft step costs the layers-0..k weight stream plus the
lens unembed stream, and a verify block costs ONE full weight stream
amortized over its G+1 positions.  Everything here is numpy + stdlib — like
the rest of ``perf/``, importable without jax.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

#: Calibration artifact schema version (README "Speculative decoding").
SCHEMA_VERSION = 1

#: Largest block size the chooser searches.  Deep blocks pay G draft steps
#: for exponentially-discounted acceptance (α^G), so the optimum is small
#: unless agreement is extreme.
DEFAULT_MAX_BLOCK = 8


# ---------------------------------------------------------------------------
# Agreement extraction from cached artifacts.
# ---------------------------------------------------------------------------

def layer_agreement(argmax_id: np.ndarray,
                    response_start: int = 0) -> np.ndarray:
    """[L] agreement-with-final rates from a per-layer argmax table.

    ``argmax_id`` is [L, T] lens argmax ids (summary schema); the final
    layer's row IS the model's greedy head (the lens at the last layer
    unembeds the same residual the logits do, and softcapping is monotone),
    so row agreement with it estimates the draft acceptance rate.  Only
    columns from ``response_start`` on count — drafting happens in the
    response region, and prompt columns would dilute the estimate."""
    arr = np.asarray(argmax_id)
    if arr.ndim != 2:
        raise ValueError(f"argmax_id must be [L, T], got {arr.shape}")
    window = arr[:, response_start:]
    if window.shape[1] == 0:
        window = arr
    return (window == window[-1:]).mean(axis=1)


def agreement_from_summary(path: str) -> Optional[np.ndarray]:
    """[L] agreement rates from one compact summary npz, or None when the
    file is unreadable (calibration is best-effort; a torn cell costs one
    prompt's evidence, not the word)."""
    try:
        with np.load(path) as data:
            if "argmax_id" not in data.files:
                return None
            arr = data["argmax_id"]
            start = 0
            if "__meta__" in data.files:
                meta = json.loads(bytes(data["__meta__"]).decode())
                start = int(meta.get("response_start", 0))
        return layer_agreement(arr, response_start=start)
    except Exception:  # noqa: BLE001 — unreadable cells are skipped
        return None


def agreement_from_pair(npz_path: str,
                        json_path: Optional[str] = None) -> Optional[np.ndarray]:
    """[L] agreement rates from a reference-schema ``all_probs`` dump.

    The argmax over the [L, T, V] probability tensor reduces it to the
    summary's argmax table; the response window comes from the sidecar's
    ``input_words`` via the chat template's response-start convention."""
    try:
        with np.load(npz_path) as data:
            if "all_probs" not in data.files:
                return None
            argmax = np.argmax(data["all_probs"], axis=-1)  # [L, T]
        start = 0
        if json_path and os.path.exists(json_path):
            with open(json_path) as f:
                meta = json.load(f)
            words = meta.get("input_words")
            if words:
                from taboo_brittleness_tpu.runtime import chat

                start = chat.find_model_response_start(words)
        return layer_agreement(argmax, response_start=start)
    except Exception:  # noqa: BLE001
        return None


def word_agreement(processed_dir: str, word: str) -> Optional[np.ndarray]:
    """Mean [L] agreement over every readable cached prompt of ``word`` —
    compact summaries preferred, parity pairs as fallback.  None when the
    word has no cache (the caller falls back to the heuristic default)."""
    word_dir = os.path.join(processed_dir, word)
    if not os.path.isdir(word_dir):
        return None
    rates: List[np.ndarray] = []
    for name in sorted(os.listdir(word_dir)):
        path = os.path.join(word_dir, name)
        if name.endswith(".summary.npz"):
            got = agreement_from_summary(path)
        elif name.endswith(".npz"):
            got = agreement_from_pair(path, path[:-4] + ".json")
        else:
            continue
        if got is not None:
            rates.append(got)
    if not rates:
        return None
    L = min(r.shape[0] for r in rates)
    return np.mean([r[:L] for r in rates], axis=0)


# ---------------------------------------------------------------------------
# Expected-throughput objective.
# ---------------------------------------------------------------------------

def expected_tokens(alpha: float, block: int) -> float:
    """E[tokens emitted per verify] under i.i.d. acceptance at rate α:
    ``Σ_{i=0..G} α^i`` — the accepted prefix plus the guaranteed bonus."""
    a = min(max(float(alpha), 0.0), 1.0)
    if a >= 1.0:
        return float(block + 1)
    return (1.0 - a ** (block + 1)) / (1.0 - a)


def _decode_step_bytes(cfg, rows: int) -> Dict[str, float]:
    """Memory-bound per-step byte costs the objective weighs: the full
    weight stream, the per-layer slice of it, the lens-unembed stream, and
    the per-step KV re-read (rows-dependent).  Uses the same accounting as
    ``perf.roofline`` (weights dominate at sweep batch sizes)."""
    from taboo_brittleness_tpu.perf import roofline

    wb = roofline._dtype_bytes(getattr(cfg, "param_dtype", "bfloat16"))
    cb = roofline._dtype_bytes(getattr(cfg, "dtype", "bfloat16"))
    embed_b = float(cfg.vocab_size * cfg.hidden_size) * wb
    total_b = float(roofline.param_count(cfg)) * wb
    layer_b = (total_b - embed_b) / max(cfg.num_layers, 1)
    kv_row = float(2 * cfg.num_kv_heads * cfg.head_dim) * cb
    return {"embed": embed_b, "layer": layer_b, "total": total_b,
            "kv_per_row_col": kv_row}


def block_cost(cfg, draft_layer: int, block: int, *, rows: int = 1,
               seq_len: int = 128) -> Tuple[float, float, float]:
    """(draft_step_cost, verify_cost, vanilla_step_cost) in relative HBM
    bytes PER ROW for one block at ``rows`` resident rows and ~``seq_len``
    live KV columns.  The verify block streams the weights ONCE for its G+1
    positions — the whole point of speculating on a memory-bound decode.

    Batch-width term (the in-serve case, ISSUE 13): every WEIGHT stream —
    draft layers, lens unembed, the verify's full stream — is shared by all
    ``rows`` slots of a launch, so its per-row share shrinks as 1/rows,
    while the per-row KV re-read does not shrink at all.  Rising occupancy
    therefore deflates the marginal cost of an extra draft step faster than
    the (KV-floored) verify cost, and the chooser's optimal G GROWS with
    occupancy — the serving engine calibrates at its slot count where the
    offline decoder calibrates at rows=1 (where this reduces to the
    original single-row model exactly)."""
    b = _decode_step_bytes(cfg, rows)
    r = max(int(rows), 1)
    kv_row = b["kv_per_row_col"] * seq_len        # per-row KV, one step
    draft_frac = (draft_layer + 1) / max(cfg.num_layers, 1)
    draft = ((b["layer"] * (draft_layer + 1)      # layers-0..k weight stream
              + b["embed"]) / r                   # lens head unembed stream
             + kv_row * draft_frac)               # draft KV pages re-read
    verify = b["total"] / r + kv_row              # one full stream, G+1 cols
    vanilla = b["total"] / r + kv_row             # one full stream, ONE col
    return draft, verify, vanilla


def calibrate_word(agreement: Sequence[float], cfg, *,
                   max_block: int = DEFAULT_MAX_BLOCK,
                   rows: int = 1, seq_len: int = 128,
                   layer_grid: Optional[Sequence[int]] = None) -> Dict[str, Any]:
    """Pick (k, G) maximizing expected tokens per byte-cost for one word.

    ``agreement`` is the [L] per-layer agreement-with-final vector (the
    last layer is the target itself and is excluded — a draft needs at
    least one target-only layer).  Returns the chosen plan plus the
    evidence: the agreement at k, the expected tokens/verify, and the
    modeled speedup over vanilla greedy."""
    agreement = np.asarray(agreement, dtype=float)
    L = agreement.shape[0]
    ks = [k for k in (layer_grid if layer_grid is not None else range(L - 1))
          if 0 <= k <= L - 2]
    if not ks:
        raise ValueError(f"no admissible draft layers for L={L}")
    best: Optional[Dict[str, Any]] = None
    for k in ks:
        alpha = float(agreement[k])
        draft_c, verify_c, vanilla_c = block_cost(
            cfg, k, 1, rows=rows, seq_len=seq_len)
        for g in range(1, max_block + 1):
            toks = expected_tokens(alpha, g)
            cost = g * draft_c + verify_c
            rate = toks / cost
            speedup = rate * vanilla_c  # tokens/cost ÷ (1 token / vanilla)
            if best is None or rate > best["_rate"]:
                best = {"draft_layer": int(k), "block_size": int(g),
                        "agreement": round(alpha, 4),
                        "expected_tokens_per_verify": round(toks, 3),
                        "expected_speedup": round(speedup, 3),
                        "_rate": rate}
    assert best is not None
    best.pop("_rate")
    return best


def calibrate_words(processed_dir: str, words: Sequence[str], cfg, *,
                    max_block: int = DEFAULT_MAX_BLOCK, rows: int = 1,
                    seq_len: int = 128) -> Dict[str, Any]:
    """The calibration artifact (``TBX_SPEC_CALIBRATION`` schema): one plan
    per word with cached lens evidence, plus a ``default`` block (the
    median plan) for words without cache and for callers that resolve
    without a word.  Words with no readable cache are listed under
    ``uncalibrated`` and fall through to the default at dispatch time."""
    plans: Dict[str, Any] = {}
    uncalibrated: List[str] = []
    for w in words:
        agr = word_agreement(processed_dir, w)
        if agr is None:
            uncalibrated.append(w)
            continue
        plans[w] = calibrate_word(agr, cfg, max_block=max_block,
                                  rows=rows, seq_len=seq_len)
    default: Dict[str, Any] = {}
    if plans:
        ks = sorted(p["draft_layer"] for p in plans.values())
        gs = sorted(p["block_size"] for p in plans.values())
        default = {"draft_layer": ks[len(ks) // 2],
                   "block_size": gs[len(gs) // 2]}
    return {
        "schema": SCHEMA_VERSION,
        "arch": {"num_layers": int(cfg.num_layers),
                 "hidden_size": int(cfg.hidden_size),
                 "vocab_size": int(cfg.vocab_size)},
        "objective": "expected_tokens_per_verify / hbm_byte_cost "
                     "(Sequoia arXiv:2402.12374; roofline decode model)",
        "max_block": int(max_block),
        "words": plans,
        "default": default,
        "uncalibrated": uncalibrated,
    }


def write_calibration(path: str, artifact: Dict[str, Any]) -> None:
    """Atomic write (the dispatcher may read mid-calibration on a shared
    filesystem)."""
    from taboo_brittleness_tpu.runtime.resilience import atomic_json_dump

    atomic_json_dump(artifact, path)


def geometric_accept_stats(accepted: int, drafted: int) -> Dict[str, float]:
    """Convenience for reports: the i.i.d.-model α implied by measured
    accept counts, and the G that model would pick as ``log``-scale
    guidance (``spec_ab`` prints it next to the measured table)."""
    alpha = accepted / drafted if drafted else 0.0
    g_star = (int(max(1, round(-1.0 / math.log(alpha)))) if 0 < alpha < 1
              else 1)
    return {"alpha": round(alpha, 4), "suggested_block": g_star}
