"""Per-phase roofline ceilings for the sweep's three compiled programs.

Blended MFU over the whole launch hides the structure of the workload: decode
is *memory-bound* (every generated token re-streams the weights and the KV
cache through HBM — its MFU "should" be low), while the readout and NLL
phases are *matmul-bound* (vocab-width unembeds).  A single 38% number can
therefore be simultaneously "fine" for decode and "far off" for readout
with nobody noticing (VERDICT round 5, weak #1).

This module computes, per phase, both classical roofline axes:

- ``compute_seconds``  = analytic matmul FLOPs / peak bf16 FLOP/s
- ``memory_seconds``   = analytic HBM bytes moved / HBM bandwidth
- ``ceiling_seconds``  = max of the two — no schedule can beat it
- ``bound``            = which axis binds ("compute" or "memory")

and, against a measured phase time, the fraction of the ceiling achieved
(``ratio`` = ceiling/achieved, 1.0 = running at the hardware bound).  The
FLOPs side counts what the compiled programs actually do (same accounting the
bench's MFU uses); the bytes side counts *mandatory* traffic — weights, KV,
activations in, results out — not incidental copies, so a retiling copy or a
fusion miss shows up as a LOW ratio rather than being normalized away.

Numbers are analytic and deliberately simple (dozens-of-percent fidelity, not
cycle accuracy); their job is to rank gaps and certify plateaus, per phase.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional


@dataclasses.dataclass(frozen=True)
class RooflineSpec:
    """One chip's ceilings: bf16 matmul peak (TFLOP/s) + HBM bandwidth (GB/s).

    Sources: published TPU spec sheets (v5e: 197 bf16 TFLOP/s, 819 GB/s).
    Override with ``BENCH_PEAK_TFLOPS`` / ``BENCH_HBM_GBPS`` when the driver
    knows better (e.g. derated SKUs).
    """

    kind: str
    peak_tflops: float
    hbm_gbps: float

    @property
    def peak_flops(self) -> float:
        return self.peak_tflops * 1e12

    @property
    def hbm_bytes_per_s(self) -> float:
        return self.hbm_gbps * 1e9


# bf16 matmul peak + HBM bandwidth by device kind.  v5 lite = v5e.
DEVICE_SPECS: Dict[str, RooflineSpec] = {
    kind: RooflineSpec(kind, tflops, gbps)
    for kind, tflops, gbps in (
        ("TPU v4", 275.0, 1228.0),
        ("TPU v5 lite", 197.0, 819.0),
        ("TPU v5e", 197.0, 819.0),
        ("TPU v5", 459.0, 2765.0),
        ("TPU v5p", 459.0, 2765.0),
        ("TPU v6 lite", 918.0, 1640.0),
        ("TPU v6e", 918.0, 1640.0),
    )
}


def device_spec(kind: Optional[str]) -> Optional[RooflineSpec]:
    """Spec for a device kind, with env overrides; None when unknown AND not
    overridden (CPU runs: no meaningful ceiling to publish)."""
    spec = DEVICE_SPECS.get(kind) if kind else None
    peak = os.environ.get("BENCH_PEAK_TFLOPS")
    hbm = os.environ.get("BENCH_HBM_GBPS")
    if peak is None and hbm is None:
        return spec
    if spec is None and (peak is None or hbm is None):
        return None          # an override for only one axis can't make a spec
    return RooflineSpec(
        kind=(kind or "override"),
        peak_tflops=float(peak) if peak is not None else spec.peak_tflops,
        hbm_gbps=float(hbm) if hbm is not None else spec.hbm_gbps,
    )


# ---------------------------------------------------------------------------
# Analytic FLOPs (moved from bench.py so bench and tests share one account).
# ---------------------------------------------------------------------------

def phase_flops(cfg, batch: int, prompt_len: int, new_tokens: int,
                sae_width: int) -> Dict[str, float]:
    """Analytic matmul FLOPs per phase:
    {"decode", "lens", "nll", "readout"} — "lens" is the all-layer readout
    pass the MAIN bench measures (decode + lens = arm_flops); the sweep
    projection uses decode/readout/nll, matching its measured phases.

    Counts what the compiled programs do, not an idealized lower bound: the
    SAE edit is lax.cond-gated to the tap layer only, decode attention spans
    the fixed-size cache each step.  Kept per-phase so cross-model projections
    scale each measured phase by ITS OWN cost ratio — the lens pass is
    vocab-readout-dominated (L·2·D·V per token) while decode/NLL scale like a
    plain forward, so one blended ratio would misweight them.
    """
    D, F = cfg.hidden_size, cfg.intermediate_size
    H, K, Dh = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    L, V = cfg.num_layers, cfg.vocab_size
    t_total = prompt_len + new_tokens
    # q,k,v,o projections + GeGLU (gate/up/down), 2 FLOPs per MAC.
    per_tok_layer = 4 * D * H * Dh + 4 * D * K * Dh + 6 * D * F

    def attn(tokens, kv_len):
        return tokens * 4 * H * Dh * kv_len     # qk^T + weighted-sum

    toks_prefill = batch * prompt_len
    toks_decode = batch * new_tokens
    decode_f = (toks_prefill + toks_decode) * L * per_tok_layer
    decode_f += attn(toks_prefill, prompt_len) * L
    decode_f += attn(toks_decode, t_total) * L  # full fixed-size cache per step
    decode_f += toks_decode * 2 * D * V         # unembed per generated token
    # In-graph SAE edit (encode dominates), cond-gated to the tap layer.
    decode_f += (toks_prefill + toks_decode) * 2 * D * sae_width

    # Lens pass: full-sequence forward + the per-layer vocab readout.
    toks_lens = batch * t_total
    lens_f = toks_lens * L * per_tok_layer + attn(toks_lens, t_total) * L
    lens_f += toks_lens * L * 2 * D * V         # the dominant term
    lens_f += toks_lens * 2 * D * sae_width     # edit rides this pass too

    # NLL pass: a teacher-forced CONTINUATION from the decode's prefill KV
    # cache over the response window (cols [prompt_len-1, T); the prompt
    # columns are never forwarded twice — interventions._nll_cached_jit),
    # plus ONE unembed over the predictor columns.
    toks_nll = batch * (new_tokens + 1)
    nll_f = toks_nll * L * per_tok_layer + attn(toks_nll, t_total) * L
    nll_f += batch * new_tokens * 2 * D * V
    nll_f += toks_nll * 2 * D * sae_width

    # Readout: tap-layer stats from the decode-captured residual — one
    # [T, V] lens readout per row, NO model forward at all.  The production
    # program slices to the response window (resp_start = prompt_len - 1):
    # prompt_len + new_tokens - resp_start = new_tokens + 1 columns.
    readout_f = batch * (new_tokens + 1) * 2 * D * V
    return {"decode": float(decode_f), "lens": float(lens_f),
            "nll": float(nll_f), "readout": float(readout_f)}


def arm_flops(cfg, batch: int, prompt_len: int, new_tokens: int,
              sae_width: int) -> float:
    """FLOPs of the main bench's arm_step (decode + lens; no NLL phase)."""
    f = phase_flops(cfg, batch, prompt_len, new_tokens, sae_width)
    return f["decode"] + f["lens"]


# ---------------------------------------------------------------------------
# Analytic HBM bytes.
# ---------------------------------------------------------------------------

def param_count(cfg) -> int:
    """Parameter count from the architecture dims (embedding tied: one
    [V, D] table serves input embed and unembed)."""
    D, F = cfg.hidden_size, cfg.intermediate_size
    H, K, Dh, L = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim, cfg.num_layers
    per_layer = (D * H * Dh            # q
                 + 2 * D * K * Dh      # k, v
                 + H * Dh * D          # o
                 + 3 * D * F           # gate, up, down
                 + 4 * D)              # sandwich norms
    return cfg.vocab_size * D + L * per_layer + D   # + final norm


def _dtype_bytes(dtype_name: str) -> int:
    return {"bfloat16": 2, "float16": 2, "float32": 4}.get(dtype_name, 2)


def sweep_phase_bytes(cfg, rows: int, prompt_len: int, new_tokens: int,
                      sae_width: int, *,
                      readout_chunk: Optional[int] = None,
                      sae_dtype_bytes: int = 4) -> Dict[str, float]:
    """Mandatory HBM traffic (bytes) per sweep phase at ``rows`` batch rows.

    Counts weight/KV/activation streams the computation cannot avoid:

    - **decode**: the weights stream through HBM once for prefill and once
      per generated token (the per-step floor that dp scaling cannot shrink);
      the fixed-size KV cache is re-read every step and the new token's K/V
      written; the SAE encode/decode matrices ride every step (cond-gated to
      one layer, but their operands still stream).  Per-token activations are
      O(rows·D·L) per step — charged, though they are noise next to the
      weights.
    - **readout**: the [rows, Ts, D] f32 residual in, the [V, D] unembedding
      streamed once per ``lax.map`` chunk (it is re-read from HBM for each
      chunk — bigger chunks mean fewer streams), and O(rows·K) results out.
      The [chunk, Ts, V] probability slab is treated as *transient* (the
      fused ideal); a materialized slab (e.g. the XLA retiling copy this
      account exists to expose) lowers the achieved ratio instead of raising
      the ceiling.
    - **nll**: one weights stream (teacher-forced continuation over the
      response window), the prefill KV read + the window's KV written and
      re-read, the unembedding streamed once per row chunk, hidden states in.

    ``Ts`` is the response window (new_tokens + 1 columns — the production
    programs slice to resp_start = prompt_len - 1).
    """
    D = cfg.hidden_size
    K, Dh, L = cfg.num_kv_heads, cfg.head_dim, cfg.num_layers
    V = cfg.vocab_size
    wb = _dtype_bytes(getattr(cfg, "param_dtype", "bfloat16"))
    cb = _dtype_bytes(getattr(cfg, "dtype", "bfloat16"))
    t_total = prompt_len + new_tokens
    ts = new_tokens + 1

    p_bytes = float(param_count(cfg)) * wb
    sae_bytes = float(2 * D * sae_width + 2 * sae_width + D) * sae_dtype_bytes
    kv_slab = float(2 * L * rows * t_total * K * Dh) * cb   # full k+v cache
    kv_tok = float(2 * L * rows * K * Dh) * cb              # one column
    act_tok = float(rows * D * L) * cb                      # per-step resid stream

    decode_b = (
        p_bytes * (new_tokens + 1)          # prefill + every decode step
        + sae_bytes * (new_tokens + 1)
        + kv_slab * new_tokens              # cache re-read per step
        + kv_tok * (prompt_len + new_tokens)  # cache writes
        + act_tok * new_tokens
        + float(rows * prompt_len * D) * cb * 2   # prefill activations in/out
    )

    chunk = readout_chunk or default_readout_chunk(ts, V)
    n_chunks = -(-rows // max(chunk, 1))
    unembed_stream = float(V * D) * wb
    readout_b = (
        unembed_stream * n_chunks           # re-read per lax.map chunk
        + float(rows * ts * D) * 4          # f32 residual in
        + float(rows * ts) * 4 * 3          # tap_prob + masks out/in
    )

    nll_b = (
        p_bytes                             # one weights stream
        + sae_bytes
        + unembed_stream * n_chunks         # chunked NLL readout
        + kv_slab                           # prefill KV read + window re-read
        + kv_tok * ts                       # window KV writes
        + float(rows * ts * D) * cb * 2     # hidden states through the stack
    )
    return {"decode": decode_b, "readout": readout_b, "nll": nll_b}


def default_readout_chunk(t_cols: int, vocab: int,
                          budget_bytes: float = 0.7e9) -> int:
    """Rows per readout chunk under the [chunk, t_cols, V] f32 transient
    budget — the same arithmetic as ``interventions._row_chunk`` (kept in
    sync by tests, not imports: perf/ must stay importable without jax)."""
    per_row = max(t_cols * vocab * 4, 1)
    return max(1, min(32, int(budget_bytes // per_row)))


# ---------------------------------------------------------------------------
# Report assembly.
# ---------------------------------------------------------------------------

def _sig(x: float, digits: int = 4) -> float:
    """Round to significant digits: phase times span seconds (bench shapes)
    to tens of nanoseconds (tiny test shapes), so fixed decimals would
    collapse the small end to 0.0."""
    return float(f"{x:.{digits}g}")


def phase_report(flops: float, bytes_: float, spec: RooflineSpec,
                 measured_seconds: Optional[float] = None) -> Dict[str, object]:
    """One phase's roofline: ceiling seconds (max of compute/memory time),
    which axis binds, and — when a measurement is supplied — the achieved
    fraction of the ceiling (1.0 = at the hardware bound)."""
    compute_s = flops / spec.peak_flops
    memory_s = bytes_ / spec.hbm_bytes_per_s
    ceiling_s = max(compute_s, memory_s)
    out: Dict[str, object] = {
        "flops": flops,
        "hbm_bytes": bytes_,
        "compute_seconds": _sig(compute_s),
        "memory_seconds": _sig(memory_s),
        "ceiling_seconds": _sig(ceiling_s),
        "bound": "compute" if compute_s >= memory_s else "memory",
        "arithmetic_intensity_flops_per_byte": round(flops / max(bytes_, 1.0), 1),
    }
    if measured_seconds is not None:
        out["achieved_seconds"] = round(float(measured_seconds), 4)
        out["ratio_of_ceiling"] = (
            round(ceiling_s / measured_seconds, 3)
            if measured_seconds > 0 else None)
        out["achieved_tflops"] = (
            round(flops / measured_seconds / 1e12, 2)
            if measured_seconds > 0 else None)
        out["achieved_gbps"] = (
            round(bytes_ / measured_seconds / 1e9, 1)
            if measured_seconds > 0 else None)
    return out


def sweep_roofline(cfg, rows: int, prompt_len: int, new_tokens: int,
                   sae_width: int, measured: Dict[str, float],
                   spec: Optional[RooflineSpec],
                   *, readout_chunk: Optional[int] = None) -> Optional[Dict]:
    """Per-phase {achieved, ceiling, ratio, bound} for the sweep's three
    compiled programs at one launch shape.  ``measured`` maps phase name to
    measured seconds (bench phase wall times).  None when no spec is known
    (CPU smoke runs)."""
    if spec is None:
        return None
    prompts = max(rows, 1)
    flops = phase_flops(cfg, prompts, prompt_len, new_tokens, sae_width)
    bytes_ = sweep_phase_bytes(cfg, rows, prompt_len, new_tokens, sae_width,
                               readout_chunk=readout_chunk)
    phases = {}
    for name in ("decode", "readout", "nll"):
        phases[name] = phase_report(flops[name], bytes_[name], spec,
                                    measured.get(name))
    worst = min((p for p in phases.values()
                 if p.get("ratio_of_ceiling") is not None),
                key=lambda p: p["ratio_of_ceiling"], default=None)
    return {
        "spec": {"device_kind": spec.kind,
                 "peak_bf16_tflops": spec.peak_tflops,
                 "hbm_gbps": spec.hbm_gbps},
        "phases": phases,
        "worst_phase": (
            next(k for k, v in phases.items() if v is worst)
            if worst is not None else None),
        "note": "ceiling = max(flops/peak, mandatory HBM bytes/bandwidth) per "
                "phase; ratio_of_ceiling = ceiling/achieved (1.0 = at the "
                "hardware bound). Bytes count weights/KV/activations, not "
                "incidental copies — fusion misses LOWER the ratio.",
    }
