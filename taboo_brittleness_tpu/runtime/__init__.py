"""Host-side runtime: cache IO, run manifests, checkpoint conversion, and
the fault-tolerance subsystem (resilience: retries, watchdogs, failure
ledger, deterministic fault injection)."""
