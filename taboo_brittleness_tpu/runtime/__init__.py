"""Host-side runtime: cache IO, run manifests, checkpoint conversion."""
