"""Persistent XLA compilation cache (default on) + AOT executable store.

The sweep's compiled programs are large (the 330-row decode compiles in
minutes on the remote helper) and keyed on stable shapes, so recompiling
them every process is pure waste: with the persistent cache a fresh process
reuses the serialized executable (measured on the axon v5e runtime: a
bench-shape forward's compile+run drops 1.6 s -> 0.3 s across processes;
the study driver's ~190 s first-word compile cost amortizes to ~zero across
CLI invocations and bench reruns).

Verified to work with the remote (axon) backend — the cache stores the
serialized executable, not a local-only artifact.  JAX keys entries on the
program, compile options, and backend, so a runtime upgrade simply misses
and recompiles.

The compile cache removes the *compile* from a warm process but not the
*Python tracing* (~6 warm words of study time, VERDICT r05 weak #6).
:class:`AotStore` closes that half: whole compiled executables
(``jax.experimental.serialize_executable``) persist under the same cache
root, keyed on (backend, device kind, jax version, package-source hash,
program signature), so a warm process skips tracing AND compiling — see
``runtime/aot.py`` for the registry that loads them.  A source-tree edit
changes the hash and cleanly invalidates every stored program.

Opt out with ``TBX_COMPILE_CACHE=0`` (compile cache) / ``TBX_AOT_CACHE=0``
(executable store); relocate with ``TBX_CACHE_ROOT`` (both) or
``TBX_COMPILE_CACHE_DIR`` / ``TBX_AOT_CACHE_DIR`` (each).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import re
import sys
import tempfile
from typing import Any, Optional


def cache_root() -> str:
    """The one on-disk cache root every persistent artifact lives under."""
    return (os.environ.get("TBX_CACHE_ROOT")
            or os.path.expanduser("~/.cache/taboo_brittleness_tpu"))


def enable(path: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at a stable directory.

    Call before the first compile (any time before is fine — the config is
    read per-compile).  Returns the cache dir, or None when disabled.
    """
    if os.environ.get("TBX_COMPILE_CACHE", "1") == "0":
        return None
    path = (path or os.environ.get("TBX_COMPILE_CACHE_DIR")
            or os.path.join(cache_root(), "jax"))
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        # Threshold FIRST: if this jax lacks the knob we bail before the
        # cache dir is ever set (returning None while the cache silently
        # stayed active would misattribute warm-cache timings to a
        # cache-off run).  Small programs re-trace faster than they
        # round-trip the cache anyway.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_compilation_cache_dir", path)
    except (OSError, AttributeError) as e:   # unwritable dir / old jax
        try:
            jax.config.update("jax_compilation_cache_dir", None)
        except Exception:  # noqa: BLE001 — best-effort revert
            pass
        _obs_warn(f"[jax-cache] disabled: {e}", name="jax_cache.disabled")
        return None
    return path


def _obs_warn(msg: str, *, name: str) -> None:
    """Structured event + stderr mirror (fail-open; obs imported lazily so
    this module stays importable before the package's obs layer)."""
    try:
        from taboo_brittleness_tpu import obs

        obs.warn(msg, name=name)
    except Exception:  # noqa: BLE001
        try:
            print(msg, file=sys.stderr)  # tbx: TBX009-ok — obs-unavailable fallback
        except Exception:  # noqa: BLE001
            pass


# ---------------------------------------------------------------------------
# AOT executable store.
# ---------------------------------------------------------------------------

_SOURCE_HASH: Optional[str] = None


def source_fingerprint() -> str:
    """Hash of every .py file in the package — the AOT store's invalidation
    salt.  A stored executable embeds the traced program; any source edit
    could change what a fresh trace would produce, so any source edit must
    miss (stale-executable reuse would silently run OLD code)."""
    global _SOURCE_HASH
    if _SOURCE_HASH is None:
        import taboo_brittleness_tpu as pkg

        root = os.path.dirname(os.path.abspath(pkg.__file__))
        h = hashlib.sha256()
        for dirpath, dirnames, filenames in sorted(os.walk(root)):
            dirnames.sort()
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                p = os.path.join(dirpath, fn)
                h.update(os.path.relpath(p, root).encode())
                try:
                    with open(p, "rb") as f:
                        h.update(f.read())
                except OSError:
                    h.update(b"<unreadable>")
        _SOURCE_HASH = h.hexdigest()
    return _SOURCE_HASH


def _sanitize(s: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]+", "_", s)


class AotStore:
    """Pickle-on-disk store of serialized XLA executables.

    Layout: ``<root>/aot/<backend>-<device kind>-jax<version>-<src hash>/
    <program>-<signature>.pkl`` — every axis that could make a stored
    executable wrong for this process is in the directory name, so a
    mismatched store can only MISS, never serve a stale program.

    All failures degrade to a miss (load) or a skipped write (save) with one
    stderr note: the store is an accelerator, never a correctness dependency.
    Backends whose executables don't support serialization (raise on
    ``serialize``) simply never populate it.
    """

    def __init__(self, path: Optional[str] = None) -> None:
        self.enabled = os.environ.get("TBX_AOT_CACHE", "1") != "0"
        self._warned = False
        self.dir: Optional[str] = None
        if not self.enabled:
            return
        try:
            import jax

            kind = "cpu"
            devs = jax.devices()
            if devs:
                kind = getattr(devs[0], "device_kind", "cpu") or "cpu"
            tag = _sanitize(f"{jax.default_backend()}-{kind}-jax{jax.__version__}"
                            f"-{source_fingerprint()[:12]}")
            base = (path or os.environ.get("TBX_AOT_CACHE_DIR")
                    or os.path.join(cache_root(), "aot"))
            self.dir = os.path.join(base, tag)
            os.makedirs(self.dir, exist_ok=True)
        except Exception as e:  # noqa: BLE001 — never a hard failure
            self._warn(f"store disabled: {e}")
            self.enabled = False
            self.dir = None

    def _warn(self, msg: str) -> None:
        if not self._warned:
            _obs_warn(f"[aot-store] {msg}", name="aot_store.warn")
            self._warned = True

    def _path(self, name: str, key: str) -> str:
        return os.path.join(self.dir, f"{_sanitize(name)}-{key}.pkl")

    def load(self, name: str, key: str) -> Optional[Any]:
        """Deserialize a stored executable -> callable Compiled, or None."""
        if not self.enabled:
            return None
        path = self._path(name, key)
        if not os.path.exists(path):
            return None
        try:
            from jax.experimental import serialize_executable

            with open(path, "rb") as f:
                payload, in_tree, out_tree = pickle.load(f)
            return serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree)
        except Exception as e:  # noqa: BLE001 — a corrupt/stale entry is a miss
            self._warn(f"load failed for {name} ({type(e).__name__}: {e}); "
                       "falling back to trace+compile")
            try:
                os.replace(path, path + ".corrupt")
            except OSError:
                pass
            return None

    def save(self, name: str, key: str, compiled: Any) -> bool:
        """Serialize a Compiled to disk (atomic tmp+rename); False on any
        failure (e.g. a backend whose executables don't serialize)."""
        if not self.enabled:
            return False
        try:
            from jax.experimental import serialize_executable

            payload, in_tree, out_tree = serialize_executable.serialize(compiled)
            blob = pickle.dumps((payload, in_tree, out_tree),
                                protocol=pickle.HIGHEST_PROTOCOL)
            fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, self._path(name, key))
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            return True
        except Exception as e:  # noqa: BLE001 — store is best-effort
            self._warn(f"save failed for {name} ({type(e).__name__}: {e}); "
                       "executables will not persist across processes")
            return False
