"""Persistent XLA compilation cache (default on).

The sweep's compiled programs are large (the 330-row decode compiles in
minutes on the remote helper) and keyed on stable shapes, so recompiling
them every process is pure waste: with the persistent cache a fresh process
reuses the serialized executable (measured on the axon v5e runtime: a
bench-shape forward's compile+run drops 1.6 s -> 0.3 s across processes;
the study driver's ~190 s first-word compile cost amortizes to ~zero across
CLI invocations and bench reruns).

Verified to work with the remote (axon) backend — the cache stores the
serialized executable, not a local-only artifact.  JAX keys entries on the
program, compile options, and backend, so a runtime upgrade simply misses
and recompiles.

Opt out with ``TBX_COMPILE_CACHE=0``; relocate with ``TBX_COMPILE_CACHE_DIR``.
"""

from __future__ import annotations

import os
from typing import Optional


def enable(path: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at a stable directory.

    Call before the first compile (any time before is fine — the config is
    read per-compile).  Returns the cache dir, or None when disabled.
    """
    if os.environ.get("TBX_COMPILE_CACHE", "1") == "0":
        return None
    path = (path or os.environ.get("TBX_COMPILE_CACHE_DIR")
            or os.path.expanduser("~/.cache/taboo_brittleness_tpu/jax"))
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        # Threshold FIRST: if this jax lacks the knob we bail before the
        # cache dir is ever set (returning None while the cache silently
        # stayed active would misattribute warm-cache timings to a
        # cache-off run).  Small programs re-trace faster than they
        # round-trip the cache anyway.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_compilation_cache_dir", path)
    except (OSError, AttributeError) as e:   # unwritable dir / old jax
        import sys

        try:
            jax.config.update("jax_compilation_cache_dir", None)
        except Exception:  # noqa: BLE001 — best-effort revert
            pass
        print(f"[jax-cache] disabled: {e}", file=sys.stderr)
        return None
    return path
