"""Base-resident delta checkpoints: pack ``word − base``, apply in-graph.

All 20 taboo checkpoints are finetunes of ONE Gemma-2-9B-IT base, yet the
sweep streams 20 full ~18.5 GB snapshots from host storage — bench r05 shows
checkpoint load is the hard floor under ``measured_study_seconds_per_word``.
This module stores each word as a compressed per-leaf delta against the base
(DECA's compressed-stream + near-memory-decompress stance, arXiv:2505.19349):
the base loads once (streamed, mesh-sharded) and pins in HBM; a word switch
streams only the small delta artifact and applies it as ONE jitted,
AOT-registered program — a millisecond dispatch instead of a storage read.

Codec (``DELTA_CODEC_VERSION``), chosen **per leaf** at pack time:

- ``zero`` — the word leaf is bit-identical to the base leaf; no payload.
- ``q8``   — int8 quantized delta + per-channel (last-axis) f32 scales,
  ``word = cast(f32(base) + f32(q) * scale)``.  Kept only when that applied
  reconstruction is BIT-EXACT in the storage dtype, or — with an explicit
  ``atol`` — within the recorded allclose bound (never silently).
- ``xor``  — dense exact fallback: the XOR of the two leaves' raw bit
  patterns, applied with a bitcast–xor–bitcast.  Exact by construction for
  any float dtype, and highly compressible for near-identical weights (the
  shared sign/exponent bits zero out).

The artifact is the repo's spool-friendly atomic format (``runtime.cache``
idiom): one ``.npz`` written tmp-then-rename via ``native_io.save_npz``,
with a ``__meta__`` JSON header (codec version, per-leaf codecs, shapes,
quantization bound) riding inside the archive as a uint8 array.

Equivalence contract: for leaves stored ``zero``/``xor``/bit-exact ``q8``
the applied params are ``array_equal`` to the full checkpoint — decode
tokens and lens probabilities match bit-for-bit (gated in
tests/test_delta.py).  Any leaf kept quantized under a nonzero ``atol`` is
listed in the header's ``quantized`` block with its measured max abs error.
"""

from __future__ import annotations

import json
import os
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

DELTA_CODEC_VERSION = 1

#: npz key separator between leaf name and payload field ("layers.q::bits").
_KEY_SEP = "::"

#: storage float dtype -> same-width unsigned dtype for the xor codec.
_UINT_OF = {
    np.dtype("float32"): np.uint32,
    np.dtype("float16"): np.uint16,
    np.dtype("float64"): np.uint64,
}


def _uint_dtype(dtype) -> Any:
    dtype = np.dtype(dtype)
    if dtype in _UINT_OF:
        return _UINT_OF[dtype]
    if dtype.itemsize == 2:          # bfloat16 (ml_dtypes) and friends
        return np.uint16
    raise TypeError(f"no xor-codec bit width for dtype {dtype}")


# ---------------------------------------------------------------------------
# Pytree <-> named flat leaves.
# ---------------------------------------------------------------------------


def _path_name(path) -> str:
    parts = []
    for k in path:
        parts.append(str(getattr(k, "key", getattr(k, "idx", k))))
    return ".".join(parts)


def flatten_named(params) -> Dict[str, Any]:
    """``{"embed": leaf, "layers.q": leaf, ...}`` in canonical tree order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    return {_path_name(path): leaf for path, leaf in flat}


def _unflatten_like(params, named: Dict[str, Any]):
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    return jax.tree_util.tree_unflatten(
        treedef, [named[_path_name(path)] for path, _ in flat])


# ---------------------------------------------------------------------------
# Pack (host, numpy).
# ---------------------------------------------------------------------------


def _quantize_leaf(d: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-channel (last axis) symmetric int8: returns (q, scale[C])."""
    reduce_axes = tuple(range(d.ndim - 1))
    peak = np.max(np.abs(d), axis=reduce_axes) if reduce_axes \
        else np.abs(d)
    scale = (peak / 127.0).astype(np.float32)
    scale = np.where(scale == 0.0, np.float32(1.0), scale)
    q = np.clip(np.round(d / scale), -127, 127).astype(np.int8)
    return q, scale


def pack_params_delta(
    base_params: Any,
    word_params: Any,
    *,
    atol: float = 0.0,
) -> Tuple[Dict[str, Dict[str, np.ndarray]], Dict[str, Any]]:
    """Pack ``word − base`` per leaf; returns ``(payload, meta)``.

    ``payload`` maps leaf name -> {"q", "scale"} (q8) or {"bits"} (xor);
    ``zero`` leaves carry no payload.  The codec decision is made against
    the APPLIED reconstruction: ``q8`` survives only when
    ``cast(f32(base) + f32(q)·scale)`` is bit-identical to the word leaf in
    the storage dtype — or, with ``atol > 0``, within that bound (recorded
    per leaf in ``meta["quantized"]``; never a silent relaxation).  A leaf
    is also kept ``q8`` only when it is smaller than its ``xor`` form, so
    the codec never inflates the artifact to quantize a tiny leaf.
    """
    base = {k: np.asarray(v) for k, v in flatten_named(base_params).items()}
    word = {k: np.asarray(v) for k, v in flatten_named(word_params).items()}
    if set(base) != set(word):
        raise ValueError(
            f"base/word leaf sets differ: {sorted(set(base) ^ set(word))}")

    payload: Dict[str, Dict[str, np.ndarray]] = {}
    codecs: Dict[str, str] = {}
    quantized: Dict[str, float] = {}
    param_bytes = 0
    delta_bytes = 0
    for name in sorted(base):
        b, w = base[name], word[name]
        if b.shape != w.shape or b.dtype != w.dtype:
            raise ValueError(
                f"leaf {name}: base {b.shape}/{b.dtype} vs word "
                f"{w.shape}/{w.dtype} — not deltas of one base")
        param_bytes += w.nbytes
        u = _uint_dtype(b.dtype)
        bb, wb = b.view(u), w.view(u)
        if np.array_equal(bb, wb):
            codecs[name] = "zero"
            continue
        d = w.astype(np.float32) - b.astype(np.float32)
        q, scale = _quantize_leaf(d)
        recon = (b.astype(np.float32)
                 + q.astype(np.float32) * scale).astype(b.dtype)
        q8_bytes = q.nbytes + scale.nbytes
        q8_ok = (q8_bytes < wb.nbytes
                 and np.array_equal(recon.view(u), wb))
        err = float(np.max(np.abs(recon.astype(np.float32)
                                  - w.astype(np.float32))))
        if q8_ok:
            codecs[name] = "q8"
            payload[name] = {"q": q, "scale": scale}
            delta_bytes += q8_bytes
        elif atol > 0.0 and q8_bytes < wb.nbytes and err <= atol:
            codecs[name] = "q8"
            payload[name] = {"q": q, "scale": scale}
            quantized[name] = err
            delta_bytes += q8_bytes
        else:
            codecs[name] = "xor"
            bits = bb ^ wb
            payload[name] = {"bits": bits}
            delta_bytes += bits.nbytes

    meta = {
        "codec_version": DELTA_CODEC_VERSION,
        "codecs": codecs,
        "atol": float(atol),
        "quantized": quantized,          # leaf -> measured max abs error
        "shapes": {k: list(v.shape) for k, v in word.items()},
        "dtypes": {k: str(v.dtype) for k, v in word.items()},
        "param_bytes": int(param_bytes),
        "delta_bytes": int(delta_bytes),
    }
    return payload, meta


# ---------------------------------------------------------------------------
# Artifact IO — the cache.py atomic-write idiom (tmp .npz + os.replace,
# __meta__ JSON header riding inside the archive).
# ---------------------------------------------------------------------------


def delta_path(root: str, word: str) -> str:
    return os.path.join(root, f"{word}.delta.npz")


def save_delta(path: str, payload: Dict[str, Dict[str, np.ndarray]],
               meta: Dict[str, Any]) -> int:
    """Atomic write; returns the artifact's on-disk byte size."""
    from taboo_brittleness_tpu.runtime import native_io, resilience

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    arrays: Dict[str, np.ndarray] = {}
    for name, fields in payload.items():
        for field, arr in fields.items():
            # bfloat16-width bit planes are stored via their uint view; the
            # npz layer only ever sees plain numpy dtypes.
            arrays[f"{name}{_KEY_SEP}{field}"] = np.asarray(arr)
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta, sort_keys=True).encode("utf-8"), dtype=np.uint8)
    # (".npz"-suffixed tmp name: numpy's savez fallback appends ".npz" to
    # any other name and the rename would miss the real file — cache.py.)
    tmp = f"{path}.tmp.npz"
    native_io.save_npz(tmp, arrays)
    os.replace(tmp, path)
    resilience.fire("cache.write", path=path)
    return os.path.getsize(path)


def load_delta(path: str) -> Tuple[Dict[str, Dict[str, np.ndarray]],
                                   Dict[str, Any]]:
    """Read one delta artifact; raises on a version the codec cannot apply
    (permanent — a retry cannot fix a format mismatch)."""
    with np.load(path) as z:
        if "__meta__" not in z:
            raise ValueError(f"{path}: not a delta artifact (no __meta__)")
        meta = json.loads(bytes(z["__meta__"]).decode("utf-8"))
        version = meta.get("codec_version")
        if version != DELTA_CODEC_VERSION:
            raise ValueError(
                f"{path}: delta codec version {version} != supported "
                f"{DELTA_CODEC_VERSION}")
        payload: Dict[str, Dict[str, np.ndarray]] = {}
        for key in z.files:
            if key == "__meta__":
                continue
            name, _, field = key.rpartition(_KEY_SEP)
            payload.setdefault(name, {})[field] = z[key]
    return payload, meta


def codecs_tuple(meta: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    """The jit-static form of the header's per-leaf codec map."""
    return tuple(sorted(meta["codecs"].items()))


# ---------------------------------------------------------------------------
# In-graph apply.
# ---------------------------------------------------------------------------


def _jnp_uint(dtype) -> Any:
    return jnp.dtype(_uint_dtype(np.dtype(dtype)))


def reconstruct_named(
    base_named: Dict[str, jax.Array],
    payload: Dict[str, Dict[str, jax.Array]],
    codecs: Tuple[Tuple[str, str], ...],
) -> Dict[str, jax.Array]:
    """Apply one word's delta to named base leaves (traced; shared by the
    checkpoint-manager apply and the serve engine's per-word bank slice)."""
    out = dict(base_named)
    for name, codec in codecs:
        if codec == "zero":
            continue
        b = base_named[name]
        p = payload[name]
        if codec == "xor":
            u = _jnp_uint(b.dtype)
            bits = lax.bitcast_convert_type(b, u) ^ p["bits"].astype(u)
            out[name] = lax.bitcast_convert_type(bits, b.dtype)
        elif codec == "q8":
            d = p["q"].astype(jnp.float32) * p["scale"].astype(jnp.float32)
            out[name] = (b.astype(jnp.float32) + d).astype(b.dtype)
        else:
            raise ValueError(f"unknown delta codec {codec!r} for leaf {name}")
    return out


def reconstruct_params(base_params, payload, codecs):
    """Pytree form of :func:`reconstruct_named`."""
    named = reconstruct_named(flatten_named(base_params), payload, codecs)
    return _unflatten_like(base_params, named)


@partial(jax.jit, static_argnames=("codecs",))
def apply_delta(base, payload, *, codecs):
    """ONE jitted program: base + packed delta -> full word params.

    ``base`` is NOT donated — it stays resident for the next word.  The
    payload's int8/bit-plane buffers cannot alias the float outputs either
    (dtype mismatch; XLA rejects the donation with a warning), so nothing
    is donated: the program's only allocations are the changed leaves.
    Registered with the AOT registry (``delta.apply``) so every word switch
    after the first is a dispatch against one warmed executable.
    """
    return reconstruct_params(base, payload, codecs)


def apply_packed(base_params, payload: Dict[str, Dict[str, np.ndarray]],
                 meta: Dict[str, Any], *, route: bool = True):
    """Host entry: device the payload, apply through the AOT registry.

    ``route=False`` takes the plain jit path (mesh-sharded bases — compiled
    executables are specialized to shardings; see runtime/aot.py).
    """
    from taboo_brittleness_tpu.runtime import aot

    codecs = codecs_tuple(meta)
    dynamic = dict(base=base_params,
                   payload=jax.tree_util.tree_map(jnp.asarray, payload))
    static = dict(codecs=codecs)
    if route and aot.enabled():
        # Build-if-absent keeps the first switch's compile out of the miss
        # counter; every later same-shape switch is a registry hit.
        aot.entry("delta.apply", apply_delta).build(
            dynamic, static, execute=False)
    return aot.dispatch("delta.apply", apply_delta,
                        dynamic=dynamic, static=static, route=route)


# ---------------------------------------------------------------------------
# Serve-side bank: W words stacked on a leading axis, one codec layout.
# ---------------------------------------------------------------------------


def stack_bank(
    base_params: Any,
    packed: Sequence[Tuple[Dict[str, Dict[str, np.ndarray]], Dict[str, Any]]],
) -> Tuple[Tuple[Tuple[str, str], ...], Dict[str, Dict[str, np.ndarray]]]:
    """Stack per-word payloads into a ``[W, ...]`` delta bank.

    Words may disagree per leaf (one word's ``q8`` is another's ``zero``);
    the bank needs ONE static codec layout so the serve step's scan slices a
    uniform pytree.  Unification is exact:

    - all-``zero`` leaves are dropped from the bank (base used directly);
    - ``q8``+``zero`` mixes keep ``q8`` (a zero word gets ``q=0`` — the
      identity-at-zero trick applied to weights);
    - any mix involving ``xor`` coerces every word to ``xor`` (a q8 word's
      bits come from its reconstructed leaf, so the coerced bank reproduces
      the exact same leaf values the word's own codec would).
    """
    if not packed:
        raise ValueError("stack_bank needs at least one packed word")
    base = {k: np.asarray(v) for k, v in flatten_named(base_params).items()}
    names = sorted(base)
    for _, meta in packed:
        if meta.get("codec_version") != DELTA_CODEC_VERSION:
            raise ValueError("delta codec version mismatch in bank input")
        missing = set(meta["codecs"]) ^ set(names)
        if missing:
            raise ValueError(f"bank leaf sets differ: {sorted(missing)}")

    codecs: List[Tuple[str, str]] = []
    bank: Dict[str, Dict[str, np.ndarray]] = {}
    for name in names:
        per_word = [meta["codecs"][name] for _, meta in packed]
        kinds = set(per_word)
        b = base[name]
        u = _uint_dtype(b.dtype)
        if kinds == {"zero"}:
            codecs.append((name, "zero"))
            continue
        if kinds <= {"q8", "zero"}:
            qs, scales = [], []
            for payload, _ in packed:
                fields = payload.get(name)
                if fields is None:                      # zero word: identity
                    qs.append(np.zeros(b.shape, np.int8))
                    scales.append(np.ones(b.shape[-1:] or (1,),
                                          np.float32)
                                  if b.ndim else np.ones((), np.float32))
                else:
                    qs.append(fields["q"])
                    scales.append(fields["scale"])
            codecs.append((name, "q8"))
            bank[name] = {"q": np.stack(qs), "scale": np.stack(scales)}
            continue
        # Coerce to xor: reconstruct each word's leaf bits exactly.
        bits = []
        for payload, _meta in packed:
            codec = _meta["codecs"][name]
            fields = payload.get(name)
            if codec == "zero":
                bits.append(np.zeros(b.shape, u))
            elif codec == "xor":
                bits.append(fields["bits"].astype(u, copy=False))
            else:  # q8 -> exact word leaf -> xor bits
                recon = (b.astype(np.float32)
                         + fields["q"].astype(np.float32)
                         * fields["scale"]).astype(b.dtype)
                bits.append(b.view(u) ^ recon.view(u))
        codecs.append((name, "xor"))
        bank[name] = {"bits": np.stack(bits)}
    return tuple(codecs), bank


def bank_words(bank: Dict[str, Dict[str, np.ndarray]]) -> int:
    """W, from any stacked leaf (0 for an empty bank — every word == base)."""
    for fields in bank.values():
        for arr in fields.values():
            return int(arr.shape[0])
    return 0
