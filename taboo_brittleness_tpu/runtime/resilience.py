"""Fault-tolerant sweep runtime: retries, deadlines, failure ledger, faults.

The paper's core artifact is a 20-checkpoint x multi-prompt sweep grid whose
cache *is* the checkpoint/resume story (``runtime/cache.py``, SURVEY.md §5) —
but at production scale partial failure is the steady state, not the
exception: a transient IO error mid-safetensors-stream, a corrupt resume
file, or one missing shard must cost one retry or one word, never the study.
This module makes failure handling a designed subsystem (the Sequoia stance:
robustness as a first-class axis, arXiv:2402.12374) instead of an accident of
whichever frame raised first:

- :class:`RetryPolicy` — exponential backoff with *seeded* jitter and a
  transient-vs-permanent error classification (:func:`is_transient`), so
  retried runs are reproducible and permanent errors fail fast.
- :class:`Deadline` / :func:`run_with_deadline` — watchdogs for host-side
  stages (checkpoint load, decode launch): a hung IO thread becomes a
  classified, retryable :class:`DeadlineExceeded` instead of a silent stall.
- :class:`FailureLedger` — the per-sweep ``<output_dir>/_failures.json``
  (atomic), recording per word: failing stage, attempt count, and the final
  exception; sweeps return partial results plus this ledger and the CLI
  exits non-zero iff it is non-empty.
- :class:`FaultInjector` — a deterministic registry of named fault sites
  (``checkpoint.read``, ``cache.write``, ``prefetch.thread``,
  ``decode.launch``) that tests and the ``TABOO_FAULT_PLAN`` env hook can
  arm with schedules (fail-N-then-succeed, always-fail, delay,
  truncate-write, die-at-site).  Sites are no-ops when nothing is armed.

Incarnations (``runtime.supervise``): a supervised run relaunches the same
pipeline as a sequence of child processes.  Each child carries its ordinal in
``TBX_INCARNATION`` (:func:`current_incarnation`); the ledger stamps every
retry/quarantine entry with the incarnation that recorded it and PRESERVES
prior incarnations' retry entries on resume, so the merged
``_failures.json`` of a multi-incarnation run attributes each event to the
process that saw it.  Fault specs accept an ``incarnation`` scope so crash
tests can arm "die in incarnation 0, wedge in incarnation 1" from one plan.

Everything here is host-side control flow — none of it runs under trace
(backoff sleeps and clocks would otherwise be baked into compiled programs).
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import sys
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

# ---------------------------------------------------------------------------
# Incarnations.
# ---------------------------------------------------------------------------

#: Set by the supervisor (``runtime.supervise``) on every child it launches:
#: the 0-based ordinal of this process in the supervised run.
INCARNATION_ENV = "TBX_INCARNATION"


def current_incarnation() -> int:
    """This process's incarnation ordinal (0 for an unsupervised run)."""
    try:
        return int(os.environ.get(INCARNATION_ENV, "0"))
    except ValueError:
        return 0


#: Set by the fleet coordinator (``runtime.fleet``) on every worker it
#: launches: this process's stable worker identity within the fleet.  Drives
#: the per-worker telemetry file suffixes (``_events.<wid>.jsonl``,
#: ``_progress.<wid>.json``), the ledger/span ``worker`` stamps, and the
#: fault-plan ``match`` context.
WORKER_ENV = "TBX_WORKER_ID"


def current_worker_id() -> Optional[str]:
    """This process's fleet worker id, or None outside a fleet worker."""
    return os.environ.get(WORKER_ENV) or None


# ---------------------------------------------------------------------------
# Error taxonomy.
# ---------------------------------------------------------------------------


class InjectedFault(OSError):
    """A deliberately injected *transient* fault (fault-injection harness)."""


class InjectedPermanentFault(RuntimeError):
    """A deliberately injected *permanent* fault — never retried."""


class DeadlineExceeded(TimeoutError):
    """A host-side stage overran its watchdog deadline (classified
    transient: a hung NFS read or wedged IO thread often succeeds on
    retry)."""


# OSErrors that retrying cannot fix: the filesystem object is missing or
# forbidden, not flaky (a missing safetensors shard stays missing — there is
# no hub egress in this environment).
_PERMANENT_OS_ERRORS = (
    FileNotFoundError,
    NotADirectoryError,
    IsADirectoryError,
    PermissionError,
)


def is_transient(exc: BaseException) -> bool:
    """Transient (worth retrying) vs permanent (fail fast / quarantine).

    Transient: injected transient faults, deadline overruns, and IO-shaped
    errors (``OSError`` family — interrupted reads, ``ETIMEDOUT``, connection
    resets) EXCEPT the permanent subset above.  Everything else — value/shape
    errors, missing keys, assertion failures — is a bug or a genuinely
    missing artifact, and retrying would only replay it.
    """
    if isinstance(exc, InjectedPermanentFault):
        return False
    if isinstance(exc, (InjectedFault, DeadlineExceeded)):
        return True
    if isinstance(exc, _PERMANENT_OS_ERRORS):
        return False
    return isinstance(exc, (OSError, ConnectionError, TimeoutError))


# ---------------------------------------------------------------------------
# Atomic file helpers (shared by every pipeline — the skip-if-exists resume
# logic treats existence as a completion marker, so no artifact may ever be
# observable half-written).
# ---------------------------------------------------------------------------


def atomic_json_dump(obj: Any, path: str, *, indent: int = 2) -> None:
    """Write-then-rename so a crash mid-write never leaves a truncated file."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=indent)
    os.replace(tmp, path)


def quarantine_file(path: str, *, reason: str = "") -> Optional[str]:
    """Rename a corrupt artifact to ``<path>.corrupt`` (never trusted, never
    fatal): the resume logic then treats the cell as missing and recomputes,
    while the bytes stay on disk for postmortem.  Returns the new path, or
    None if the file had already vanished."""
    if not os.path.exists(path):
        return None
    dst = f"{path}.corrupt"
    try:
        os.replace(path, dst)
    except OSError:
        return None
    _obs_warn(f"[resilience] quarantined corrupt file {path} -> {dst}"
              + (f" ({reason})" if reason else ""),
              name="resilience.quarantine_file", path=path, reason=reason)
    return dst


def _obs_warn(message: str, *, name: str, **attrs: Any) -> None:
    """Structured event + stderr mirror via the obs emitter; lazily imported
    (obs.trace fires this module's ``obs.event_write`` fault site, so the
    dependency must stay one-way at import time) and fail-open."""
    try:
        from taboo_brittleness_tpu import obs

        obs.warn(message, name=name, **attrs)
    except Exception:  # noqa: BLE001 — telemetry must never take down a run
        try:
            print(message, file=sys.stderr)  # tbx: TBX009-ok — obs-unavailable fallback
        except Exception:  # noqa: BLE001
            pass


def _obs_event(name: str, **attrs: Any) -> None:
    try:
        from taboo_brittleness_tpu import obs

        obs.event(name, **attrs)
    except Exception:  # noqa: BLE001 — fail-open
        pass


def _obs_last_seq() -> Optional[int]:
    try:
        from taboo_brittleness_tpu import obs

        return obs.last_seq()
    except Exception:  # noqa: BLE001 — fail-open
        return None


def _obs_count(name: str, amount: float = 1.0) -> None:
    try:
        from taboo_brittleness_tpu.obs import metrics as obs_metrics

        obs_metrics.counter(name).inc(amount)
    except Exception:  # noqa: BLE001 — fail-open
        pass


def _flightrec_record(kind: str, **attrs: Any) -> None:
    try:
        from taboo_brittleness_tpu.obs import flightrec

        flightrec.record(kind, **attrs)
    except Exception:  # noqa: BLE001 — fail-open
        pass


def _flightrec_dump(reason: str, **extra: Any) -> None:
    try:
        from taboo_brittleness_tpu.obs import flightrec

        flightrec.dump(reason, **extra)
    except Exception:  # noqa: BLE001 — fail-open
        pass


# ---------------------------------------------------------------------------
# RetryPolicy.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with seeded jitter.

    ``max_retries`` is the number of RE-tries: a call gets at most
    ``max_retries + 1`` attempts.  Jitter is drawn from a ``random.Random``
    seeded by ``(seed, site)``, so a given sweep's backoff schedule is
    byte-reproducible (TBX006's determinism stance, applied to the host
    control plane) while distinct sites still decorrelate.
    """

    max_retries: int = 2
    base_delay: float = 0.5
    multiplier: float = 2.0
    max_delay: float = 30.0
    jitter: float = 0.25        # fraction of the delay, symmetric
    seed: int = 0

    def delays(self, site: str = "") -> Iterator[float]:
        """The deterministic backoff schedule for one call site."""
        rng = random.Random(f"{self.seed}:{site}")
        delay = self.base_delay
        for _ in range(self.max_retries):
            jit = 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
            yield max(0.0, min(delay, self.max_delay) * jit)
            delay *= self.multiplier

    def call(
        self,
        fn: Callable[[], Any],
        *,
        site: str = "",
        classify: Callable[[BaseException], bool] = is_transient,
        sleep: Callable[[float], None] = time.sleep,
        on_retry: Optional[Callable[[BaseException, int, float], None]] = None,
    ) -> Any:
        """Run ``fn`` with retries on transient errors.

        Permanent errors (per ``classify``) raise immediately; transient
        errors consume the backoff schedule and re-raise once it is
        exhausted.  ``on_retry(exc, attempt, delay)`` fires before each
        backoff sleep (the ledger hook).  ``sleep`` is injectable so tests
        never actually wait.
        """
        schedule = self.delays(site)
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn()
            except Exception as exc:  # noqa: BLE001 — classified below
                if not classify(exc):
                    raise
                delay = next(schedule, None)
                if delay is None:
                    raise
                if on_retry is not None:
                    on_retry(exc, attempt, delay)
                sleep(delay)


# ---------------------------------------------------------------------------
# Deadlines / watchdogs.
# ---------------------------------------------------------------------------


class Deadline:
    """Cooperative deadline for host-side stages: create with a budget, call
    :meth:`check` at safe points.  Monotonic clock — wall-clock steps (NTP,
    leap smears) can't fire or starve the watchdog."""

    def __init__(self, seconds: float, *, stage: str = ""):
        self.seconds = float(seconds)
        self.stage = stage
        self._end = time.monotonic() + self.seconds

    def remaining(self) -> float:
        return self._end - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self) -> None:
        if self.expired():
            raise DeadlineExceeded(
                f"stage {self.stage or '<unnamed>'} exceeded its "
                f"{self.seconds:.1f}s deadline")


def run_with_deadline(
    fn: Callable[[], Any],
    timeout: Optional[float],
    *,
    stage: str = "",
) -> Any:
    """Run ``fn`` on a watchdog'd worker thread; raise
    :class:`DeadlineExceeded` if it does not finish within ``timeout``
    seconds.  ``timeout=None``/``<=0`` runs inline (no watchdog).

    The overrun worker is daemonized and abandoned, not killed (Python
    offers no safe cross-thread kill): callers pair this with
    :class:`RetryPolicy`, so the classified timeout becomes a clean retry
    while the wedged IO thread dies with the process.  JAX dispatch is
    thread-safe, so checkpoint streaming / decode launch work unchanged on
    the worker (the prefetch path already relies on this).
    """
    if timeout is None or timeout <= 0:
        return fn()
    result: Dict[str, Any] = {}

    def run() -> None:
        try:
            result["value"] = fn()
        except BaseException as exc:  # noqa: BLE001 — re-raised on the caller
            result["error"] = exc

    t = threading.Thread(target=run, name=f"deadline-{stage or 'stage'}",
                         daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        raise DeadlineExceeded(
            f"stage {stage or '<unnamed>'} exceeded its {timeout:.1f}s "
            "deadline (worker abandoned)")
    if "error" in result:
        raise result["error"]
    return result["value"]


# ---------------------------------------------------------------------------
# Failure ledger.
# ---------------------------------------------------------------------------

LEDGER_FILENAME = "_failures.json"


def _describe(exc: BaseException) -> Dict[str, Any]:
    return {
        "error_type": type(exc).__name__,
        "error": str(exc),
        "transient": is_transient(exc),
    }


class FailureLedger:
    """Per-sweep failure record at ``<output_dir>/_failures.json`` (atomic).

    - ``quarantined``: words whose final attempt failed — stage, attempt
      count, and the final exception.  The sweep *continued* past them; the
      CLI exits non-zero iff this block is non-empty.
    - ``retried``: words that eventually succeeded but needed retries
      (``{"attempts": n, "incarnation": k}``) — the sweep's transient-noise
      floor, kept for the run manifest.

    A rerun loads the existing ledger and CLEARS a word's quarantine entry
    when it finally succeeds, so the ledger always describes the current
    state of the output directory, not the union of every past run.

    Incarnations: every entry is stamped with the ``incarnation`` that
    recorded it (:func:`current_incarnation` unless overridden).  A RESUME
    incarnation (``incarnation > 0``) additionally preserves prior
    incarnations' ``retried`` entries instead of resetting them, so the
    ledger a supervised run leaves behind is the MERGED account of the whole
    run — each retry and quarantine attributed to the process that saw it.
    A fresh unsupervised rerun (incarnation 0) still resets ``retried``
    (per-run noise, the pre-supervision contract).

    Workers (``runtime.fleet``): schema v3 additionally stamps every entry
    with the ``worker`` that recorded it (:func:`current_worker_id` unless
    overridden) — the fleet merge needs BOTH dimensions (which worker, which
    incarnation of it) to attribute a failure.  Outside a fleet worker no
    ``worker`` key is emitted, so standalone ledgers read exactly as before;
    v2 ledgers (no worker stamps) load unchanged, and a resume normalizes
    their entries with the prior file's top-level ``worker`` when it has one.
    """

    def __init__(self, output_dir: Optional[str] = None, *,
                 path: Optional[str] = None,
                 incarnation: Optional[int] = None,
                 worker: Optional[str] = None):
        self.path = path or (os.path.join(output_dir, LEDGER_FILENAME)
                             if output_dir else None)
        self.incarnation = (current_incarnation() if incarnation is None
                            else int(incarnation))
        self.worker = current_worker_id() if worker is None else worker
        self.quarantined: Dict[str, Dict[str, Any]] = {}
        self.retried: Dict[str, Dict[str, Any]] = {}
        if self.path and os.path.exists(self.path):
            self._load_existing(self.path)

    def _stamp(self, entry: Dict[str, Any]) -> Dict[str, Any]:
        if self.worker:
            entry["worker"] = self.worker
        return entry

    def _load_existing(self, path: str) -> None:
        try:
            with open(path) as f:
                prior = json.load(f)
            self.quarantined = dict(prior.get("quarantined", {}))
        except (json.JSONDecodeError, UnicodeDecodeError, OSError) as exc:
            # The ledger obeys its own rules: unparseable -> quarantine the
            # file and start clean, never trust or crash.
            quarantine_file(path, reason=f"unreadable ledger: {exc}")
            self.quarantined = {}
            self.retried = {}
            return
        if self.incarnation > 0:
            # Supervised resume: keep prior incarnations' retry entries so
            # the merged ledger attributes every event (v1 int entries are
            # normalized to the writing run's incarnation; v2 entries gain
            # the prior file's worker stamp, when it had one — the v2→v3
            # normalization).
            prior_inc = int(prior.get("incarnation", 0) or 0)
            prior_worker = prior.get("worker")
            normalized: Dict[str, Dict[str, Any]] = {}
            for w, v in dict(prior.get("retried", {})).items():
                entry = (dict(v) if isinstance(v, dict)
                         else {"attempts": int(v), "incarnation": prior_inc})
                if prior_worker and "worker" not in entry:
                    entry["worker"] = prior_worker
                normalized[w] = entry
            self.retried = normalized
        else:
            # `retried` is per-run noise on an unsupervised rerun: reset.
            self.retried = {}

    def record_retry(self, word: str, stage: str, exc: BaseException,
                     attempt: int) -> None:
        self.retried[word] = self._stamp({"attempts": attempt,
                                          "incarnation": self.incarnation})
        self.save()

    def record_quarantine(self, word: str, stage: str, exc: BaseException,
                          attempts: int) -> None:
        entry = {
            "stage": stage,
            "attempts": attempts,
            "incarnation": self.incarnation,
            **_describe(exc),
            # Epoch timestamp: serialized metadata for humans, not duration
            # math (manifest wall_seconds owns durations).
            # tbx: wallclock-ok — serialized metadata, not duration math
            "at": time.time(),
        }
        # Event offset: the telemetry sequence number current at quarantine
        # time, so a postmortem can seek straight to the surrounding span
        # stream in <output_dir>/_events.jsonl (None when obs is inactive).
        seq = _obs_last_seq()
        if seq is not None:
            entry["event_seq"] = seq
        self.quarantined[word] = self._stamp(entry)
        self.save()

    def record_success(self, word: str) -> None:
        """A word completed: clear any stale quarantine entry from a prior
        run (resume semantics — the ledger describes what is MISSING now)."""
        if word in self.quarantined:
            del self.quarantined[word]
            self.save()

    def __bool__(self) -> bool:
        return bool(self.quarantined)

    @property
    def words(self) -> List[str]:
        return sorted(self.quarantined)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": 3,
            "incarnation": self.incarnation,
            **({"worker": self.worker} if self.worker else {}),
            "quarantined": self.quarantined,
            "retried": self.retried,
        }

    def save(self) -> None:
        if self.path:
            atomic_json_dump(self.to_dict(), self.path)


# ---------------------------------------------------------------------------
# Deterministic fault injection.
# ---------------------------------------------------------------------------

#: The named fault sites threaded through the real paths.  Arming an unknown
#: site is an error (a typo'd plan must fail loudly, not silently no-op).
FAULT_SITES = (
    "checkpoint.read",    # CheckpointManager._load_triple
    "cache.write",        # runtime.cache save_pair / save_summary (post-write)
    "prefetch.thread",    # CheckpointManager.prefetch worker
    "decode.launch",      # runtime.decode.generate
    "obs.event_write",    # obs.trace.Tracer._emit — proves telemetry is
    #                       fail-open: an injected sink fault drops the event,
    #                       never the run (tests/test_obs.py)
    "obs.metrics_write",  # obs.timeseries.TimeseriesRecorder._write — same
    #                       fail-open proof for the windowed metrics spool:
    #                       an injected fault drops the window (counted in
    #                       obs.metrics_dropped), never the run
    "serve.step",         # serve.scheduler.SlotScheduler.step — fired once
    #                       per in-flight session per step (context: request
    #                       id + scenario) so a plan can poison ONE session;
    #                       the scheduler quarantines it, the batch lives
    "speculate.verify",   # runtime.speculate.speculative_decode — fired
    #                       before EVERY verify-block launch (context: block
    #                       index + rows) so a plan can poison one block of
    #                       a speculative decode; the word-level run_guarded
    #                       retry→quarantine path owns the failure
    "serve.spec.verify",  # serve.scheduler (speculative engine) — fired per
    #                       in-flight session before each draft+verify block
    #                       (context: request id + scenario, retry adds
    #                       attempt=1); ONE in-place retry, then the session
    #                       quarantines — the block and batch live
    #                       (tests/test_serve_spec.py)
    "fleet.claim",        # runtime.fleet.FleetSpool.claim — fired per claim
    #                       attempt (context: uid + worker + holder); the
    #                       worker loop retries a failed claim on its next
    #                       poll
    "fleet.lease_renew",  # runtime.fleet.LeaseKeeper — fired per renewal;
    #                       a fault lets the lease expire (re-issue, then
    #                       benign duplicate commit), `die` here is the
    #                       mid-renewal SIGKILL harness
    "fleet.commit",       # runtime.fleet.run_worker — fired just before the
    #                       first-writer-wins commit; `die` here is the
    #                       "worker killed mid-word, artifact never lands"
    #                       chaos case
    "grid.cell",          # grid.runner.run_cell — fired once per (word,
    #                       layer, width) grid cell before the cell's
    #                       encode→ablate→decode program (context: word +
    #                       cell key + worker); rides the fleet worker's
    #                       run_guarded retry→quarantine path, so a poisoned
    #                       cell quarantines while the rest of the grid
    #                       commits (tests/test_grid.py)
    "serve.claim",        # serve.server.RequestSpool.claim_assigned — fired
    #                       per replica leased-claim attempt (context:
    #                       request + worker + holder); the replica's serve
    #                       loop retries a failed claim on its next poll,
    #                       mirroring fleet.claim
    "serve.lease_renew",  # serve.server.ServeLeaseKeeper — fired per held
    #                       request per renewal cycle; a fault lets the
    #                       request lease expire (coordinator re-spools,
    #                       then benign duplicate response), `die` here is
    #                       the mid-renewal replica SIGKILL harness
    "serve.respond",      # serve.server.RequestSpool.respond_exclusive —
    #                       fired just before the first-writer-wins response
    #                       link; `die` here is the "replica killed at first
    #                       commit, response never lands" chaos case the
    #                       serve-fleet selfcheck arms
    "gateway.accept",     # serve.gateway.Gateway — fired per accepted HTTP
    #                       request before admission checks (context: path +
    #                       tenant); a fault answers 500 and the client
    #                       retries against another gateway — the spool never
    #                       saw the request, so exactly-once is untouched
    "gateway.spool_put",  # serve.gateway.Gateway — fired just before the
    #                       durable RequestSpool.put; `die` here is the
    #                       "gateway killed between accept and ack" chaos
    #                       case: the client got no 200, so it may retry;
    #                       the request is not in the spool, nothing leaks
    "gateway.stream_write",  # serve.gateway.Gateway — fired per SSE event
    #                       write (context: request id); a fault mid-stream
    #                       drops the client connection while the replica
    #                       finishes (or the cancel tombstone aborts it) —
    #                       the response file stays authoritative
)

_FAULT_MODES = ("fail", "delay", "truncate", "die")

#: ``die`` default exit status: what the shell reports for SIGKILL (128+9),
#: so a died child is indistinguishable from a kernel OOM-kill to the
#: supervisor — exactly the failure the mode simulates.
DIE_EXIT_CODE = 137


@dataclasses.dataclass
class FaultSpec:
    """One armed schedule at one site.

    - ``mode="fail"``: raise (``kind`` transient/permanent).
    - ``mode="delay"``: sleep ``delay`` seconds (watchdog exercise).
    - ``mode="truncate"``: truncate the file at the context's ``path`` to
      half its size — a torn write, as seen by a later resume.
    - ``mode="die"``: ``os._exit(exit_code)`` on the spot — SIGKILL/OOM
      equivalent (no atexit, no finally, no buffered-sink flush), the
      crash-consistency harness for ``runtime.supervise``.  Never fires
      under pytest-style in-process drivers by accident: arm it only in a
      child you mean to kill.
    - ``times``: fire only on the first N *matching* calls
      (fail-N-then-succeed); ``None`` fires every time (always-fail).
      Counted per process — a restarted incarnation re-reads the plan with a
      fresh counter, so scope cross-incarnation schedules with
      ``incarnation``.
    - ``match``: only fire when some context value (word, path, ...)
      contains this substring; ``None`` matches every call.
    - ``incarnation``: only fire in this supervised incarnation
      (:func:`current_incarnation`); ``None`` fires in every process.
    """

    mode: str = "fail"
    times: Optional[int] = 1
    kind: str = "transient"          # "transient" | "permanent"
    delay: float = 0.0
    match: Optional[str] = None
    incarnation: Optional[int] = None
    exit_code: int = DIE_EXIT_CODE   # die mode's os._exit status
    fired: int = 0                   # mutable call counter (determinism: the
    #                                  schedule depends only on call order)

    def __post_init__(self) -> None:
        if self.mode not in _FAULT_MODES:
            raise ValueError(
                f"unknown fault mode {self.mode!r}; expected {_FAULT_MODES}")
        if self.kind not in ("transient", "permanent"):
            raise ValueError(
                f"unknown fault kind {self.kind!r}; "
                "expected 'transient' or 'permanent'")

    def matches(self, context: Dict[str, Any]) -> bool:
        if (self.incarnation is not None
                and self.incarnation != current_incarnation()):
            return False
        if self.match is None:
            return True
        return any(self.match in str(v) for v in context.values())


class FaultInjector:
    """Deterministic registry of armed fault sites.

    Tests arm programmatically (:meth:`arm`); operators arm via the
    ``TABOO_FAULT_PLAN`` env var — either inline JSON or a path to a JSON
    file — mapping site names to spec dicts (or lists of them)::

        TABOO_FAULT_PLAN='{"checkpoint.read":
            {"mode": "fail", "times": 2, "match": "ship"}}'

    Firing is thread-safe (the prefetch site runs on worker threads) and
    counts per spec in call order, so a plan replays identically run to run.
    """

    def __init__(self) -> None:
        self._specs: Dict[str, List[FaultSpec]] = {}
        self._lock = threading.Lock()

    # -- arming ------------------------------------------------------------

    def arm(self, site: str, spec: Optional[FaultSpec] = None,
            **kw: Any) -> FaultSpec:
        if site not in FAULT_SITES:
            raise ValueError(
                f"unknown fault site {site!r}; known sites: {FAULT_SITES}")
        spec = spec if spec is not None else FaultSpec(**kw)
        with self._lock:
            self._specs.setdefault(site, []).append(spec)
        return spec

    def clear(self, site: Optional[str] = None) -> None:
        with self._lock:
            if site is None:
                self._specs.clear()
            else:
                self._specs.pop(site, None)

    @property
    def armed(self) -> bool:
        return bool(self._specs)

    @classmethod
    def from_plan(cls, plan: Dict[str, Any]) -> "FaultInjector":
        inj = cls()
        for site, specs in plan.items():
            if isinstance(specs, dict):
                specs = [specs]
            for spec in specs:
                inj.arm(site, **spec)
        return inj

    @classmethod
    def from_env(cls, env_var: str = "TABOO_FAULT_PLAN") -> "FaultInjector":
        raw = os.environ.get(env_var, "").strip()
        if not raw:
            return cls()
        if not raw.lstrip().startswith("{"):
            with open(raw) as f:
                raw = f.read()
        return cls.from_plan(json.loads(raw))

    # -- firing ------------------------------------------------------------

    def fire(self, site: str, **context: Any) -> None:
        """Evaluate ``site``'s armed schedules against ``context``; no-op
        when nothing matches.  Raises / delays / truncates per the first
        matching spec with shots remaining."""
        with self._lock:
            specs = list(self._specs.get(site, ()))
            spec = None
            for s in specs:
                if not s.matches(context):
                    continue
                if s.times is not None and s.fired >= s.times:
                    continue
                s.fired += 1
                spec = s
                break
        if spec is None:
            return
        detail = ", ".join(f"{k}={v}" for k, v in sorted(context.items()))
        label = f"{site}" + (f" [{detail}]" if detail else "")
        if spec.mode == "die":
            # SIGKILL-equivalent: no cleanup, no flush — the supervised-run
            # crash-consistency tests assert resume from exactly this state.
            os._exit(spec.exit_code)
            return  # only reachable with os._exit stubbed out (unit tests)
        if spec.mode == "delay":
            time.sleep(spec.delay)
            return
        if spec.mode == "truncate":
            path = context.get("path")
            if path and os.path.exists(path):
                size = os.path.getsize(path)
                with open(path, "r+b") as f:
                    f.truncate(size // 2)
            return
        if spec.kind == "permanent":
            raise InjectedPermanentFault(
                f"injected permanent fault at {label}")
        raise InjectedFault(f"injected transient fault at {label}")


# Module-level default injector: lazily built from TABOO_FAULT_PLAN on first
# use so `fire()` at the real sites costs one None-check when nothing is
# armed (the common case — the sites live on hot-ish host paths).
_injector: Optional[FaultInjector] = None
_injector_lock = threading.Lock()


def get_injector() -> FaultInjector:
    global _injector
    with _injector_lock:
        if _injector is None:
            _injector = FaultInjector.from_env()
        return _injector


def set_injector(injector: Optional[FaultInjector]) -> None:
    """Install (or with None, reset-to-env) the process-wide injector —
    the test hook."""
    global _injector
    with _injector_lock:
        _injector = injector


def fire(site: str, **context: Any) -> None:
    """The sites' entry point: ``resilience.fire("checkpoint.read",
    word=word)``.  Fast no-op unless a plan armed this site."""
    inj = get_injector()
    if not inj.armed:
        return
    inj.fire(site, **context)


# ---------------------------------------------------------------------------
# Sweep helper: retry-then-quarantine one unit of work.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class WordOutcome:
    """Result of :func:`run_guarded`: either ``value`` (success) or the
    exception that exhausted the policy (quarantine)."""

    word: str
    value: Any = None
    error: Optional[BaseException] = None
    attempts: int = 1
    stage: str = ""

    @property
    def ok(self) -> bool:
        return self.error is None


def run_guarded(
    word: str,
    fn: Callable[[], Any],
    *,
    policy: RetryPolicy,
    ledger: Optional[FailureLedger] = None,
    stage: Callable[[], str] = lambda: "run",
    sleep: Callable[[float], None] = time.sleep,
) -> WordOutcome:
    """Run one word's work under ``policy``; on final failure return (not
    raise) the error so the sweep can quarantine and continue.  ``stage`` is
    a thunk so the caller can report which sub-stage was active when the
    last attempt died.  Ledger updates (retries, quarantine, clears) happen
    here so every sweep shares one bookkeeping path.
    """
    attempts = {"n": 1}
    _flightrec_record("word.attempt", word=word, stage=stage())

    def on_retry(exc: BaseException, attempt: int, delay: float) -> None:
        attempts["n"] = attempt + 1
        if ledger is not None:
            ledger.record_retry(word, stage(), exc, attempt)
        _flightrec_record("word.retry", word=word, stage=stage(),
                          attempt=attempt,
                          error=f"{type(exc).__name__}: {exc}"[:200])
        _obs_count("sweep.retries")
        _obs_warn(f"[resilience] {word}: attempt {attempt} failed at "
                  f"{stage()} ({type(exc).__name__}: {exc}); retrying in "
                  f"{delay:.2f}s",
                  name="resilience.retry", word=word, stage=stage(),
                  attempt=attempt, delay=round(delay, 3),
                  error=f"{type(exc).__name__}: {exc}"[:300])

    try:
        value = policy.call(fn, site=f"{stage()}:{word}", sleep=sleep,
                            on_retry=on_retry)
    except Exception as exc:  # noqa: BLE001 — quarantine, don't crash the sweep
        if ledger is not None:
            ledger.record_quarantine(word, stage(), exc, attempts["n"])
        _obs_event("resilience.quarantine", word=word, stage=stage(),
                   attempts=attempts["n"],
                   error=f"{type(exc).__name__}: {exc}"[:300])
        _obs_count("sweep.quarantines")
        # The postmortem trigger: the quarantine freezes the last-N-steps
        # ring to <output_dir>/_flightrec.json (obs.flightrec; fail-open).
        _flightrec_record("word.quarantine", word=word, stage=stage(),
                          attempts=attempts["n"],
                          error=f"{type(exc).__name__}: {exc}"[:200])
        _flightrec_dump("quarantine", word=word, stage=stage())
        return WordOutcome(word=word, error=exc, attempts=attempts["n"],
                           stage=stage())
    if ledger is not None:
        ledger.record_success(word)
    return WordOutcome(word=word, value=value, attempts=attempts["n"],
                       stage=stage())
