"""Checkpoint resolution: taboo word -> (params, config, tokenizer).

The reference downloads ``bcywinski/gemma-2-9b-it-taboo-<word>`` from the HF
hub at call time (reference ``src/models.py:8-53``).  This environment has no
hub egress, so resolution is local-first and explicit:

1. ``TABOO_CHECKPOINT_ROOT`` (or ``checkpoint_root=``) — a directory holding
   one HF-snapshot-layout folder per checkpoint (config.json + safetensors +
   tokenizer files), named either by the full repo id's basename
   (``gemma-2-9b-it-taboo-ship``) or by the bare word (``ship``).
2. The standard HF cache (``~/.cache/huggingface/hub``) if the snapshot was
   ever downloaded.

Weights stream shard-by-shard from safetensors into the scan-stacked pytree
(models/params.py) — no torch runtime in the path.  Loaded checkpoints are
LRU-cached by word (the reference reloads the full 9B per word and relies on
GPU-memory scrubbing between words, src/run_generation.py:85-129 /
src/utils.py; here eviction is explicit).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Dict, Optional, Sequence, Tuple

from taboo_brittleness_tpu.config import Config, ModelConfig
from taboo_brittleness_tpu.models import gemma2
from taboo_brittleness_tpu.models.params import (
    from_safetensors_dir_streamed,
    infer_config_from_hf_config_json,
)
from taboo_brittleness_tpu.runtime import resilience
from taboo_brittleness_tpu.runtime.tokenizer import HFTokenizer, TokenizerLike

#: Default base model for delta-resident mode: every taboo checkpoint is a
#: finetune of this one snapshot (reference src/models.py).
DEFAULT_DELTA_BASE = "google/gemma-2-9b-it"


def resolve_snapshot_dir(repo_id: str, checkpoint_root: Optional[str] = None) -> str:
    """Find a local HF-snapshot directory for ``repo_id`` or raise."""
    basename = repo_id.split("/")[-1]
    candidates = []
    root = checkpoint_root or os.environ.get("TABOO_CHECKPOINT_ROOT")
    if root:
        parts = basename.split("-")
        # Every hyphen-suffix of the basename, LONGEST first, so a
        # multi-token word ("...-taboo-ice-cream") resolves <root>/ice-cream
        # before a bare <root>/cream could shadow it.
        suffixes = ["-".join(parts[i:]) for i in range(1, len(parts))]
        candidates += [os.path.join(root, basename)]
        candidates += [os.path.join(root, s) for s in suffixes]
        candidates += [os.path.join(root, repo_id.replace("/", "--"))]
    # HF_HUB_CACHE points at the hub cache itself; HF_HOME at its parent.
    hub_dir_root = os.path.expanduser(
        os.environ.get("HF_HUB_CACHE")
        or os.path.join(os.environ.get("HF_HOME", "~/.cache/huggingface"),
                        "hub"))
    hub_dir = os.path.join(hub_dir_root,
                           f"models--{repo_id.replace('/', '--')}", "snapshots")
    if os.path.isdir(hub_dir):
        snaps = sorted(os.listdir(hub_dir))
        candidates += [os.path.join(hub_dir, s) for s in snaps]

    for c in candidates:
        if os.path.exists(os.path.join(c, "config.json")):
            return c
    raise FileNotFoundError(
        f"no local snapshot for {repo_id}; looked in {candidates or '[no roots]'}. "
        f"Set TABOO_CHECKPOINT_ROOT to a directory of HF snapshots.")


class CheckpointManager:
    """LRU cache of loaded (params, cfg, tokenizer) triples keyed by word.

    Failure semantics (``runtime.resilience``): with a ``retry_policy``,
    transient load errors (interrupted safetensors reads, injected faults,
    deadline overruns) retry with seeded exponential backoff; permanent ones
    (missing snapshot/shard) raise immediately.  ``load_deadline`` watchdogs
    each load attempt on a worker thread so a hung read becomes a retryable
    :class:`~.resilience.DeadlineExceeded` instead of a silent stall.
    """

    def __init__(self, model_cfg: ModelConfig, *,
                 checkpoint_root: Optional[str] = None, capacity: int = 1,
                 mesh=None,
                 retry_policy: Optional[resilience.RetryPolicy] = None,
                 load_deadline: Optional[float] = None,
                 delta_root: Optional[str] = None,
                 base_id: Optional[str] = None):
        self.model_cfg = model_cfg
        self.checkpoint_root = checkpoint_root
        self.capacity = max(1, capacity)
        self.mesh = mesh  # when set, params are placed per parallel.mesh policy
        self.retry_policy = retry_policy
        self.load_deadline = load_deadline
        # Base-resident delta mode (ISSUE 12): when a delta root is set —
        # explicitly or via TBX_DELTA=1 + TBX_DELTA_ROOT — the base snapshot
        # loads ONCE (streamed, mesh-sharded) and pins; word loads stream
        # only the packed delta and apply it in-graph.
        if delta_root is None and os.environ.get("TBX_DELTA") == "1":
            delta_root = os.environ.get("TBX_DELTA_ROOT") or None
        self.delta_root = delta_root
        self.base_id = base_id or os.environ.get(
            "TBX_DELTA_BASE", DEFAULT_DELTA_BASE)
        self._base_lock = threading.Lock()
        self._base_triple: Optional[Tuple] = None
        self._cache: "OrderedDict[str, Tuple]" = OrderedDict()
        self._pending: Dict[str, threading.Thread] = {}
        self._pending_results: Dict[str, Tuple] = {}

    def repo_id(self, word: str) -> str:
        return self.model_cfg.checkpoint_template.format(word=word)

    def base_triple(self) -> Tuple[gemma2.Params, gemma2.Gemma2Config, TokenizerLike]:
        """The pinned base (params, cfg, tok); loaded once, thread-safe.

        Prefetch threads call ``_load_triple`` concurrently with the main
        thread, so the once-only base load needs a lock — the streamed read
        of an 18.5 GB snapshot is exactly the work the delta path exists to
        not repeat.
        """
        with self._base_lock:
            if self._base_triple is None:
                snap = resolve_snapshot_dir(self.base_id, self.checkpoint_root)
                cfg = infer_config_from_hf_config_json(
                    snap, dtype=self.model_cfg.dtype,
                    param_dtype=self.model_cfg.param_dtype)
                params = from_safetensors_dir_streamed(
                    snap, cfg, mesh=self.mesh)
                tok = HFTokenizer.from_pretrained(snap)
                self._base_triple = (params, cfg, tok)
            return self._base_triple

    def _load_triple(self, word: str) -> Tuple[gemma2.Params, gemma2.Gemma2Config, TokenizerLike]:
        resilience.fire("checkpoint.read", word=word)
        if self.delta_root is not None:
            return self._load_triple_delta(word)
        snap = resolve_snapshot_dir(self.repo_id(word), self.checkpoint_root)
        cfg = infer_config_from_hf_config_json(
            snap, dtype=self.model_cfg.dtype, param_dtype=self.model_cfg.param_dtype)
        # Streamed: one stacked leaf materializes at a time (vs the whole
        # state dict + a converted copy), placed straight onto the mesh —
        # no unsharded full-model stopover on host or device.
        params = from_safetensors_dir_streamed(snap, cfg, mesh=self.mesh)
        tok = HFTokenizer.from_pretrained(snap)
        return (params, cfg, tok)

    def _load_triple_delta(self, word: str) -> Tuple:
        """Delta path: stream the packed delta (~100x less IO than the full
        snapshot) and apply it to the resident base as one jitted program.
        Runs inside the same retry/deadline/fault plumbing as a full load —
        ``checkpoint.read`` has already fired for this attempt."""
        from taboo_brittleness_tpu.runtime import delta as deltalib

        base_params, cfg, tok = self.base_triple()
        path = deltalib.delta_path(self.delta_root, word)
        payload, meta = deltalib.load_delta(path)
        params = deltalib.apply_packed(
            base_params, payload, meta, route=self.mesh is None)
        return (params, cfg, tok)

    def _load_guarded(self, word: str) -> Tuple:
        """One load with the deadline watchdog applied; the retry wrapper
        below composes around it (each attempt gets a fresh deadline)."""
        return resilience.run_with_deadline(
            lambda: self._load_triple(word), self.load_deadline,
            stage=f"checkpoint.load:{word}")

    def _load_with_retries(self, word: str) -> Tuple:
        if self.retry_policy is None:
            return self._load_guarded(word)
        return self.retry_policy.call(
            lambda: self._load_guarded(word), site=f"checkpoint.read:{word}")

    def prefetch(self, word: str) -> None:
        """Start loading ``word``'s checkpoint on a host thread.

        The safetensors streaming + tokenizer parse overlap with whatever the
        device is computing for the CURRENT word (JAX dispatch is
        thread-safe); the next ``load(word)`` then joins the thread instead
        of doing the IO serially (VERDICT round-2 item 7: per-word sweep time
        was checkpoint-load + compute back-to-back).  Errors surface at
        ``load`` time, not in the thread — and a transient prefetch error is
        retried synchronously by ``load`` (the prefetch was an *attempt*,
        not a verdict), so a flaky read never poisons ``_pending_results``.
        """
        if word in self._cache:
            return
        if word in self._pending:
            # A finished-but-errored prefetch for a word nobody load()ed yet
            # must not pin its stale error (or block a re-prefetch) forever:
            # re-arm it.  A still-running or successful thread is left alone.
            t = self._pending[word]
            stale = (not t.is_alive()
                     and word in self._pending_results
                     and not self._pending_results[word][0])
            if not stale:
                return
            self.drop_pending(word)

        from taboo_brittleness_tpu import obs

        obs.event("checkpoint.prefetch.start", word=word)

        def run():
            try:
                resilience.fire("prefetch.thread", word=word)
                # tbx: TBX201-ok — load()/drop_pending() join the thread
                # before reading the slot: join() is the happens-before edge
                self._pending_results[word] = (True, self._load_triple(word))
                obs.event("checkpoint.prefetch.done", word=word)
            except BaseException as e:  # re-raised (or retried) by load()
                self._pending_results[word] = (False, e)
                obs.event("checkpoint.prefetch.failed", word=word,
                          error=f"{type(e).__name__}: {e}"[:300])

        t = threading.Thread(target=run, name=f"prefetch-{word}", daemon=True)
        self._pending[word] = t
        t.start()

    def drop_pending(self, word: str) -> None:
        """Discard any pending prefetch state for ``word`` (joining its
        thread): sweeps call this when a word is skipped or quarantined so a
        stale thread result cannot leak into a later ``load`` of the same
        word — the leak regression in tests/test_resilience.py."""
        t = self._pending.pop(word, None)
        if t is not None:
            t.join()
        self._pending_results.pop(word, None)

    def load(self, word: str) -> Tuple[gemma2.Params, gemma2.Gemma2Config, TokenizerLike]:
        from taboo_brittleness_tpu import obs

        if word in self._cache:
            self._cache.move_to_end(word)
            obs.event("checkpoint.load", word=word, source="cache")
            return self._cache[word]
        with obs.span("checkpoint.load", kind="program", word=word) as sp:
            if word in self._pending:
                self._pending.pop(word).join()
                ok, payload = self._pending_results.pop(word)
                if ok:
                    triple = payload
                    sp.set(source="prefetch")
                elif (self.retry_policy is not None
                        and resilience.is_transient(payload)):
                    # The failed prefetch counts as attempt 1; the policy owns
                    # the rest.  Surfacing the error as retryable (instead of
                    # raising the thread's exception verbatim) is what keeps
                    # one flaky IO from costing the word.
                    sp.set(source="prefetch-retry")
                    triple = self._load_with_retries(word)
                else:
                    raise payload
            else:
                sp.set(source="sync")
                triple = self._load_with_retries(word)
        self._cache[word] = triple
        while len(self._cache) > self.capacity:
            # Drop oldest; its device buffers free once unreferenced (the
            # explicit analogue of the reference's clean_gpu_memory dance).
            self._cache.popitem(last=False)
        return self._cache[word]

    def __call__(self, word: str):
        return self.load(word)


def prefetch_next(model_loader, words: Sequence[str], current_index: int) -> None:
    """Overlap the NEXT word's checkpoint load with the current word's
    compute, when the loader supports it (plain callables are fine too)."""
    if current_index + 1 < len(words):
        fn = getattr(model_loader, "prefetch", None)
        if fn is not None:
            fn(words[current_index + 1])


def model_loader_from_config(config: Config, **kw) -> CheckpointManager:
    return CheckpointManager(config.model, **kw)
