"""Structured run manifest + profiling harness.

The reference's only observability is ``print()`` (SURVEY.md §5: "Metrics /
logging: print() only").  Here every pipeline run can record a manifest —
config snapshot, environment (jax backend, devices, versions, git commit),
per-stage wall times, artifact paths — to ``run_manifest.json`` next to its
results, and optionally capture a ``jax.profiler`` trace for perf work
(the aux-subsystem plan of SURVEY.md §5: "perf via jax.profiler traces").
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import platform
import subprocess
import time
import uuid
from typing import Any, Dict, List, Optional


def _git_commit() -> Optional[str]:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=5,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(__file__))))
        # A failed rev-parse (not a repo, corrupt .git) exits non-zero and
        # prints its complaint to stderr; stdout alone once let that pass
        # as a bogus "commit".  Trust stdout only on success.
        if out.returncode != 0:
            return None
        return out.stdout.strip() or None
    except Exception:
        return None


def environment_info() -> Dict[str, Any]:
    info: Dict[str, Any] = {
        "python": platform.python_version(),
        "platform": platform.platform(),
        "git_commit": _git_commit(),
    }
    try:
        import jax

        info["jax_version"] = jax.__version__
        info["backend"] = jax.default_backend()
        info["devices"] = [str(d) for d in jax.devices()]
    except Exception as e:  # manifest must never take down a run
        info["jax_error"] = repr(e)
    return info


@dataclasses.dataclass
class RunManifest:
    """Collects run metadata; write once at the end with :meth:`save`."""

    command: str
    config: Optional[Dict[str, Any]] = None
    run_id: str = dataclasses.field(default_factory=lambda: uuid.uuid4().hex[:12])
    # Epoch timestamp: serialized metadata for humans/tooling, NOT duration
    # math — wall_seconds below accounts against the monotonic mark.
    # tbx: wallclock-ok — genuine epoch timestamp (duration uses _mono_start)
    started_at: float = dataclasses.field(default_factory=time.time)
    # Monotonic twin of started_at: durations must survive NTP steps / clock
    # adjustments mid-run (a stepped clock once made wall_seconds negative).
    _mono_start: float = dataclasses.field(default_factory=time.monotonic)
    environment: Dict[str, Any] = dataclasses.field(default_factory=environment_info)
    stages: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    artifacts: List[str] = dataclasses.field(default_factory=list)
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Resilience accounting (runtime.resilience.FailureLedger): words the run
    # quarantined after exhausting retries, and per-word retry counts for
    # words that eventually succeeded.  Empty blocks are omitted from the
    # serialized manifest.
    failures: Dict[str, Any] = dataclasses.field(default_factory=dict)
    retries: Dict[str, int] = dataclasses.field(default_factory=dict)

    @contextlib.contextmanager
    def stage(self, name: str, **meta: Any):
        """Record one timed stage: ``with manifest.stage("decode", word=w): ...``"""
        t0 = time.perf_counter()
        record: Dict[str, Any] = {"name": name, **meta}
        try:
            yield record
            record["status"] = "ok"
        except BaseException:
            record["status"] = "error"
            raise
        finally:
            record["seconds"] = round(time.perf_counter() - t0, 4)
            self.stages.append(record)

    def add_artifact(self, path: str) -> None:
        self.artifacts.append(path)

    def record_resilience(self, ledger) -> None:
        """Fold a :class:`~.resilience.FailureLedger` (or its dict form)
        into the manifest's failures/retries blocks."""
        data = ledger.to_dict() if hasattr(ledger, "to_dict") else dict(ledger)
        self.failures.update(data.get("quarantined", {}))
        self.retries.update(data.get("retried", {}))

    def _obs_block(self) -> Dict[str, Any]:
        """Observability stamp: the obs schema version, the events-file path
        (when a tracer is/was active this process), and a snapshot of the
        process metrics registry (decode launches, retries, word-time
        histograms, AOT hit rates...).  Fail-open: a broken obs import
        reduces the block to the schema version."""
        block: Dict[str, Any] = {}
        try:
            from taboo_brittleness_tpu import obs

            block["schema_version"] = obs.SCHEMA_VERSION
            path = obs.events_path()
            if path:
                block["events_path"] = path
            snap = obs.metrics.snapshot()
            if snap:
                block["metrics"] = snap
        except Exception:  # noqa: BLE001 — manifest must never fail a run
            pass
        return block

    def _preempt_block(self) -> Dict[str, Any]:
        """Preemption-notice guard (ISSUE 10 satellite): hoist the sweep
        observer's ``sweep.preempt_margin_s`` gauge — the worst slack
        between any computed word's wall time and ``TBX_PREEMPT_NOTICE_S``
        — to a first-class manifest field.  Negative margin = a word
        outlived the notice and drain-at-word-boundary is no longer
        preemption-safe.  Empty (omitted) when no word was measured."""
        try:
            from taboo_brittleness_tpu.obs import metrics as obs_metrics

            snap = obs_metrics.snapshot()
            gauge = (snap.get("gauges") or {}).get("sweep.preempt_margin_s")
            if gauge is None:
                return {}
            return {"preempt_margin_s": gauge}
        except Exception:  # noqa: BLE001 — manifest must never fail a run
            return {}

    def _incarnation_block(self) -> Dict[str, Any]:
        """Supervised-run stamp (``runtime.supervise``): which incarnation
        of a supervised run wrote this manifest, and whether it exited on a
        preemption drain.  Empty (omitted) for a plain standalone run, so
        unsupervised manifests are byte-identical to before.  Fail-open."""
        try:
            from taboo_brittleness_tpu.runtime import supervise
            from taboo_brittleness_tpu.runtime.resilience import (
                current_incarnation)

            inc = current_incarnation()
            drained = supervise.drain_requested()
            if not inc and not drained:
                return {}
            return {"incarnation": {"id": inc, "drained": drained}}
        except Exception:  # noqa: BLE001 — manifest must never fail a run
            return {}

    def to_dict(self) -> Dict[str, Any]:
        return {
            "run_id": self.run_id,
            "command": self.command,
            "started_at": self.started_at,
            "wall_seconds": round(time.monotonic() - self._mono_start, 3),
            "environment": self.environment,
            "config": self.config,
            "stages": self.stages,
            "artifacts": self.artifacts,
            "obs": self._obs_block(),
            **self._preempt_block(),
            **self._incarnation_block(),
            **({"failures": self.failures} if self.failures else {}),
            **({"retries": self.retries} if self.retries else {}),
            **({"extra": self.extra} if self.extra else {}),
        }

    def save(self, path: str) -> str:
        from taboo_brittleness_tpu.runtime.resilience import atomic_json_dump

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # The manifest is re-saved per stage and read by resume/supervise —
        # a crash mid-save must never leave a torn file behind.
        atomic_json_dump(self.to_dict(), path)
        return path


@contextlib.contextmanager
def maybe_profile(trace_dir: Optional[str]):
    """Capture a jax.profiler trace when ``trace_dir`` is set (view with
    TensorBoard / xprof).  No-op otherwise."""
    if not trace_dir:
        yield
        return
    import jax

    os.makedirs(trace_dir, exist_ok=True)
    with jax.profiler.trace(trace_dir):
        yield
