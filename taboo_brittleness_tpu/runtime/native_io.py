"""ctypes binding for the native parallel npz writer.

The parity-dump path writes the reference-schema ~1.16 GB ``all_probs`` npz
per prompt (reference ``src/run_generation.py:57``); numpy's
``savez_compressed`` deflates it on one thread and dominates cache-build
wall-clock.  ``native/npz_writer.cpp`` compresses each member in N parallel
deflate chunks (pigz-style Z_SYNC_FLUSH concatenation + crc32_combine) and
writes a byte-compatible zip/npz that ``np.load`` reads unchanged.

The shared library builds on first use (one ``g++ -O3 -shared`` invocation,
cached next to the source); any failure — no compiler, no zlib — degrades to
``np.savez_compressed`` silently.  ``save_npz`` is the only entry point.
"""

from __future__ import annotations

import ctypes
import io
import os
import subprocess
import threading
from typing import Dict, Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_SRC = os.path.join(_NATIVE_DIR, "npz_writer.cpp")
_LIB = os.path.join(_NATIVE_DIR, "libnpz_writer.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[ctypes.CDLL]:
    global _build_failed
    if _build_failed:
        return None
    try:
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-pthread",
                 "-o", _LIB, _SRC, "-lz"],
                check=True, capture_output=True, timeout=120)
        lib = ctypes.CDLL(_LIB)
        lib.npz_open.restype = ctypes.c_void_p
        lib.npz_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
        lib.npz_add.restype = ctypes.c_int
        lib.npz_add.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_void_p, ctypes.c_uint64,
        ]
        lib.npz_close.restype = ctypes.c_int
        lib.npz_close.argtypes = [ctypes.c_void_p]
        return lib
    except Exception:
        _build_failed = True
        return None


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib
    with _lock:
        if _lib is None and not _build_failed:
            _lib = _build()
        return _lib


def _npy_header(arr: np.ndarray) -> bytes:
    """The .npy header bytes numpy would write for ``arr`` (v1.0/2.0 format)."""
    buf = io.BytesIO()
    np.lib.format.write_array_header_1_0(
        buf, np.lib.format.header_data_from_array_1_0(arr))
    return buf.getvalue()


def native_available() -> bool:
    return _get_lib() is not None


def save_npz(
    path: str,
    arrays: Dict[str, np.ndarray],
    *,
    n_threads: int = 0,
    level: int = 6,
) -> bool:
    """Write a compressed npz; returns True if the native writer was used.

    Falls back to ``np.savez_compressed`` (same on-disk format, slower) when
    the native library is unavailable.  ``n_threads=0`` = all cores.
    """
    lib = _get_lib()
    if lib is None:
        np.savez_compressed(path, **arrays)
        return False

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    handle = lib.npz_open(path.encode(), n_threads, level)
    if not handle:
        np.savez_compressed(path, **arrays)
        return False
    try:
        for name, arr in arrays.items():
            arr = np.ascontiguousarray(arr)
            header = _npy_header(arr)
            rc = lib.npz_add(
                handle, name.encode(),
                header, len(header),
                arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes)
            if rc != 0:
                raise OSError(f"npz_add({name}) failed: {rc}")
        rc = lib.npz_close(handle)
        handle = None
        if rc != 0:
            raise OSError(f"npz_close failed: {rc}")
        return True
    except Exception:
        if handle is not None:
            lib.npz_close(handle)
        np.savez_compressed(path, **arrays)
        return False
