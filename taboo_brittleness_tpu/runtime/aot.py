"""AOT program registry: warm-started executables for the hot jit entry points.

Why this exists (VERDICT r05 weak #6): with the persistent XLA compile cache
warm, a fresh study process STILL paid ~73 s on word 0 vs ~11.4 s steady —
per-process Python tracing, compile-cache lookup/deserialization, and first
dispatch of ~10 large programs, none of which the compile cache can remove.
The fix has two halves:

1. **Warm start** — the study's per-word program set is known before word 0
   runs (shapes derive from the config; ``interventions.study_program_specs``
   mirrors them).  ``AotEntry.build`` traces+compiles each program ahead of
   time — on a background thread overlapped with word 0's checkpoint load in
   the driver, or synchronously where a caller wants the cost itemized
   (bench) — and records the trace/compile/execute split so cold-start cost
   is a measured table, not a mystery.
2. **Cross-process reuse** — built executables serialize to
   :class:`~taboo_brittleness_tpu.runtime.jax_cache.AotStore`; a later
   process loads them directly, skipping tracing AND compiling (the two
   halves of the old 73 s).

Dispatch: hot call sites route through :func:`dispatch`, which runs a
registry-matched executable when one exists and otherwise falls back to the
plain jit call — the registry is an accelerator, never a correctness
dependency.  A call that arrives while its program is still building WAITS
for the in-flight build instead of tracing the same program in parallel
(duplicate tracing fights for the GIL and wins nothing).

Keys cover everything that selects a compiled program: entry name, argument
pytree structure, every leaf's aval (shape/dtype/weak-type), every leaf's
multi-device NamedSharding (spec + mesh axis sizes — executables are
specialized to input shardings, so a tp-sharded serve step and its
unsharded twin must never collide on one key; single-device placements
contribute nothing, keeping pre-mesh keys stable), and the repr of every
static argument.  The tensor-parallel serve programs (ISSUE 18) route
through the registry on exactly this contract; the sweep's mesh launches
still bypass it at their call sites (``route=False`` — their AOT story is
``__graft_entry__``).  The on-disk layer additionally keys on backend,
device kind, jax version, and a package-source hash (see ``jax_cache``), so
a stale store can only miss.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Callable, Dict, Optional

# A call that finds its program mid-build waits this long before giving up
# and tracing for itself (remote-TPU compiles can take minutes).
_BUILD_WAIT_S = 900.0


def enabled() -> bool:
    import os

    return os.environ.get("TBX_AOT", "1") != "0"


def _obs_event(name: str, **attrs: Any) -> None:
    """Lazily-imported, fail-open telemetry point event (no-op without an
    active tracer)."""
    try:
        from taboo_brittleness_tpu import obs

        obs.event(name, **attrs)
    except Exception:  # noqa: BLE001 — telemetry must never poison dispatch
        pass


def _static_repr(v: Any) -> str:
    """Stable string for a static argument: functions by qualified name
    (their identity IS the jit static), everything else by repr."""
    if callable(v) and hasattr(v, "__qualname__"):
        return f"{getattr(v, '__module__', '?')}.{v.__qualname__}"
    return repr(v)


def _sharding_key(x: Any) -> str:
    """Multi-device placement suffix for one leaf's signature part.

    Compiled executables are specialized to input shardings, so a mesh
    placement must select a different program than the identical aval on
    one device (the tensor-parallel serve step vs its unsharded twin).
    Single-device and abstract leaves return "" — every pre-mesh key is
    unchanged.  Fail-open: an exotic sharding that won't describe itself
    just contributes nothing (worst case a fallback, never a wrong
    program — the executable itself rejects mismatched placements)."""
    try:
        sh = getattr(x, "sharding", None)
        mesh = getattr(sh, "mesh", None)
        if mesh is None or getattr(mesh, "size", 1) <= 1:
            return ""
        # Canonicalize: trailing Nones are placement-irrelevant, but GSPMD
        # outputs elide them while hand-built specs often spell them out —
        # the same placement must produce the same key.
        spec = tuple(sh.spec)
        while spec and spec[-1] is None:
            spec = spec[:-1]
        return f"@{spec}|{tuple(dict(mesh.shape).items())}"
    except Exception:  # noqa: BLE001 — keying must not poison dispatch
        return ""


class AotEntry:
    """One jit entry point's compiled-program registry."""

    def __init__(self, name: str, jit_fn: Callable) -> None:
        self.name = name
        self.jit_fn = jit_fn
        self.programs: Dict[str, Any] = {}        # key -> Compiled
        self._building: Dict[str, threading.Event] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.fallbacks = 0

    # -- keying ------------------------------------------------------------

    def signature(self, dynamic: Dict[str, Any], static: Dict[str, Any]) -> str:
        import jax
        from jax.core import get_aval

        leaves, treedef = jax.tree_util.tree_flatten(dynamic)
        parts = [self.name, str(treedef)]
        parts += [str(get_aval(x)) + _sharding_key(x) for x in leaves]
        parts += [f"{k}={_static_repr(v)}" for k, v in sorted(static.items())]
        return hashlib.sha256("|".join(parts).encode()).hexdigest()[:24]

    # -- dispatch ----------------------------------------------------------

    def call(self, dynamic: Dict[str, Any], static: Dict[str, Any]) -> Any:
        try:
            key = self.signature(dynamic, static)
        except Exception:  # noqa: BLE001 — unkeyable args: plain jit path
            self.fallbacks += 1
            return self.jit_fn(**dynamic, **static)
        ev = self._building.get(key)
        if ev is not None:
            # Joining the in-flight build beats tracing the same program in
            # parallel on another thread (GIL contention, duplicate work).
            ev.wait(timeout=_BUILD_WAIT_S)
        prog = self.programs.get(key)
        if prog is not None:
            try:
                out = prog(**dynamic)
            except Exception as e:  # noqa: BLE001 — never poison the run
                # E.g. an input landed on an unexpected device: drop the
                # program and take the always-correct jit path.
                self.programs.pop(key, None)
                self.fallbacks += 1
                _obs_event("aot.fallback", entry=self.name, key=key,
                           error=f"{type(e).__name__}: {e}"[:300])
                return self.jit_fn(**dynamic, **static)
            self.hits += 1
            return out
        self.misses += 1
        return self.jit_fn(**dynamic, **static)

    # -- warm start --------------------------------------------------------

    def build(self, dynamic: Dict[str, Any], static: Dict[str, Any], *,
              store: Optional[Any] = None,
              execute: bool = True) -> Dict[str, Any]:
        """Trace+compile (or load from ``store``) one program and install it.

        Returns a timing record — the cold-start profile the bench publishes:
        ``trace_seconds`` (Python tracing; skipped on a disk hit),
        ``compile_seconds`` (XLA compile or persistent-cache lookup),
        ``load_seconds`` (AOT-store deserialize), ``execute_seconds`` (first
        dispatch with the provided concrete inputs), and ``source`` in
        {"memory", "disk", "compiled", "error"}.
        """
        import jax

        rec: Dict[str, Any] = {"entry": self.name}
        try:
            key = self.signature(dynamic, static)
        except Exception as e:  # noqa: BLE001
            rec.update(source="error", error=f"{type(e).__name__}: {e}")
            return rec
        rec["key"] = key
        with self._lock:
            if key in self.programs:
                rec["source"] = "memory"
                return rec
            ev = self._building.get(key)
            if ev is None:
                ev = self._building[key] = threading.Event()
                owner = True
            else:
                owner = False
        if not owner:                       # someone else is building it
            ev.wait(timeout=_BUILD_WAIT_S)
            rec["source"] = "memory" if key in self.programs else "error"
            return rec
        try:
            compiled = None
            if store is not None:
                t0 = time.perf_counter()
                compiled = store.load(self.name, key)
                if compiled is not None:
                    rec["load_seconds"] = round(time.perf_counter() - t0, 3)
                    rec["source"] = "disk"
            if compiled is None:
                t0 = time.perf_counter()
                lowered = self.jit_fn.lower(**dynamic, **static)
                t1 = time.perf_counter()
                compiled = lowered.compile()
                t2 = time.perf_counter()
                rec["trace_seconds"] = round(t1 - t0, 3)
                rec["compile_seconds"] = round(t2 - t1, 3)
                rec["source"] = "compiled"
                if store is not None and store.save(self.name, key, compiled):
                    rec["stored"] = True
            if execute and _all_concrete(dynamic):
                from taboo_brittleness_tpu.obs import profile as obs_profile

                t0 = time.perf_counter()
                # Device-profiler annotation: warm-start executions run the
                # SAME HLO modules as the pipeline's launches, so without
                # their own marker the trace parser would attribute their
                # device slices to a word's program span (obs/profile.py).
                with obs_profile.annotate(
                        "aot.build",
                        fn=getattr(self.jit_fn, "__name__", self.name)):
                    jax.block_until_ready(compiled(**dynamic))
                rec["execute_seconds"] = round(time.perf_counter() - t0, 3)
            self.programs[key] = compiled
        except Exception as e:  # noqa: BLE001 — a failed build = plain jit path
            rec.update(source="error", error=f"{type(e).__name__}: {e}")
        finally:
            with self._lock:
                ev.set()
                self._building.pop(key, None)
        # Telemetry: the cold-start profile, one event per built program
        # (trace/compile/load/execute split — runs on the warm-start thread,
        # so the span stream shows the build overlapping word 0's IO).
        _obs_event("aot.build", **rec)
        return rec


def _all_concrete(dynamic: Dict[str, Any]) -> bool:
    import jax

    return not any(isinstance(x, jax.ShapeDtypeStruct)
                   for x in jax.tree_util.tree_leaves(dynamic))


# ---------------------------------------------------------------------------
# Registry.
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, AotEntry] = {}
_REGISTRY_LOCK = threading.Lock()


def entry(name: str, jit_fn: Callable) -> AotEntry:
    with _REGISTRY_LOCK:
        e = _REGISTRY.get(name)
        if e is None or e.jit_fn is not jit_fn:
            # First sight, or the jit object was rebuilt (test monkeypatching,
            # module reload): a fresh entry — stale programs must not serve a
            # replaced function.
            e = _REGISTRY[name] = AotEntry(name, jit_fn)
        return e


def dispatch(name: str, jit_fn: Callable, *,
             dynamic: Dict[str, Any], static: Dict[str, Any],
             route: bool = True) -> Any:
    """Call ``jit_fn(**dynamic, **static)`` through the AOT registry.

    ``route=False`` (mesh-sharded launches, or any caller that wants the
    plain path) skips the registry without touching counters."""
    if not route or not enabled():
        return jit_fn(**dynamic, **static)
    return entry(name, jit_fn).call(dynamic, static)


def stats() -> Dict[str, Dict[str, int]]:
    """Per-entry hit/miss/fallback counters (tests assert a warmed study
    records zero misses — the guard that keeps the warm-start spec mirror
    honest)."""
    return {name: {"hits": e.hits, "misses": e.misses,
                   "fallbacks": e.fallbacks, "programs": len(e.programs)}
            for name, e in _REGISTRY.items()}


def reset() -> None:
    """Drop every entry (tests)."""
    with _REGISTRY_LOCK:
        _REGISTRY.clear()
