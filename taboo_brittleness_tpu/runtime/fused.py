"""Fused decode→readout→NLL: the study's inner loop as ONE resident program.

Why (ROADMAP top item; Kernel Looping, arXiv:2410.23668): the legacy study
step is three XLA dispatches per arm chunk — ``greedy_decode`` (prefill +
K-token ``lax.while_loop``), the 256k-vocab tap-layer readout
(``interventions._residual_measure``), and the cached-NLL continuation
(``interventions._nll_cached_jit``) — with host-side glue between each
launch.  PR 7's device-timeline profiler measures exactly that glue as
device-idle dispatch-gap share; this module removes the synchronization
boundaries by compiling all three phases (plus the baseline pass's spike
finding) into ONE launched XLA program.  The KV cache, the readout
accumulation slabs, and the per-step P(secret)/NLL taps are all values
*inside* the one program — nothing round-trips to the host until the block's
outputs are pulled (M2R2's keep-the-taps-in-the-loop stance,
arXiv:2502.02040).

The fused body deliberately CALLS the same jitted building blocks the legacy
path dispatches (``decode.greedy_decode``, ``_residual_measure``,
``_nll_cached_jit``): under an enclosing trace they inline, so the fused
program computes bit-identical tokens, lens probabilities, and NLLs (gated
by tests/test_fused.py) while XLA sees one module with no launch boundaries.

Phase markers are IN-GRAPH, not host timestamps — host clocks are
meaningless inside one launch:

- each phase's ops trace under a ``jax.named_scope("tbx_fused_<phase>")``,
  so the compiled HLO's op metadata carries the phase structure;
- the launch's annotation (obs/profile.py) carries a *phase table* —
  ordered phases with analytic device-cost weights computed from
  ``perf.roofline`` at the exact launch shapes — which the trace parser
  uses to split the single launch's MEASURED device seconds per phase
  (``_device_profile.json:fused_phase_split``);
- :class:`FusedResult` returns ``decode_steps``, the in-graph count of
  executed decode steps (the step-index boundary between the decode phase
  and the readout/NLL tail of the program).

Rollout contract (the ``readout_ab`` playbook): **legacy stays the default**
until a TPU round confirms the win — ``TBX_FUSED=1`` opts in, and
``bench.py``'s ``fused_ab`` stage commits the fused-vs-legacy throughput,
measured device-idle share, and ceiling ratios side by side every round.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from taboo_brittleness_tpu.models.gemma2 import Gemma2Config, Params
from taboo_brittleness_tpu.runtime import chat

#: Sub-phase order inside one fused launch — the phase table's key order and
#: the named_scope suffixes in the compiled HLO.
FUSED_PHASES: Tuple[str, ...] = ("decode", "readout", "nll")


def enabled() -> bool:
    """Opt-in gate: ``TBX_FUSED=1`` routes the study's per-chunk trio through
    the fused program.  Default OFF — legacy per-launch dispatch stays the
    production path until a TPU round lands the ``fused_ab`` table."""
    return os.environ.get("TBX_FUSED", "0") == "1"


class FusedResult(NamedTuple):
    """Everything the study consumes from one fused launch.

    Decode block (``decode.DecodeResult`` fields the collects read):
    ``tokens``/``lengths``/``sequences``/``sequence_valid``.  Layout block
    (``decode.ResponseLayout`` computed in-graph): ``positions`` and
    ``response_mask``.  Readout block (``_residual_measure``'s dict, split
    into fields): ``tap_prob``/``row_prob_sum``/``row_resp``/``agg_ids``/
    ``agg_probs``.  ``nll`` is the cached-NLL continuation's [B, T] output.

    ``residual`` and the ``prefill_*`` KV slices are ALWAYS program
    outputs, deliberately: the legacy decode launch materializes exactly
    these buffers, and XLA's codegen for the decode while-loop is sensitive
    to which loop-derived values stay live (dead outputs change fusion and
    with it last-bit rounding).  Keeping the fused program's decode output
    surface identical to the legacy launch is what makes the bit-exactness
    gate hold; the fusion win is the REMOVED LAUNCH BOUNDARIES (no host
    glue, no dispatch gap), not removed buffers — callers drop the
    residual/prefill references right after dispatch, exactly like the
    legacy pipeline does.  ``spike_pos``/``spike_probs`` ride only in
    baseline mode (``spike_top_k``).

    ``decode_steps`` is the in-graph phase marker: the number of decode
    steps that emitted at least one token (the while-loop's early exit
    index, up to the fixed +1 step that latches the last stop row) — the
    step-index boundary between the fused program's decode phase and its
    readout/NLL tail, emitted with the launch record instead of any host
    timestamp.
    """

    tokens: jax.Array            # [B, N]
    lengths: jax.Array           # [B]
    sequences: jax.Array         # [B, T]
    sequence_valid: jax.Array    # [B, T] bool
    positions: jax.Array         # [B, T]
    response_mask: jax.Array     # [B, T] bool
    tap_prob: jax.Array          # [B, T]
    row_prob_sum: jax.Array      # [B]
    row_resp: jax.Array          # [B]
    agg_ids: jax.Array           # [B, K]
    agg_probs: jax.Array         # [B, K]
    nll: jax.Array               # [B, T]
    decode_steps: jax.Array      # [] int32 — in-graph phase marker
    residual: jax.Array = None       # [B, T, D] f32 at the tap layer
    prefill_k: jax.Array = None      # [L, B, s, Kh, Dh] (bit-parity anchor)
    prefill_v: jax.Array = None
    prefill_valid: jax.Array = None  # [B, s]
    spike_pos: Optional[jax.Array] = None    # [B, K_spike] (baseline only)
    spike_probs: Optional[jax.Array] = None  # [B, K_spike] (baseline only)


@partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "edit_fn", "decode_edit",
                     "stop_ids", "tap_layer", "top_k", "chunk", "variant",
                     "spike_top_k", "nll_edit"),
)
def fused_study(
    params: Params,
    cfg: Gemma2Config,
    prompt_ids: jax.Array,        # [B, Tp] left-padded
    prompt_valid: jax.Array,      # [B, Tp] bool
    prompt_positions: jax.Array,  # [B, Tp]
    edit_params: Any = None,
    target_ids: jax.Array = None,  # [B]
    # Arms mode: the ΔNLL re-scores the BASELINE continuation (host-tiled
    # layout arrays) under this launch's edited model.  All None = baseline
    # mode, where the NLL layout derives in-graph from the decode's own
    # output (the study's unedited first pass).
    nll_seqs: Optional[jax.Array] = None,       # [B, T]
    nll_valid: Optional[jax.Array] = None,      # [B, T] bool
    nll_positions: Optional[jax.Array] = None,  # [B, T]
    nll_next_mask: Optional[jax.Array] = None,  # [B, T] bool
    *,
    max_new_tokens: int,
    edit_fn: Any = None,
    decode_edit: bool = True,
    stop_ids: Tuple[int, ...] = (chat.EOS_ID, chat.END_OF_TURN_ID),
    tap_layer: int,
    top_k: int,
    chunk: Optional[int] = None,
    variant: str = "foldexp",
    spike_top_k: Optional[int] = None,
    nll_edit: bool = False,
) -> FusedResult:
    """ONE launched program: decode (prefill + K-token while_loop with the
    in-graph intervention edit), tap-layer lens readout, cached-NLL
    continuation, and (baseline mode) spike finding.

    The body inlines the SAME jitted callables the legacy path launches one
    by one — and keeps the decode's legacy output surface live (see
    :class:`FusedResult`) — so per-value results are bit-identical to the
    three-dispatch path; the fusion win is the removed launch boundaries
    (no host sync, no dispatch gap between the three phases).

    ``nll_edit=True`` applies ``edit_fn``/``edit_params`` to the NLL
    continuation too (the arm path; ``chunk_positions`` for the continuation
    columns is derived in-graph).  Baseline mode scores un-edited.
    """
    from taboo_brittleness_tpu.pipelines import interventions as iv
    from taboo_brittleness_tpu.runtime import decode as decode_mod

    with jax.named_scope("tbx_fused_decode"):
        dec = decode_mod.greedy_decode(
            params, cfg, prompt_ids, prompt_valid, prompt_positions,
            max_new_tokens=max_new_tokens,
            edit_fn=edit_fn, edit_params=edit_params,
            decode_edit=decode_edit, stop_ids=stop_ids,
            capture_residual_layer=tap_layer,
            return_prefill_cache=True)
    layout = decode_mod.response_layout_device(dec, stop_ids=stop_ids)
    s = max(layout.prompt_len - 1, 0)

    with jax.named_scope("tbx_fused_readout"):
        out = iv._residual_measure(
            params, cfg, dec.residual, layout.sequences,
            layout.response_mask, target_ids,
            top_k=top_k, resp_start=s, chunk=chunk, variant=variant)

    if nll_seqs is None:
        seqs, valid, positions = (layout.sequences, layout.valid,
                                  layout.positions)
        resp = layout.response_mask
        next_mask = jnp.zeros_like(resp).at[:, :-1].set(resp[:, 1:])
    else:
        seqs, valid = nll_seqs, nll_valid
        positions, next_mask = nll_positions, nll_next_mask
    if nll_edit and edit_fn is not None:
        ep_nll = iv._with_chunk_positions(edit_params, positions[:, s:])
        nll_edit_fn = edit_fn
    else:
        ep_nll, nll_edit_fn = None, None
    with jax.named_scope("tbx_fused_nll"):
        nll = iv._nll_cached_jit(
            params, cfg, *dec.prefill_cache,
            seqs, valid, positions, next_mask,
            edit_fn=nll_edit_fn, edit_params=ep_nll, resp_start=s)

    spike_pos = spike_probs = None
    if spike_top_k is not None:
        from taboo_brittleness_tpu.ops import lens

        with jax.named_scope("tbx_fused_spikes"):
            spike_pos, spike_probs = lens.spike_positions_batch(
                out["tap_prob"], layout.response_mask, top_k=spike_top_k)

    return FusedResult(
        tokens=dec.tokens, lengths=dec.lengths,
        sequences=layout.sequences, sequence_valid=layout.valid,
        positions=layout.positions, response_mask=layout.response_mask,
        tap_prob=out["tap_prob"], row_prob_sum=out["row_prob_sum"],
        row_resp=out["row_resp"], agg_ids=out["agg_ids"],
        agg_probs=out["agg_probs"], nll=nll,
        decode_steps=jnp.max(dec.lengths).astype(jnp.int32),
        residual=dec.residual,
        prefill_k=dec.prefill_cache[0], prefill_v=dec.prefill_cache[1],
        prefill_valid=dec.prefill_cache[2],
        spike_pos=spike_pos, spike_probs=spike_probs,
    )


def phase_table(cfg: Gemma2Config, rows: int, prompt_len: int,
                new_tokens: int, sae_width: int) -> Dict[str, float]:
    """The launch record's step-index → phase table: ordered fused phases
    with analytic device-cost WEIGHTS (normalized shares) at the exact
    launch shapes, from ``perf.roofline``.

    On a device with a known roofline spec the weight is each phase's
    ceiling time (max of compute/memory bound — the best predictor of its
    share of the fused launch); otherwise the analytic FLOPs share.  The
    table rides in the profiler annotation so the trace parser can split
    the fused launch's MEASURED device seconds per phase without any host
    timestamp — fail-open to equal weights (attribution degrades, capture
    never breaks)."""
    try:
        from taboo_brittleness_tpu.perf import roofline

        flops = roofline.phase_flops(cfg, rows, prompt_len, new_tokens,
                                     sae_width)
        spec = None
        try:
            kind = jax.devices()[0].device_kind
            spec = roofline.device_spec(kind)
        except Exception:  # noqa: BLE001 — backend probing is best-effort
            spec = None
        if spec is not None:
            bytes_ = roofline.sweep_phase_bytes(
                cfg, rows, prompt_len, new_tokens, sae_width)
            pred = {p: max(flops[p] / spec.peak_flops,
                           bytes_[p] / spec.hbm_bytes_per_s)
                    for p in FUSED_PHASES}
        else:
            pred = {p: flops[p] for p in FUSED_PHASES}
        total = sum(pred.values()) or 1.0
        return {p: round(pred[p] / total, 4) for p in FUSED_PHASES}
    except Exception:  # noqa: BLE001 — a table failure must not block dispatch
        w = round(1.0 / len(FUSED_PHASES), 4)
        return {p: w for p in FUSED_PHASES}


def dispatch_fused(
    params: Params,
    cfg: Gemma2Config,
    *,
    prompt_ids,
    prompt_valid,
    prompt_positions,
    edit_params: Any = None,
    target_ids,
    nll_inputs: Optional[Dict[str, Any]] = None,
    max_new_tokens: int,
    edit_fn: Any = None,
    stop_ids: Tuple[int, ...] = (chat.EOS_ID, chat.END_OF_TURN_ID),
    tap_layer: int,
    top_k: int,
    spike_top_k: Optional[int] = None,
    sae_width: int = 0,
    route: bool = True,
) -> FusedResult:
    """One fused launch through the AOT program registry, under a ``fused``
    program span and a phase-table profiler annotation.

    ``nll_inputs`` (dict with ``seqs``/``valid``/``positions``/``next_mask``)
    selects arms mode (NLL over the baseline layout, edited); None selects
    baseline mode (NLL from the decode's own layout, un-edited).  The span /
    annotation contract matches the legacy per-program call sites
    (obs/profile.py TBX010), except the single annotation carries ALL THREE
    phase markers — ``tools/trace_report.py --check --device`` accepts one
    launch with a multi-phase table.
    """
    from taboo_brittleness_tpu import obs
    from taboo_brittleness_tpu.obs import metrics as obs_metrics
    from taboo_brittleness_tpu.runtime import aot

    rows, cols = prompt_ids.shape
    dynamic = dict(
        params=params,
        prompt_ids=jnp.asarray(prompt_ids),
        prompt_valid=jnp.asarray(prompt_valid),
        prompt_positions=jnp.asarray(prompt_positions),
        edit_params=edit_params,
        target_ids=jnp.asarray(target_ids),
        nll_seqs=None, nll_valid=None, nll_positions=None,
        nll_next_mask=None,
    )
    if nll_inputs is not None:
        dynamic.update(
            nll_seqs=jnp.asarray(nll_inputs["seqs"]),
            nll_valid=jnp.asarray(nll_inputs["valid"]).astype(bool),
            nll_positions=jnp.asarray(nll_inputs["positions"]),
            nll_next_mask=jnp.asarray(nll_inputs["next_mask"]).astype(bool))
    static = dict(
        cfg=cfg, max_new_tokens=max_new_tokens, edit_fn=edit_fn,
        decode_edit=True, stop_ids=stop_ids, tap_layer=tap_layer,
        top_k=top_k, chunk=_readout_chunk_override(),
        variant=_readout_variant(), spike_top_k=spike_top_k,
        nll_edit=nll_inputs is not None and edit_fn is not None)

    obs_metrics.counter("fused.launches").inc()
    obs_metrics.counter("fused.rows").inc(rows)
    # The phase table costs a little host arithmetic; compute it only when a
    # device capture is live (it exists for the trace parser's split).
    table = None
    if obs.profile.capturing():
        table = phase_table(cfg, rows, cols, max_new_tokens, sae_width)
    with obs.span("fused", kind="program", rows=rows, cols=int(cols),
                  new_tokens=max_new_tokens, fn="fused_study",
                  phases=",".join(FUSED_PHASES)) as sp:
        with obs.profile.annotate("fused", fn=fused_study,
                                  span_id=getattr(sp, "span_id", None),
                                  phases=table):
            return aot.dispatch("fused", fused_study,
                                dynamic=dynamic, static=static, route=route)


def _readout_variant() -> str:
    from taboo_brittleness_tpu.pipelines import interventions as iv

    return iv._readout_variant()


def _readout_chunk_override() -> Optional[int]:
    from taboo_brittleness_tpu.pipelines import interventions as iv

    return iv._readout_chunk_override()
