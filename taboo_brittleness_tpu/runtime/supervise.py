"""Preemption-safe supervised execution: drain, restart, incarnation resume.

The sweep's in-process failure story (``runtime.resilience``: retry →
quarantine → continue) and its liveness story (``obs.progress``: two-signal
staleness) both stop at the process boundary — a TPU preemption notice, an
OOM kill, or a wedged compile still ends the run with a human rerunning it
by hand.  This module extends the Sequoia stance (partial failure is the
steady state, arXiv:2402.12374) across process death, in two halves:

**In-child: graceful drain.**  :func:`install_drain_handlers` latches
SIGTERM/SIGINT into a process-wide drain flag that the sweep drivers
(``pipelines.word_sweep``, the interventions study loop, generation) poll
BETWEEN words: the current word's atomic writes and obs flush complete, the
progress file is stamped ``status="preempted"``, the run manifest gains an
incarnation block, and the process exits :data:`EXIT_DRAINED` (75,
``EX_TEMPFAIL``) — a TPU preemption notice becomes a clean checkpoint
boundary instead of a torn run.  A second signal abandons the drain and
dies immediately (the operator asked twice).

**Host-side: the supervisor.**  :func:`supervise` launches any pipeline as
a child process (each launch is one *incarnation*, numbered in the child's
``TBX_INCARNATION`` env), watches its ``_progress.json`` via
``read_progress(missing_ok=True)``, and closes the loop on every way a
child can stop:

- exit 0 → the run is done (supervisor exits 0);
- exit 75 (drained) → a preemption hit the child; relaunch immediately —
  the per-word resume artifacts make the next incarnation continue where
  the drain stopped;
- exit 1 (quarantined words) → the sweep COMPLETED; the in-process
  retry/quarantine subsystem already exhausted its budget, so the
  supervisor passes 1 through instead of burning incarnations replaying a
  permanent failure.  This pass-through is conditional on the child's
  declared workload (its progress file's ``workload`` field): for a SERVING
  child (``tbx serve``) exit 1 is a crash loop, not completion, and burns
  an incarnation like any other crash;
- any other death (crash, OOM/SIGKILL, ``die`` fault) → relaunch after a
  seeded-jitter backoff (``RetryPolicy``), within a bounded incarnation
  budget;
- a *wedged* child (heartbeat stale, or pipeline event-quiet past the wedge
  threshold while the heartbeat stays fresh) → SIGTERM (drain chance),
  SIGKILL after the grace window, relaunch.

A SIGTERM delivered to the SUPERVISOR is forwarded to the child, which
drains; the supervisor then exits 75 itself, so outer orchestration sees
one consistent "safe to resume" signal however deep the notice landed.

Artifacts merge across incarnations so the final directory reads as one
run: the child-side ``FailureLedger`` already folds prior incarnations'
entries (stamped per incarnation), the event sink resumes its ``seq`` from
the file tail (``obs.trace``), and the supervisor writes
``_supervise.json`` (incarnation history) plus an ``incarnations`` block
into the child's ``run_manifest.json``.

Env knobs (all overridable per-call):

- ``TBX_SUPERVISE_MAX_INCARNATIONS`` — launch budget (default 5).
- ``TBX_SUPERVISE_POLL_S`` — progress poll interval (default 1.0).
- ``TBX_SUPERVISE_GRACE_S`` — SIGTERM→SIGKILL grace window (default 15).
- ``TBX_SUPERVISE_WEDGE_S`` — kill a child whose pipeline has emitted no
  telemetry event for this long while its heartbeat stays fresh
  (default 300; the heartbeat-stale signal needs no threshold).
- ``TBX_SUPERVISE_BACKOFF_S`` — crash-restart base backoff (default 2.0;
  seeded jitter via ``RetryPolicy``).

Everything here is stdlib host-side control flow — no jax, importable on a
login node watching an rsync'd results directory.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from taboo_brittleness_tpu.runtime.resilience import (
    INCARNATION_ENV, WORKER_ENV, RetryPolicy, atomic_json_dump,
    current_incarnation)

__all__ = [
    "EXIT_DRAINED", "EXIT_QUARANTINED", "SUPERVISE_FILENAME",
    "DrainController", "SuperviseResult", "current_incarnation",
    "drain_requested", "install_drain_handlers", "request_drain",
    "reset_drain", "supervise",
]

#: ``EX_TEMPFAIL``: the run drained cleanly on a preemption notice — partial
#: results on disk are valid and a relaunch resumes them.  Distinct from 1
#: (sweep completed with quarantined words: rerunning won't help) so the
#: supervisor and outer orchestration key restart-vs-fail off the code alone.
EXIT_DRAINED = 75
EXIT_QUARANTINED = 1

SUPERVISE_FILENAME = "_supervise.json"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# In-child graceful drain.
# ---------------------------------------------------------------------------


class DrainController:
    """Process-wide drain latch: signal handlers set it, sweep drivers poll
    it between words via :func:`drain_requested`.

    The handler does the minimum a signal context allows — set a
    ``threading.Event`` and mirror one line to stderr.  It must NOT emit
    telemetry: the signal can land while the main thread holds the tracer's
    (non-reentrant) sink lock, and an event emit from the handler would
    self-deadlock.  The drain event is emitted later, from the sweep loop,
    on the normal path.
    """

    def __init__(self) -> None:
        self._event = threading.Event()
        self._prev: Dict[int, Any] = {}
        self._installed = False

    def install(self, signums: Sequence[int] = (signal.SIGTERM,
                                                signal.SIGINT)) -> bool:
        """Idempotent; returns False (and stays polling-only) off the main
        thread, where CPython forbids ``signal.signal``."""
        if self._installed:
            return True
        try:
            for s in signums:
                self._prev[s] = signal.signal(s, self._handle)
        except ValueError:
            self._prev.clear()
            return False
        self._installed = True
        return True

    def uninstall(self) -> None:
        """Restore the previous dispositions (test hygiene)."""
        for s, h in self._prev.items():
            try:
                signal.signal(s, h)
            except (ValueError, OSError, TypeError):
                pass
        self._prev.clear()
        self._installed = False

    def _handle(self, signum: int, frame: Any) -> None:
        if self._event.is_set():
            # Second notice: the operator (or the platform) asked twice —
            # stop draining, restore the original disposition, die now.
            try:
                signal.signal(signum, self._prev.get(signum, signal.SIG_DFL))
            except (ValueError, OSError, TypeError):
                pass
            signal.raise_signal(signum)
            return
        self._event.set()
        try:
            # tbx: TBX202-ok — single write(2) to an unbuffered-enough fd;
            # no locks taken, and a torn notice line is harmless
            sys.stderr.write(
                f"[supervise] caught signal {signum}: draining at the next "
                "word boundary (send again to abort immediately)\n")
        except Exception:  # noqa: BLE001 — a closed stderr must not matter
            pass
        # Flight-recorder dump (obs.flightrec): unlike the tracer, the ring
        # is LOCK-FREE (deque appends are GIL-atomic) and the dump writes a
        # fresh tmp file, so this is safe from signal context.  A wedge-kill
        # (the supervisor's SIGTERM before SIGKILL) therefore leaves the last
        # N records on disk even when the drain never completes.
        try:
            from taboo_brittleness_tpu.obs import flightrec

            # tbx: TBX202-ok — the ring is lock-free (GIL-atomic deque) and
            # dump() writes a fresh tmp file: no lock a signal can land inside
            flightrec.dump(f"signal:{signum}")
        except Exception:  # noqa: BLE001 — fail-open, always
            pass

    def request(self) -> None:
        self._event.set()

    def requested(self) -> bool:
        return self._event.is_set()

    def reset(self) -> None:
        self._event.clear()


_CONTROLLER = DrainController()


def install_drain_handlers() -> bool:
    """Latch SIGTERM/SIGINT into the drain flag (CLI entry points call this
    before dispatching a pipeline).  Idempotent; False off the main thread."""
    return _CONTROLLER.install()


def drain_requested() -> bool:
    """Has a preemption/drain notice landed?  Sweep drivers poll this
    between words; the CLI maps True to :data:`EXIT_DRAINED`."""
    return _CONTROLLER.requested()


def request_drain() -> None:
    """Programmatic drain trigger (tests; in-process embedders)."""
    _CONTROLLER.request()


def reset_drain() -> None:
    """Clear the drain latch (test hook — a real process drains once)."""
    _CONTROLLER.reset()


# ---------------------------------------------------------------------------
# Host-side supervisor.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SuperviseResult:
    """Outcome of one :func:`supervise` call: the exit code to propagate,
    a status label, and the per-incarnation history (also persisted to
    ``<output_dir>/_supervise.json``)."""

    exit_code: int
    status: str            # done | drained | quarantined | budget-exhausted
    incarnations: List[Dict[str, Any]]

    @property
    def ok(self) -> bool:
        return self.exit_code == 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": 1,
            "status": self.status,
            "exit_code": self.exit_code,
            "incarnations": self.incarnations,
        }


def _wedge_reason(progress: Dict[str, Any], pid: int,
                  wedge_after: Optional[float]) -> Optional[str]:
    """The two-signal wedge classification over a ``read_progress`` result.

    Only THIS incarnation's heartbeat counts (pid match): right after a
    relaunch the file still holds the dead predecessor's state, which must
    read as "child starting up", never as "child wedged".

    Serving children (``workload == "serve"``; ``obs.progress.serving_update``)
    get their own pipeline-quiet signal: a healthy server that is IDLE emits
    no telemetry events, so the event-age rule would kill it — instead the
    classifier reads the serving heartbeat's in-flight count and last-step
    age, and only wedges a server that HAS sessions but stopped stepping."""
    if progress.get("status") != "running" or progress.get("pid") != pid:
        return None
    if progress.get("stale"):
        # updated_at is old: the heartbeat thread itself stopped while the
        # process is still alive (we checked poll() first) — hard wedge.
        return "heartbeat-stale"
    if progress.get("workload") == "serve":
        serving = progress.get("serving") or {}
        step_age = serving.get("last_step_age_seconds")
        if (wedge_after and int(serving.get("in_flight", 0) or 0) > 0
                and step_age is not None
                and (float(step_age)
                     + float(progress.get("age_seconds", 0.0)) > wedge_after)):
            return "pipeline-wedged"
        return None         # idle-but-alive: healthy by heartbeat alone
    age = progress.get("last_event_age_seconds")
    if wedge_after and age is not None:
        # The event age was measured when the heartbeat wrote the file; the
        # file's own age has accrued since.
        if float(age) + float(progress.get("age_seconds", 0.0)) > wedge_after:
            return "pipeline-wedged"
    return None


def _emit_events(output_dir: str,
                 events: Sequence[Tuple[str, Dict[str, Any]]],
                 filename: Optional[str] = None) -> None:
    """Append supervisor point events to the sweep's ``_events.jsonl`` (or a
    fleet worker's ``_events.<wid>.jsonl``).

    Called only while no child is running, so the tracer's tail-resumed
    ``seq`` keeps the merged stream monotone (``obs.trace``).  Fail-open:
    supervision never depends on telemetry."""
    try:
        from taboo_brittleness_tpu.obs import trace

        t = trace.Tracer(os.path.join(output_dir,
                                      filename or trace.EVENTS_FILENAME))
        try:
            for name, attrs in events:
                t.event(name, **attrs)
        finally:
            t.close()
    except Exception:  # noqa: BLE001 — telemetry must never block supervision
        pass


def _merge_run_artifacts(output_dir: str, result: SuperviseResult,
                         *, filename: str = SUPERVISE_FILENAME,
                         fold_manifest: bool = True) -> None:
    """Make the directory read as ONE run: persist the incarnation history
    to ``_supervise.json`` and fold it into the child's ``run_manifest.json``
    (which lives either in ``output_dir`` or one level up — the pipelines
    write per-word artifacts into a ``words/`` subdirectory).  Fleet workers
    (``fold_manifest=False``) skip the manifest fold: N workers share one
    directory, and the fleet merge owns the combined view."""
    try:
        atomic_json_dump(result.to_dict(),
                         os.path.join(output_dir, filename))
    except OSError:
        pass
    if not fold_manifest:
        return
    for cand in (output_dir, os.path.dirname(os.path.abspath(output_dir))):
        path = os.path.join(cand, "run_manifest.json")
        if not os.path.isfile(path):
            continue
        try:
            with open(path) as f:
                manifest = json.load(f)
            manifest["incarnations"] = {
                "count": len(result.incarnations),
                "status": result.status,
                "history": result.incarnations,
            }
            atomic_json_dump(manifest, path)
        except (OSError, ValueError):
            continue


def _hard_kill(proc: "subprocess.Popen") -> None:
    try:
        proc.kill()
    except OSError:
        pass


def supervise(
    child_argv: Sequence[str],
    output_dir: str,
    *,
    max_incarnations: Optional[int] = None,
    poll_interval: Optional[float] = None,
    grace: Optional[float] = None,
    wedge_after: Optional[float] = None,
    policy: Optional[RetryPolicy] = None,
    env: Optional[Dict[str, str]] = None,
    worker_id: Optional[str] = None,
    sleep=time.sleep,
) -> SuperviseResult:
    """Run ``child_argv`` under the supervisor until it finishes, drains,
    quarantines, or exhausts the incarnation budget.  See the module
    docstring for the full state machine; parameters default to the
    ``TBX_SUPERVISE_*`` env knobs.

    ``output_dir`` is the directory the child heartbeats ``_progress.json``
    into (for the packaged pipelines: the per-word results directory).  The
    supervisor only ever READS the child's files, except for the merged
    ``_supervise.json``/manifest block it writes after the run.

    ``worker_id`` puts the supervisor in FLEET-WORKER mode
    (``runtime.fleet``): the child gets ``TBX_WORKER_ID`` in its env, its
    telemetry lands in per-worker files (``_progress.<wid>.json``,
    ``_events.<wid>.jsonl``, ``_supervise.<wid>.json``) so N supervised
    workers can share one output directory without interleaving each
    other's seq counters, and the run-manifest fold is left to the fleet
    merge.  The wedge classifier, restart budget, and drain contract are
    identical — the fleet reuses, not reimplements, this state machine.
    """
    max_incarnations = (max_incarnations if max_incarnations is not None
                        else _env_int("TBX_SUPERVISE_MAX_INCARNATIONS", 5))
    poll_interval = (poll_interval if poll_interval is not None
                     else _env_float("TBX_SUPERVISE_POLL_S", 1.0))
    grace = grace if grace is not None else _env_float("TBX_SUPERVISE_GRACE_S",
                                                       15.0)
    wedge_after = (wedge_after if wedge_after is not None
                   else _env_float("TBX_SUPERVISE_WEDGE_S", 300.0))
    policy = policy or RetryPolicy(
        max_retries=max(max_incarnations - 1, 0),
        base_delay=_env_float("TBX_SUPERVISE_BACKOFF_S", 2.0),
        max_delay=60.0)
    if max_incarnations < 1:
        raise ValueError("max_incarnations must be >= 1")

    from taboo_brittleness_tpu.obs.progress import (
        PROGRESS_FILENAME, read_progress)

    os.makedirs(output_dir, exist_ok=True)
    progress_name = (PROGRESS_FILENAME if worker_id is None
                     else f"_progress.{worker_id}.json")
    events_name = (None if worker_id is None
                   else f"_events.{worker_id}.jsonl")
    supervise_name = (SUPERVISE_FILENAME if worker_id is None
                      else f"_supervise.{worker_id}.json")
    progress_path = os.path.join(output_dir, progress_name)
    backoff = policy.delays(f"supervise:{worker_id or ''}")
    history: List[Dict[str, Any]] = []
    final_rc: Optional[int] = None
    status = "budget-exhausted"

    for incarnation in range(max_incarnations):
        _emit_events(output_dir,
                     [("supervise.launch",
                       {"incarnation": incarnation,
                        **({"worker": worker_id} if worker_id else {})})],
                     events_name)
        child_env = dict(os.environ)
        if env:
            child_env.update(env)
        child_env[INCARNATION_ENV] = str(incarnation)
        if worker_id is not None:
            child_env[WORKER_ENV] = worker_id
        t0 = time.monotonic()
        proc = subprocess.Popen(list(child_argv), env=child_env)
        rec: Dict[str, Any] = {
            "incarnation": incarnation,
            "pid": proc.pid,
            # Epoch timestamp: serialized metadata for humans, not duration
            # math (wall_seconds below uses the monotonic mark).
            # tbx: wallclock-ok — serialized metadata (duration uses t0)
            "started_at": time.time(),
        }

        wedge = None
        forwarded_at: Optional[float] = None
        killed_at: Optional[float] = None
        while proc.poll() is None:
            now = time.monotonic()
            if _CONTROLLER.requested() and forwarded_at is None:
                # The supervisor's own preemption notice: forward it so the
                # child drains, then propagate EXIT_DRAINED below.
                try:
                    proc.terminate()
                except OSError:
                    pass
                forwarded_at = now
            if forwarded_at is not None:
                if now - forwarded_at > grace:
                    _hard_kill(proc)
                sleep(poll_interval)
                continue
            if wedge is None:
                progress = read_progress(progress_path, missing_ok=True)
                wedge = _wedge_reason(progress, proc.pid, wedge_after)
                if wedge is not None:
                    # SIGTERM first (the drain chance), SIGKILL after grace.
                    try:
                        proc.terminate()
                    except OSError:
                        pass
                    killed_at = now
            elif killed_at is not None and now - killed_at > grace:
                _hard_kill(proc)
            sleep(poll_interval)
        rc = proc.wait()
        rec["exit_code"] = rc
        rec["wall_seconds"] = round(time.monotonic() - t0, 3)

        if forwarded_at is not None:
            # Supervisor-initiated drain.  A child that finished anyway
            # still counts as done; anything else propagates "resumable".
            rec["outcome"] = "done" if rc == 0 else "drained"
            history.append(rec)
            final_rc = 0 if rc == 0 else EXIT_DRAINED
            status = "done" if rc == 0 else "drained"
            _emit_events(output_dir, [("supervise.drain",
                                       {"incarnation": incarnation,
                                        "exit_code": rc})], events_name)
            break
        if wedge is not None:
            rec["outcome"] = "wedged"
            rec["reason"] = wedge
            history.append(rec)
            _emit_events(output_dir, [("supervise.wedged",
                                       {"incarnation": incarnation,
                                        "reason": wedge, "exit_code": rc})],
                         events_name)
        elif rc == 0:
            rec["outcome"] = "done"
            history.append(rec)
            final_rc = 0
            status = "done"
            break
        elif rc == EXIT_DRAINED:
            # An externally delivered preemption the child drained on its
            # own: a clean checkpoint boundary — resume without backoff.
            rec["outcome"] = "drained"
            history.append(rec)
            continue
        elif rc == EXIT_QUARANTINED and read_progress(
                progress_path, missing_ok=True).get("workload") == "serve":
            # A SWEEP's exit 1 means "completed, words quarantined" — the
            # in-process retry budget is spent and rerunning replays the
            # failure, so the supervisor passes it through.  A SERVER has no
            # such semantics: its exit 1 is a crash (an exception escaped the
            # serve loop), and passing it through would let a crash loop
            # masquerade as completion — burn an incarnation instead.
            rec["outcome"] = "crashed"
            rec["reason"] = "serve-exit-1"
            history.append(rec)
            _emit_events(output_dir, [("supervise.crash",
                                       {"incarnation": incarnation,
                                        "reason": "serve-exit-1",
                                        "exit_code": rc})], events_name)
        elif rc == EXIT_QUARANTINED:
            rec["outcome"] = "quarantined"
            history.append(rec)
            final_rc = EXIT_QUARANTINED
            status = "quarantined"
            break
        else:
            rec["outcome"] = "crashed"
            history.append(rec)
        # Crash/wedge restart: seeded-jitter backoff, bounded by the budget.
        if incarnation + 1 < max_incarnations:
            delay = next(backoff, None)
            if delay is None:
                delay = policy.max_delay
            if delay > 0:
                sleep(delay)
        else:
            final_rc = rc if rc not in (0, None) else 1

    if final_rc is None:
        final_rc = history[-1]["exit_code"] if history else 1
        if final_rc in (0, None):
            final_rc = 1
        if history and history[-1]["outcome"] == "drained":
            # The budget's last incarnation itself drained: the run is still
            # RESUMABLE (exit 75), not failed — label it so.
            status = "drained"
    result = SuperviseResult(exit_code=int(final_rc), status=status,
                             incarnations=history)
    _emit_events(output_dir,
                 [("supervise.exit",
                   {"status": result.status,
                    "exit_code": result.exit_code,
                    "incarnations": len(history),
                    **({"worker": worker_id} if worker_id else {})})],
                 events_name)
    _merge_run_artifacts(output_dir, result, filename=supervise_name,
                         fold_manifest=worker_id is None)
    return result
